"""Serving replica fleet — health-routed multi-replica dispatch with
zero-loss failover.

The reference's Cluster Serving inherited horizontal scale and task restarts
from Flink's runtime (PAPERS.md "BigDL 2.0"); this module builds the same
supervision loop natively over the queue broker, in the at-least-once
redelivery spirit of PAPERS.md "TensorFlow: A system for large-scale machine
learning":

* :class:`ReplicaRouter` sits at the broker: it consumes the client-facing
  request stream under its own consumer group and forwards each entry onto a
  per-replica dispatch stream (``fleet:req:<rid>``), choosing the replica by a
  pluggable policy — ``round_robin`` or ``least_pending`` (fed by the same
  per-replica queue-depth numbers it publishes as ``zoo_fleet_queue_depth``
  gauges). A per-replica :class:`~..common.resilience.CircuitBreaker` gates
  eligibility: an evicted replica takes no traffic until its half-open probe
  request is observed SERVED.

* :class:`FleetSupervisor` owns the replica lifecycle: it spawns N
  :class:`~.engine.ClusterServing` replicas (``thread`` mode — N engines in
  this process — or ``process`` mode — one subprocess each, see ``main``),
  folds their broker-side heartbeats (``fleet:hb:<rid>``, written by the
  engine's fleet-heartbeat loop) into a
  :class:`~..common.resilience.HealthRegistry`, and reacts to liveness
  TRANSITIONS via the registry's listener hook: a replica that goes silent is
  evicted from routing, its claimed-but-unacked requests are moved back onto
  the dispatch stream in one atomic broker ``XTRANSFER`` (delivery counts
  ride along), and the replica is respawned. Requests are therefore
  at-least-once: a slow-not-dead replica may still answer work that was
  requeued — replica sinks write results with ``HSETNX`` (first-write-wins,
  dedup-on-uri), so the client sees exactly one response per submitted uri.

* Graceful drain (``drain()`` / the ``cli drain`` command) flips a replica to
  stop-accepting via its control hash; it finishes + acks in-flight work,
  reaches state ``drained``, and is deregistered from routing — the
  zero-downtime half of :meth:`FleetSupervisor.rolling_restart`, which drains,
  restarts and readmits replicas one at a time (the model hot-swap
  precondition).

* Cross-host fleets (``fleet_spawn: host`` / ``fleet_hosts > 0``) add a HOST
  failure domain above the replica tier: per-machine :class:`~.hostagent.
  HostAgent` daemons register under ``fleet:host:<hid>``, spawn replicas on
  supervisor command (the declarative ``fleet:hostctl:<hid>`` hash), and
  heartbeat host-level liveness distinct from replica liveness. Placement is
  spread-by-default (the emptiest registered host first — the autoscaler
  "borrows an idle machine" before packing a busy one) under a per-host
  capacity; host-heartbeat expiry triggers WHOLE-HOST failover: every
  replica on the host is evicted, claim-transferred, and respawned on
  surviving hosts in one decision (one ``fleet.host_failed`` event whose
  trace carries spans tagged with both host ids and the measured clock
  offset). A per-host :class:`~..common.resilience.CircuitBreaker` makes
  dials to a dead host fail fast with a computed Retry-After.

Wire layout on the broker::

    serving_stream                   client XADDs (unchanged client API)
    fleet:req:<rid>                  router -> replica dispatch stream
    fleet:hb:<rid>                   replica heartbeat hash {ts, state, served}
    fleet:ctl:<rid>                  supervisor/cli -> replica control hash
    fleet:host:<hid>                 host-agent heartbeat hash (hostagent.py)
    fleet:hostctl:<hid>              supervisor -> host-agent desired state
    fleet:members                    supervisor-published replica roster
    result:<uri>                     replica HSETNX (first answer wins)
"""

from __future__ import annotations

import argparse
import collections
import logging
import os
import signal
import subprocess
import sys
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common import telemetry as _tm
from ..common.chaos import chaos_point
from ..common.locks import traced_lock
from ..common.resilience import (CircuitBreaker, HealthRegistry,
                                 RetryAbortedError, RetryPolicy)
from ..observability import events as _ev
from ..observability import recorder as _flight
from . import qos as _qos
from . import slo_metrics as _slo_metrics
from .client import INPUT_STREAM, RESULT_PREFIX, _Conn
from .config import ServingConfig
from .engine import FLEET_CTL_PREFIX, FLEET_HB_PREFIX, ClusterServing
from .hostagent import HOST_CTL_PREFIX, HOST_HB_PREFIX, HostAgent
from .schema import payload_deadline, payload_priority
from .shm import host_identity

logger = logging.getLogger("analytics_zoo_tpu.serving.fleet")

REPLICA_STREAM_PREFIX = "fleet:req:"
ROUTER_GROUP = "fleet-router"

# the router resolves half-open probes and trips/queries breakers while
# holding its own lock; the breaker lock is a declared leaf (resilience.py),
# so this nesting is the one legal order — the witness + static graph fail
# on any inversion
# zoo-lock: order(ReplicaRouter._lock < CircuitBreaker._lock)
MEMBERS_KEY = "fleet:members"
ROLLING_KEY = "fleet:ctl:__rolling__"

_DISPATCH = _tm.counter("zoo_fleet_dispatch_total",
                        "Requests dispatched to a replica by the router",
                        labels=("replica",))
_REQUEUED = _tm.counter(
    "zoo_fleet_requeued_requests_total",
    "Requests claim-transferred back to the dispatch stream from a dead "
    "replica (XTRANSFER moves; each implies a redelivery)")
_FLEET_RESPAWNS = _tm.counter("zoo_fleet_respawns_total",
                              "Dead replicas respawned by the supervisor")
_FAILOVER = _tm.histogram(
    "zoo_fleet_failover_seconds",
    "Death detection -> claimed work requeued + respawn initiated",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
_NO_REPLICA = _tm.counter(
    "zoo_fleet_route_stalls_total",
    "Router iterations that held traffic because no replica was eligible")
_ROUTER_SHED = _tm.counter(
    "zoo_fleet_shed_total",
    "Requests the router shed (answered + acked, never dispatched) because "
    "their deadline provably cannot be met, by overload class",
    labels=("reason",))
# per-class SLO evidence, registered once in serving/slo_metrics.py
_REQ_OUTCOMES = _slo_metrics.REQUEST_OUTCOMES
_AUTOSCALE = _tm.counter(
    "zoo_autoscale_events_total",
    "Autoscaler scale events, by direction (up = capacity spawned on "
    "sustained queue pressure, down = capacity drained away when idle) and "
    "scope (replica = single-machine fleet, host = cross-host placement — "
    "up borrows an idle host, down retires a whole host to idle)",
    labels=("direction", "scope"))
_HOST_SKEW = _tm.gauge(
    "zoo_fleet_host_clock_skew_seconds",
    "Per-host wall-clock offset vs the supervisor, estimated NTP-style from "
    "heartbeat round trips (positive = host clock ahead); feeds the QoS "
    "deadline skew tolerance", labels=("host",))
_HOST_FAILOVERS = _tm.counter(
    "zoo_fleet_host_failovers_total",
    "Whole-host failovers: a host heartbeat expired and every replica on it "
    "was evicted, requeued, and respawned on surviving hosts in one decision")
_HOSTS = _tm.gauge(
    "zoo_fleet_hosts",
    "Registered fleet hosts, by liveness state", labels=("state",))

# scrape-time gauges walk the live routers (weakset, the resilience.py
# pattern): eligible-replica count + per-replica queue depth — the numbers
# the least_pending policy itself routes on
_LIVE_ROUTERS: "weakref.WeakSet[ReplicaRouter]" = weakref.WeakSet()


def _collect_eligible():
    out = {}
    for r in list(_LIVE_ROUTERS):
        out[(r.name,)] = float(len(r.eligible_ids()))
    return out.items()


def _collect_depths():
    out = {}
    for r in list(_LIVE_ROUTERS):
        for rid, depth in r.depths().items():
            out[(rid,)] = float(depth)
    return out.items()


_tm.collector("zoo_fleet_eligible_replicas",
              "Replicas currently eligible for dispatch (heartbeat fresh, "
              "state up, breaker not open)", _collect_eligible,
              labels=("router",))
_tm.collector("zoo_fleet_queue_depth",
              "Per-replica pending work (dispatch-stream depth + reported "
              "in-flight) — the least_pending routing signal",
              _collect_depths, labels=("replica",))


class _ReplicaSlot:
    """Router-side view of one replica: breaker, liveness fed by the
    supervisor's heartbeat polls, dispatch/depth accounting, and the
    outstanding half-open probe (if any)."""

    def __init__(self, rid: str, config: ServingConfig):
        self.rid = rid
        self.breaker = CircuitBreaker(
            failure_threshold=config.breaker_failure_threshold,
            reset_timeout_s=config.breaker_reset_timeout_s,
            name=f"fleet-replica-{rid}")
        self.alive = True           # hb freshness (supervisor-fed)
        self.state = "up"           # replica lifecycle state from the hb
        self.served = 0             # replica's cumulative served counter
        self.dispatched = 0
        self.depth = 0              # stream LEN + reported in-flight
        self.reported_inflight = 0  # engine-internal queue depth from the hb
        # (served_at_dispatch, t_dispatch) while a half-open probe request
        # is outstanding; progress on `served` closes the breaker
        self.probe: Optional[Tuple[int, float]] = None
        # hot-swap telemetry folded from the heartbeat (serving/hotswap.py):
        # the rollout controller validates the canary on these
        self.last_seen = time.monotonic()   # last alive=True liveness feed
        self.model_version: Optional[str] = None
        self.swap_state: Optional[str] = None
        self.swap_error: Optional[str] = None
        self.swap_nonce: Any = None   # nonce of the replica's LAST swap
                                      # command — scopes swap_error to it
        self.errors = 0             # cumulative error-result counter
        self.lat_ms = 0.0           # receipt->computed latency EMA
        self.svc_ms = 0.0           # per-record COMPUTE time EMA (no queue
                                    # wait) — the deadline-shed evidence
        # canary traffic weight: 1.0 = full member of the rotation; a
        # fraction f < 1 admits this replica on ~every (1/f)th pick only
        self.weight = 1.0
        self.host: Optional[str] = None   # placement (cross-host fleets)


class ReplicaRouter:
    """Broker-level dispatch tier over N engine replicas.

    Consumes ``stream`` under consumer group ``group`` and forwards each
    entry to ``prefix + <chosen replica>``; the origin entry is XACKed only
    after the forward landed, so a router crash redelivers (at-least-once,
    deduped on uri by the replica sinks). Standalone use (e.g. routing the
    generation stream over :class:`~.generation.GenerationEngine` replicas)
    needs only ``replica_ids``; under a :class:`FleetSupervisor` the
    supervisor feeds liveness into :meth:`set_liveness`/:meth:`evict`.
    """

    def __init__(self, config: Optional[ServingConfig] = None,
                 replica_ids: Tuple[str, ...] = (), *,
                 stream: str = INPUT_STREAM,
                 prefix: str = REPLICA_STREAM_PREFIX,
                 group: str = ROUTER_GROUP,
                 policy: Optional[str] = None,
                 registry: Optional[HealthRegistry] = None,
                 name: str = "fleet", group_fmt: str = "fleet-{rid}"):
        self.config = config or ServingConfig()
        self.stream, self.prefix, self.group = stream, prefix, group
        # each replica's consumer-group name (the depth probe counts work
        # OWED to that group: undelivered + claimed-but-unacked)
        self.group_fmt = group_fmt
        self.policy = policy or self.config.fleet_policy
        if self.policy not in ("least_pending", "round_robin"):
            raise ValueError(f"unknown routing policy {self.policy!r}")
        self.registry = registry
        self.name = name
        # zoo-lock: guards(_slots, _rr_next, _pick_seq, _host_breakers)
        self._lock = traced_lock("ReplicaRouter._lock")
        self._slots: "collections.OrderedDict[str, _ReplicaSlot]" = \
            collections.OrderedDict()
        # per-host circuit breakers (supervisor-fed, shared objects): an
        # OPEN host breaker removes every replica placed there from
        # eligibility in one stroke — dials to a dead host fail fast
        self._host_breakers: Dict[str, CircuitBreaker] = {}
        # fleet-wide deadline slack for cross-host clock skew (supervisor-
        # fed: configured floor + worst measured per-host offset). Plain
        # float, single writer — a stale read for one poll interval only
        # shifts the shed boundary by that poll's skew delta
        self.skew_s = 0.0
        for rid in replica_ids:
            self.add_replica(rid)
        self._rr_next = 0
        self._pick_seq = 0          # canary-weight admission counter
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._depths_refreshed = 0.0
        self.routed = 0
        self.shed = 0           # monotonic: deadline sheds at this tier —
                                # with queue depth, the autoscaler's
                                # pressure signal
        _LIVE_ROUTERS.add(self)

    # -- membership / liveness (supervisor-fed) ------------------------------

    def add_replica(self, rid: str) -> None:
        with self._lock:
            if rid not in self._slots:
                self._slots[rid] = _ReplicaSlot(rid, self.config)

    def remove_replica(self, rid: str) -> None:
        with self._lock:
            self._slots.pop(rid, None)

    def set_replica_host(self, rid: str, hid: Optional[str]) -> None:
        """Record a replica's host placement (cross-host fleets): host-spread
        tie-breaking in ``least_pending`` and host-breaker gating key on it."""
        with self._lock:
            slot = self._slots.get(rid)
            if slot is not None:
                slot.host = hid

    def set_host_breaker(self, hid: str,
                         breaker: Optional[CircuitBreaker]) -> None:
        """Share a host's breaker with the router (``None`` removes it). The
        SUPERVISOR owns host liveness and trips it; the router only reads
        state — a dial toward a dead host is refused at pick time instead of
        hanging a dispatch."""
        with self._lock:
            if breaker is None:
                self._host_breakers.pop(hid, None)
            else:
                self._host_breakers[hid] = breaker

    def _host_open_locked(self, slot: _ReplicaSlot) -> bool:
        """Caller holds the router lock. Reading the breaker takes its leaf
        lock — the declared ReplicaRouter._lock < CircuitBreaker._lock
        order."""
        if slot.host is None:
            return False
        b = self._host_breakers.get(slot.host)
        return b is not None and b.state == CircuitBreaker.OPEN

    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._slots)

    def slot(self, rid: str) -> Optional[_ReplicaSlot]:
        """Live slot handle (or None), looked up under the router lock —
        the accessor the rollout controller reads canary/cohort telemetry
        through (reaching into ``_slots`` unlocked would race membership
        churn from add/remove/failover)."""
        with self._lock:
            return self._slots.get(rid)

    def model_versions(self) -> Dict[str, Optional[str]]:
        """Per-replica active model version from the heartbeat-fed slots,
        snapshotted under the router lock."""
        with self._lock:
            return {rid: s.model_version
                    for rid, s in self._slots.items()}

    def evict(self, rid: str) -> None:
        """Force a replica out of the rotation NOW (death, operator action).
        The breaker trips open; readmission follows the normal half-open
        probe path once the replica heartbeats again."""
        with self._lock:
            slot = self._slots.get(rid)
            if slot is None:
                return
            slot.breaker.trip()
            slot.probe = None
        _ev.emit("fleet.evict", severity="warning", replica=rid,
                 router=self.name)
        logger.warning("fleet: evicted replica %s (breaker open)", rid)

    def set_liveness(self, rid: str, alive: bool, state: str = "up",
                     served: Optional[int] = None,
                     inflight: Optional[int] = None,
                     model_version: Optional[str] = None,
                     errors: Optional[int] = None,
                     lat_ms: Optional[float] = None,
                     svc_ms: Optional[float] = None,
                     swap_state: Optional[str] = None,
                     swap_error: Optional[str] = None,
                     swap_nonce: Any = None) -> None:
        """Heartbeat-poll feed from the supervisor. Also resolves half-open
        probes: a probe request counts as SUCCEEDED when the replica's
        cumulative ``served`` advanced past its at-dispatch value, and as
        FAILED when the replica went stale (or the probe aged out) — so a
        respawned replica re-earns traffic by actually serving, not merely
        by heartbeating."""
        readmitted = False
        with self._lock:
            slot = self._slots.get(rid)
            if slot is None:
                return
            slot.alive = alive
            slot.state = state
            if alive:
                slot.last_seen = time.monotonic()
            if served is not None:
                slot.served = served
            if inflight is not None:
                slot.reported_inflight = inflight
            if model_version is not None:
                slot.model_version = model_version
            if errors is not None:
                slot.errors = errors
            if lat_ms is not None:
                slot.lat_ms = lat_ms
            if svc_ms is not None:
                slot.svc_ms = svc_ms
            if swap_state is not None:
                slot.swap_state = swap_state
            slot.swap_error = swap_error
            if swap_nonce is not None:
                slot.swap_nonce = swap_nonce
            # probe resolution stays under the lock: _pick() reserves
            # slot.probe while holding it, and clearing the reservation here
            # without it could admit a second in-flight probe (the breaker's
            # own lock is leaf-level, so nesting it is deadlock-free)
            probe = slot.probe
            if probe is not None:
                served_at, t_probe = probe
                if alive and served is not None and served > served_at:
                    slot.breaker.record_success()
                    slot.probe = None
                    readmitted = True
                elif not alive or (time.monotonic() - t_probe
                                   > 2 * self.config.fleet_failover_timeout_s):
                    slot.breaker.record_failure()
                    slot.probe = None
        if readmitted:
            logger.info("fleet: replica %s probe served; readmitted", rid)

    def eligible_ids(self) -> List[str]:
        """Replicas a dispatch could go to right now (hb fresh, lifecycle
        ``up``, neither the replica's nor its host's breaker open; half-open
        counts — the probe admission happens per-dispatch via ``allow()``)."""
        with self._lock:
            slots = list(self._slots.values())
            host_open = {s.rid: self._host_open_locked(s) for s in slots}
        return [s.rid for s in slots
                if s.alive and s.state == "up"
                and s.breaker.state != CircuitBreaker.OPEN
                and s.probe is None and not host_open[s.rid]]

    def set_traffic_fraction(self, rid: str, fraction: float) -> None:
        """Canary traffic weighting (the rollout-policy hook): route roughly
        ``fraction`` of dispatch decisions to ``rid``, the rest to the full-
        weight members. Deterministic (every k-th pick admits the canary, k
        = round(1/fraction)) — no RNG in the dispatch path. ``1.0`` restores
        full membership."""
        if not (0.0 < fraction <= 1.0):
            raise ValueError(f"fraction must be in (0, 1], got {fraction!r}")
        with self._lock:
            slot = self._slots.get(rid)
            if slot is not None:
                slot.weight = float(fraction)

    def depths(self) -> Dict[str, int]:
        with self._lock:
            return {rid: s.depth for rid, s in self._slots.items()}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            slots = list(self._slots.values())
        return {"routed": self.routed, "shed": self.shed,
                "policy": self.policy, "skew_s": self.skew_s,
                "replicas": {
                    s.rid: {"dispatched": s.dispatched, "depth": s.depth,
                            "alive": s.alive, "state": s.state,
                            "served": s.served, "errors": s.errors,
                            "model_version": s.model_version,
                            "swap_state": s.swap_state,
                            "weight": s.weight, "lat_ms": s.lat_ms,
                            "svc_ms": s.svc_ms, "host": s.host,
                            "breaker": s.breaker.state} for s in slots}}

    # -- routing -------------------------------------------------------------

    def _connect(self, tag: str) -> _Conn:
        policy = RetryPolicy(max_attempts=None, base_delay_s=0.05,
                             max_delay_s=0.5, attempt_timeout_s=5.0,
                             retryable=(ConnectionError, OSError))
        return _Conn(self.config.queue_host, self.config.queue_port,
                     policy=policy, abort=self._stop.is_set, tag=tag)

    def _refresh_depths(self, conn: _Conn) -> None:
        """Per-replica queue depth = everything the replica still owes on
        its dispatch stream: undelivered entries PLUS claimed-but-unacked
        ones (group-aware broker LEN — an engine buffers claimed batches
        internally, so the live stream length alone understates load).
        Refreshed at most every 50ms; incremented locally per dispatch in
        between."""
        now = time.monotonic()
        if now - self._depths_refreshed < 0.05:
            return
        self._depths_refreshed = now
        for rid in self.replica_ids():
            try:
                depth = int(conn.call("LEN", self.prefix + rid,
                                      self.group_fmt.format(rid=rid)))
            except RetryAbortedError:
                raise
            except Exception:
                continue
            with self._lock:
                slot = self._slots.get(rid)
                if slot is not None:
                    slot.depth = depth

    def _pick(self) -> Optional[str]:
        """Choose an eligible replica per the policy; reserves a half-open
        probe slot via ``breaker.allow()`` (so at most one in-flight probe
        per recovering replica). Weighted (canary) replicas are admitted as
        candidates only on every ``round(1/weight)``-th pick."""
        with self._lock:
            slots = [s for s in self._slots.values()
                     if s.alive and s.state == "up"
                     and not self._host_open_locked(s)]
            if not slots:
                return None
            self._pick_seq += 1
            if any(s.weight < 1.0 for s in slots):
                admitted = [
                    s for s in slots
                    if s.weight >= 1.0
                    or self._pick_seq % max(1, round(1.0 / s.weight)) == 0]
                # a rotation of only weighted members must not stall traffic
                slots = admitted or slots
            if self.policy == "least_pending":
                # host-spread tie-break: equal-depth replicas go to the host
                # with the least TOTAL pending work first, so cross-host
                # placement stays balanced even when every replica is idle
                hload: Dict[str, int] = {}
                for s in slots:
                    key = s.host or s.rid
                    hload[key] = hload.get(key, 0) + s.depth
                order = sorted(slots,
                               key=lambda s: (s.depth,
                                              hload[s.host or s.rid]))
            else:                       # round_robin over the stable roster
                n = len(slots)
                start = self._rr_next % n
                order = slots[start:] + slots[:start]
                self._rr_next += 1
            for slot in order:
                if slot.breaker.allow():
                    # the half-open check must come AFTER the admission:
                    # allow() itself transitions OPEN -> HALF_OPEN once the
                    # reset timeout elapses, and a consumed probe slot that
                    # never lands on slot.probe would wedge the breaker
                    # half-open forever (set_liveness only resolves recorded
                    # probes). Post-admission HALF_OPEN implies exactly that
                    # a probe was reserved; CLOSED admissions need none.
                    if slot.breaker.state == CircuitBreaker.HALF_OPEN:
                        slot.probe = (slot.served, time.monotonic())
                    return slot.rid
        return None

    def _wait_estimate(self) -> Tuple[float, float, int, int]:
        """(best-replica est wait s, per-record service estimate s,
        total owed, eligible count) from the heartbeat-fed slots. The
        service estimate is the per-RECORD compute-time EMA the engines
        publish (``svc_ms``) — deliberately NOT the receipt→computed
        latency, which includes replica-side queue wait and would double-
        count it against the depth (over-shedding healthy traffic)."""
        with self._lock:
            live = [s for s in self._slots.values()
                    if s.alive and s.state == "up"
                    and s.breaker.state != CircuitBreaker.OPEN
                    and not self._host_open_locked(s)]
            depths = [s.depth for s in live]
            svcs = [s.svc_ms for s in live if s.svc_ms > 0]
        if not live:
            return 0.0, 0.0, 0, 0
        svc = (min(svcs) / 1e3) if svcs else 0.0
        return min(depths) * svc, svc, sum(depths), len(live)

    @staticmethod
    def _hold_key(item) -> Tuple:
        """(priority, deadline, arrival) ordering for held entries — the
        entry id's monotonic sequence keeps FIFO fairness inside a class."""
        entry_id, payload = item
        try:
            seq = int(str(entry_id).split("-")[0])
        except (TypeError, ValueError):
            seq = 0
        return _qos.order_key(payload_priority(payload),
                              payload_deadline(payload), seq)

    def _maybe_shed(self, conn: _Conn, payload: Any) -> bool:
        """Shed one held entry whose deadline provably cannot be met —
        BEFORE spending a dispatch on it. The shed answer (first-write-wins,
        like any replica result) carries the computed Retry-After so the
        waiting client backs off proportionally to real drain time."""
        dl = payload_deadline(payload)
        if dl is None:
            return False
        est, svc, total, eligible = self._wait_estimate()
        rec = _flight.get()
        # skew_s loosens the verdict by the fleet's measured cross-host
        # clock uncertainty: the deadline was stamped on the CLIENT's clock.
        # With no recorder installed the admit case (the per-wave hot path —
        # each held entry is re-judged every claim wave) answers on the bare
        # predicate; the shed path and any recorded decision go through the
        # full pure function, so live and replay semantics stay identical
        # (cannot_meet is monotone in `now`: an admit here is an admit there)
        if rec is None and not _qos.cannot_meet(
                dl, est, svc, skew_tolerance_s=self.skew_s):
            return False
        pri = payload_priority(payload)
        inputs = {"now": time.time(), "deadline": dl, "est_wait_s": est,
                  "service_ema_s": svc, "skew_tolerance_s": self.skew_s,
                  "depth": total, "concurrency": max(1, eligible),
                  "eligible": eligible, "priority": pri}
        decision = _qos.admission_decision(inputs)
        if rec is not None:
            # admits are recorded too: a candidate policy replayed offline
            # may shed what the incumbent admitted — the diff needs both
            rec.record("admission.router", inputs, decision)
        if decision["action"] != "shed":
            return False
        chaos_point("overload.shed", tag="router")
        uri = payload.get("uri") if isinstance(payload, dict) else None
        if uri:
            conn.call("HSETNX", RESULT_PREFIX + uri, _qos.shed_payload(
                "deadline cannot be met at the routing tier "
                f"(est wait {est + svc:.3f}s)",
                decision["retry_after_s"], reason="deadline"))
        self.shed += 1
        _ROUTER_SHED.labels(reason="deadline").inc()
        _REQ_OUTCOMES.labels(priority=pri, outcome="shed").inc()
        # audit-rate, not request-rate: under sustained overload this fires
        # per request, so repeats within the window fold into `suppressed`
        _ev.emit("shed.router", severity="warning", throttle_s=1.0,
                 reason="deadline", priority=pri,
                 est_wait_s=decision["est_wait_s"], eligible=eligible)
        return True

    def _note_dispatched(self, rid: str) -> None:
        with self._lock:
            slot = self._slots.get(rid)
            if slot is not None:
                slot.dispatched += 1
                slot.depth += 1
        self.routed += 1
        _DISPATCH.labels(replica=rid).inc()

    def _route_loop(self):
        conn = self._connect("fleet.router")
        hb = (self.registry.register("fleet.router")
              if self.registry is not None else None)
        hold: "collections.deque" = collections.deque()
        try:
            while not self._stop.is_set():
                if hb is not None:
                    hb.beat()
                if not hold:
                    if self._draining.is_set():
                        break           # drained: nothing held, stop claiming
                    try:
                        entries = conn.call("XREADGROUP", self.stream,
                                            self.group, 64, 100)
                    except RetryAbortedError:
                        break
                    if entries:
                        hold.extend(entries)
                        # (priority, deadline) ordering: eligible work is
                        # dispatched critical-first, earliest-deadline-first
                        # within a class, FIFO within ties — stable across
                        # re-sorts because the entry id is the tiebreak
                        hold = collections.deque(
                            sorted(hold, key=self._hold_key))
                    if not hold:
                        continue
                try:
                    self._refresh_depths(conn)
                    done: List[str] = []
                    stalled = False
                    while hold:
                        entry_id, payload = hold[0]
                        if self._maybe_shed(conn, payload):
                            # answered with a shed record: ack the origin
                            # entry, never dispatch it
                            hold.popleft()
                            done.append(entry_id)
                            continue
                        rid = self._pick()
                        if rid is None:
                            stalled = True
                            break
                        # deterministic fault site: a "fail" rule drops this
                        # routing decision (entry retried next iteration —
                        # at-least-once), a "delay" rule models a slow router
                        chaos_point("fleet.route", tag=rid)
                        conn.call("XADD", self.prefix + rid, payload)
                        self._note_dispatched(rid)
                        hold.popleft()
                        done.append(entry_id)
                    if done:
                        conn.call("XACK", self.stream, self.group, done)
                    if stalled:
                        _NO_REPLICA.inc()
                        self._stop.wait(0.02)
                except RetryAbortedError:
                    break
                except Exception:
                    # injected routing fault / transient broker hiccup: the
                    # un-forwarded entries stay in `hold` (and pending
                    # broker-side under the router group) — retry, never drop
                    logger.exception("fleet: routing iteration failed; "
                                     "holding %d entries", len(hold))
                    self._stop.wait(0.02)
        finally:
            if hb is not None:
                hb.stop()
            conn.close()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ReplicaRouter":
        self._stop.clear()
        self._draining.clear()
        conn = self._connect("fleet.router-init")
        try:
            conn.call("XGROUPCREATE", self.stream, self.group, "$")
        except RetryAbortedError:
            pass
        finally:
            conn.close()
        self._thread = threading.Thread(target=self._route_loop, daemon=True,
                                        name="zoo-fleet-router")
        self._thread.start()
        return self

    def stop(self, drain_s: float = 2.0):
        """Drain-then-stop: forward everything already claimed, then exit.
        Unclaimed stream entries stay on the broker (redelivered to the next
        router incarnation)."""
        self._draining.set()
        if self._thread is not None:
            self._thread.join(timeout=drain_s)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class _ReplicaHandle:
    """Supervisor-side handle on one replica incarnation."""

    def __init__(self, rid: str, mode: str):
        self.rid = rid
        self.mode = mode                    # "thread" | "process" | "host"
        self.engine: Optional[ClusterServing] = None
        self.proc: Optional[subprocess.Popen] = None
        self.host: Optional[str] = None     # placement (host mode)
        self.spawned_at = time.monotonic()
        self.drain_requested = False
        self.restarting = False             # deliberate restart in progress:
                                            # the monitor must not failover
        self.generation = 0                 # incarnation count (respawns)

    def kill(self):
        """Hard-stop this incarnation (no drain, no acks)."""
        if self.engine is not None:
            self.engine.kill()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()

    def stop(self, drain_s: float = 2.0):
        if self.engine is not None:
            self.engine.stop(drain_s)
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=max(drain_s, 5.0))
            except subprocess.TimeoutExpired:
                self.proc.kill()


class _HostSlot:
    """Supervisor-side view of one host failure domain: the desired replica
    placement, the host breaker (dials fail fast while it is open), the
    measured clock offset, and the locally-managed stand-in agent (if any).
    Single-writer: mutated only by the monitor thread + lifecycle calls,
    like ``_handles``."""

    def __init__(self, hid: str, config: ServingConfig):
        self.hid = hid
        self.capacity = max(1, getattr(config, "fleet_host_capacity", 4))
        self.breaker = CircuitBreaker(
            failure_threshold=config.breaker_failure_threshold,
            reset_timeout_s=config.breaker_reset_timeout_s,
            name=f"fleet-host-{hid}")
        self.replicas: set = set()      # desired placement (rids)
        self.reported: set = set()      # rids the agent reports running
        self.alive = False
        self.hb_seen = False            # first fresh heartbeat observed?
        self.state = "up"
        self.identity: Optional[str] = None
        self.last_hb_wall = 0.0         # supervisor clock at last fresh hb
        # NTP-style offset estimate (host clock - supervisor clock) from the
        # ping/pong riding the ctl/hb hashes; EMA over round trips
        self.clock_offset_s = 0.0
        self.skew_samples = 0
        self.last_pong_t0: Any = None   # dedupe: one sample per echo
        self.ctl_nonce = 0
        self.retiring = False           # scale-down drain owns this host
        self.proc: Optional[subprocess.Popen] = None   # stand-in subprocess
        self.agent: Optional[HostAgent] = None         # in-process stand-in


class FleetSupervisor:
    """Heartbeat-monitors N replicas, requeues a dead replica's claimed
    work, respawns it, and supports graceful drain / rolling restart.

    ``spawn="thread"`` builds each replica as a :class:`ClusterServing` in
    this process (``model_factory()`` per replica, or ``None`` to load from
    ``config.model_path``); ``spawn="process"`` launches
    ``python -m analytics_zoo_tpu.serving.fleet --replica <rid> ...`` — real
    process isolation, requires ``config.model_path`` (or ``demo=True``).

    ``spawn="host"`` (implied by ``config.fleet_hosts > 0``) places replicas
    on :class:`~.hostagent.HostAgent` failure domains instead of spawning
    them directly: the supervisor writes desired state into each host's
    ``fleet:hostctl:<hid>`` hash and the agents reconcile. With
    ``manage_agents=True`` the supervisor also launches the agents — as
    local stand-in subprocesses (each under a synthetic host identity, so
    their connections negotiate shm like genuinely remote peers and settle
    on TCP), or in-process when a live ``model_factory`` is supplied (tests:
    ``agent.kill()`` is the whole-host death). Real deployments run
    ``python -m analytics_zoo_tpu.serving.hostagent`` per machine and pass
    ``manage_agents=False``.
    """

    def __init__(self, config: ServingConfig, *,
                 model_factory: Optional[Callable[[], Any]] = None,
                 replica_ids: Optional[List[str]] = None,
                 spawn: Optional[str] = None,
                 router: Optional[ReplicaRouter] = None,
                 registry: Optional[HealthRegistry] = None,
                 demo: bool = False, config_path: Optional[str] = None,
                 platform: Optional[str] = None,
                 host_ids: Optional[List[str]] = None,
                 manage_agents: bool = True):
        self.config = config
        self.spawn = spawn or (
            "host" if getattr(config, "fleet_hosts", 0) > 0
            else config.fleet_spawn)
        if self.spawn not in ("thread", "process", "host"):
            raise ValueError(f"unknown spawn mode {self.spawn!r}")
        self.model_factory = model_factory
        self.demo = demo
        # process-mode replicas re-read the operator's YAML themselves: a
        # live ServingConfig object can't cross the fork, and spawning with
        # defaults would silently drop batch/int8/heartbeat tuning
        self.config_path = config_path
        self.platform = platform
        n0 = max(1, config.replicas)
        if getattr(config, "autoscale", False):
            # start inside the autoscaler's band: at least min_replicas, at
            # most max_replicas — the loop adjusts from there
            n0 = min(max(n0, max(1, config.min_replicas)),
                     max(1, config.max_replicas))
        ids = list(replica_ids) if replica_ids else \
            [f"r{i}" for i in range(n0)]
        self.router = router or ReplicaRouter(config, tuple(ids))
        # the fleet registry holds one component per replica; death/revival
        # TRANSITIONS drive eviction + requeue + respawn via the listener
        # hook (common/resilience.py) — /readyz and tests read it too
        self.registry = registry or HealthRegistry(
            default_timeout_s=config.fleet_failover_timeout_s, name="fleet")
        self.registry.add_transition_listener(self._on_transition)
        # single-writer state: _handles/_hb_seen are mutated only by the
        # monitor thread + lifecycle calls; the shared telemetry the router
        # needs lives on ITS slots (under ITS lock), so no supervisor lock
        self._handles: Dict[str, _ReplicaHandle] = {}
        self._hb_seen: Dict[str, bool] = {}      # first fresh hb observed?
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._conn: Optional[_Conn] = None
        self._rolling_seen: Any = None
        self._rolling_busy = False
        self.requeued = 0
        self.respawns = 0
        self.failovers: List[float] = []
        # host failure domains (spawn="host"): desired placement + liveness
        # per host; single-writer on the monitor thread like _handles
        self._host_mode = self.spawn == "host"
        self._hosts: Dict[str, _HostSlot] = {}
        self.manage_agents = manage_agents
        self.host_failovers = 0
        if self._host_mode:
            n_hosts = max(1, getattr(config, "fleet_hosts", 0) or 2)
            hids = list(host_ids) if host_ids else \
                [f"h{i}" for i in range(n_hosts)]
            for hid in hids:
                self._hosts[hid] = _HostSlot(hid, config)
        # queue-driven autoscaling (ROADMAP "adaptive serving under
        # overload"): the monitor loop watches owed work per eligible
        # replica (the zoo_fleet_queue_depth signal) plus the router's
        # deadline-shed rate, spawns replicas on sustained pressure up to
        # max_replicas, and drains them away (graceful drain + straggler
        # XTRANSFER — zero-loss by construction) when idle down to
        # min_replicas
        self.autoscale_enabled = bool(getattr(config, "autoscale", False))
        # debounce memory owned by the PURE decision function
        # (qos.autoscale_decision) — the flight recorder snapshots it into
        # every autoscale.tick record, which is what makes the recorded
        # decision stream exactly replayable offline
        self._as_state: Dict[str, Any] = {"pressure_since": None,
                                          "idle_since": None,
                                          "last_event_t": 0.0}
        self._as_last_routed = 0
        self._as_last_shed = 0
        self._as_busy = False          # a scale-down drain is in flight
        self.scale_events: List[Tuple[str, int]] = []
        # canary rollout controller (serving/hotswap.py): consumes the
        # trainer's publish stream and drives per-replica swap commands
        self.rollout = None
        if getattr(config, "hot_swap", True):
            from .hotswap import RolloutController

            self.rollout = RolloutController(self, config)

    # -- lifecycle -----------------------------------------------------------

    def _connect(self, tag: str) -> _Conn:
        policy = RetryPolicy(max_attempts=None, base_delay_s=0.05,
                             max_delay_s=0.5, attempt_timeout_s=5.0,
                             retryable=(ConnectionError, OSError))
        return _Conn(self.config.queue_host, self.config.queue_port,
                     policy=policy, abort=self._stop.is_set, tag=tag)

    def start(self) -> "FleetSupervisor":
        self._stop.clear()
        self._conn = self._connect("fleet.supervisor")
        try:
            # roster published for operators (`cli fleet-status`/frontends)
            self._conn.call("HSET", MEMBERS_KEY,
                            {"replicas": self.router.replica_ids(),
                             "spawn": self.spawn,
                             "hosts": sorted(self._hosts)})
            # a rolling-restart nonce left by a PREVIOUS stack incarnation
            # (the hash is never deleted and survives AOF replay) is an
            # already-executed command, not an order for this one: snapshot
            # it so only nonces written from now on trigger
            prior = self._conn.call("HGET", ROLLING_KEY, 0)
            if isinstance(prior, dict):
                self._rolling_seen = prior.get("nonce")
        except RetryAbortedError:
            pass
        self.router.start()
        for hid, slot in self._hosts.items():
            self.router.set_host_breaker(hid, slot.breaker)
            # host liveness budget: spawn grace until the first heartbeat
            # (the agent may still be importing/compiling), failover timeout
            # after
            self.registry.register(f"host.{hid}",
                                   timeout_s=self.config.fleet_spawn_grace_s)
            if self.manage_agents:
                self._start_agent(hid)
        for rid in self.router.replica_ids():
            self._spawn_replica(rid)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="zoo-fleet-supervisor")
        self._monitor.start()
        if self.rollout is not None:
            self.rollout.start()
        return self

    def _replica_config(self) -> ServingConfig:
        import dataclasses

        return dataclasses.replace(self.config)

    def _start_agent(self, hid: str) -> None:
        """Launch the stand-in agent for one host: in-process (a live
        ``model_factory`` can't cross a fork) or as a subprocess under a
        synthetic host identity — its engines then negotiate shm like
        genuinely remote peers (denied → TCP with retry-backed reconnect)."""
        slot = self._hosts[hid]
        if self.model_factory is not None and not self.demo:
            slot.agent = HostAgent(hid, self._replica_config(),
                                   model_factory=self.model_factory,
                                   capacity=slot.capacity)
            slot.agent.start()
            return
        cmd = [sys.executable, "-m", "analytics_zoo_tpu.serving.hostagent",
               "--hid", hid,
               "--broker-host", self.config.queue_host,
               "--broker-port", str(self.config.queue_port),
               "--capacity", str(slot.capacity)]
        if self.config_path:
            cmd += ["--config", self.config_path]
        if self.platform:
            cmd += ["--platform", self.platform]
        if self.demo:
            cmd.append("--demo")
        elif self.config.model_path:
            cmd += ["--model", self.config.model_path]
        elif not self.config_path:
            raise ValueError("host-mode agents need model_path, config_path, "
                             "demo=True, or an in-process model_factory")
        env = dict(os.environ)
        env["ZOO_HOST_IDENTITY"] = f"{host_identity()}/{hid}"
        slot.proc = subprocess.Popen(cmd, env=env)

    def _place_host(self, exclude: Tuple[str, ...] = ()) -> Optional[str]:
        """Spread placement: the emptiest host with free capacity wins, live
        hosts before not-yet-heartbeating ones, never one whose breaker is
        open. "Emptiest first" IS the borrow-a-machine policy — an idle
        registered host attracts the next replica before any occupied host
        gets packed further."""
        cands = [s for s in self._hosts.values()
                 if s.hid not in exclude and not s.retiring
                 and s.breaker.state != CircuitBreaker.OPEN
                 and len(s.replicas) < s.capacity]
        if not cands:
            return None
        cands.sort(key=lambda s: (not s.alive, len(s.replicas), s.hid))
        return cands[0].hid

    def _push_host_ctl(self, hid: str, shutdown: bool = False) -> None:
        """Publish one host's desired state (declarative: the agent
        reconciles; re-sends converge idempotently). The piggybacked
        ``ping_t0`` is the skew-estimation round trip's first leg."""
        slot = self._hosts.get(hid)
        if slot is None:
            return
        slot.ctl_nonce += 1
        mapping: Dict[str, Any] = {
            "replicas": {rid: self._handles[rid].generation
                         for rid in sorted(slot.replicas)
                         if rid in self._handles},
            "nonce": slot.ctl_nonce, "ping_t0": time.time()}
        if shutdown:
            mapping["shutdown"] = True
        try:
            self._conn.call("HSET", HOST_CTL_PREFIX + hid, mapping)
        except RetryAbortedError:
            raise
        except Exception:
            logger.exception("fleet: host ctl push for %s failed", hid)

    def _assign_replica(self, rid: str, hid: str) -> None:
        """Place one replica on a host: desired-state bookkeeping here, the
        actual engine spawn happens agent-side on the next reconcile."""
        handle = self._handles.get(rid)
        generation = handle.generation + 1 if handle is not None else 1
        handle = _ReplicaHandle(rid, "host")
        handle.generation = generation
        handle.host = hid
        try:
            self._conn.call("HDEL", FLEET_HB_PREFIX + rid)
            self._conn.call("HDEL", FLEET_CTL_PREFIX + rid)
        except RetryAbortedError:
            pass
        for s in self._hosts.values():
            s.replicas.discard(rid)
        self._hosts[hid].replicas.add(rid)
        self._handles[rid] = handle
        self._hb_seen[rid] = False
        self.registry.register(f"replica.{rid}",
                               timeout_s=self.config.fleet_spawn_grace_s)
        self.router.add_replica(rid)
        self.router.set_replica_host(rid, hid)
        self._push_host_ctl(hid)

    def _spawn_replica(self, rid: str) -> None:
        if self._host_mode:
            target = self._place_host()
            if target is None:
                raise RuntimeError(f"fleet: no host with free capacity for "
                                   f"replica {rid}")
            self._assign_replica(rid, target)
            return
        handle = self._handles.get(rid)
        generation = handle.generation + 1 if handle is not None else 1
        handle = _ReplicaHandle(rid, self.spawn)
        handle.generation = generation
        # stale state from the previous incarnation must not leak in: a dead
        # replica's old hb would otherwise look "fresh enough" right after
        # respawn, and an old drain command would insta-drain the new one
        try:
            self._conn.call("HDEL", FLEET_HB_PREFIX + rid)
            self._conn.call("HDEL", FLEET_CTL_PREFIX + rid)
        except RetryAbortedError:
            pass
        if self.spawn == "thread":
            model = self.model_factory() if self.model_factory else None
            handle.engine = ClusterServing(
                model, config=self._replica_config(), group=f"fleet-{rid}",
                stream=self.router.prefix + rid, replica_id=rid,
                dedup_results=True)
            handle.engine.start()
        else:
            cmd = [sys.executable, "-m", "analytics_zoo_tpu.serving.fleet",
                   "--replica", rid,
                   "--broker-host", self.config.queue_host,
                   "--broker-port", str(self.config.queue_port)]
            if self.config_path:
                cmd += ["--config", self.config_path]
            if self.platform:
                cmd += ["--platform", self.platform]
            if self.demo:
                cmd.append("--demo")
            elif self.config.model_path:
                cmd += ["--model", self.config.model_path]
            elif not self.config_path:
                raise ValueError("process-mode replicas need model_path, "
                                 "config_path, or demo=True")
            handle.proc = subprocess.Popen(cmd)
        self._handles[rid] = handle
        self._hb_seen[rid] = False
        # liveness budget: normal failover timeout once beating; until the
        # first heartbeat the replica may still be loading/compiling, so it
        # gets the spawn grace instead
        self.registry.register(f"replica.{rid}",
                               timeout_s=self.config.fleet_spawn_grace_s)
        self.router.add_replica(rid)

    # -- monitoring ----------------------------------------------------------

    def _monitor_loop(self):
        interval = max(0.05, min(self.config.fleet_heartbeat_s, 0.2))
        while not self._stop.is_set():
            try:
                self._poll_once()
            except RetryAbortedError:
                break
            except Exception:
                logger.exception("fleet: supervisor poll failed")
            self._stop.wait(interval)

    def _poll_hosts(self, now: float) -> None:
        """Host-tier liveness + clock-skew pass. Runs BEFORE the replica
        pass so a whole-host death is recognized as ONE decision (the
        replica pass then skips that host's replicas instead of issuing N
        independent failovers)."""
        for hid, slot in self._hosts.items():
            # re-publishing desired state is idempotent agent-side and
            # refreshes ping_t0 — each round trip is one skew sample
            self._push_host_ctl(hid)
            hb = self._conn.call("HGET", HOST_HB_PREFIX + hid, 0)
            proc_dead = (slot.proc is not None
                         and slot.proc.poll() is not None)
            fresh = False
            if isinstance(hb, dict):
                slot.identity = hb.get("identity") or slot.identity
                slot.reported = set(hb.get("replicas") or ())
                slot.state = str(hb.get("state", "up"))
                pong_t0 = hb.get("pong_t0")
                pong_host_t = hb.get("pong_host_t")
                if (pong_t0 is not None and pong_host_t is not None
                        and pong_t0 != slot.last_pong_t0):
                    # one sample per DISTINCT echo: re-reading a frozen
                    # heartbeat (dead host) must not keep feeding the EMA
                    # with an ever-staler round trip
                    slot.last_pong_t0 = pong_t0
                    # NTP-style offset from the hb round trip: the agent saw
                    # our ping_t0 and stamped its own clock at the echo;
                    # midpoint of [t0, now] is our best guess at when.
                    t2 = time.time()
                    rtt = t2 - float(pong_t0)
                    if 0.0 <= rtt < 5.0:
                        off = float(pong_host_t) - (float(pong_t0) + t2) / 2.0
                        if slot.skew_samples == 0:
                            slot.clock_offset_s = off
                        else:
                            slot.clock_offset_s = (0.7 * slot.clock_offset_s
                                                   + 0.3 * off)
                        slot.skew_samples += 1
                        _HOST_SKEW.labels(host=hid).set(slot.clock_offset_s)
                # translate the host's clock into ours before judging
                # freshness — a skewed-but-healthy host must not look stale
                ts = float(hb.get("ts", 0.0)) - slot.clock_offset_s
                fresh = (now - ts < self.config.fleet_failover_timeout_s
                         and slot.state != "stopped")
            if fresh and not proc_dead:
                if not slot.hb_seen:
                    slot.hb_seen = True
                    self.registry.register(
                        f"host.{hid}",
                        timeout_s=self.config.fleet_failover_timeout_s)
                self.registry.beat(f"host.{hid}")
                if not slot.alive:
                    # dead -> alive edge: a fresh heartbeat is live proof of
                    # recovery — close the per-host breaker rather than
                    # waiting out its probe cycle
                    slot.alive = True
                    if slot.breaker.state != CircuitBreaker.CLOSED:
                        logger.info("fleet: host %s is back", hid)
                        slot.breaker.reset()
                slot.last_hb_wall = now
            elif proc_dead:
                self.registry.register(f"host.{hid}", timeout_s=0.0)
        alive = sum(1 for s in self._hosts.values() if s.alive)
        _HOSTS.labels(state="alive").set(alive)
        _HOSTS.labels(state="dead").set(len(self._hosts) - alive)
        # worst observed |offset| across live hosts widens the QoS deadline
        # tolerance: a request is only refused when it cannot be met even
        # after allowing for how far fleet clocks disagree
        worst = max((abs(s.clock_offset_s) for s in self._hosts.values()
                     if s.alive and s.skew_samples), default=0.0)
        self.router.skew_s = (self.config.fleet_host_skew_tolerance_s
                              + worst)

    def _poll_once(self):
        now = time.time()
        if self._host_mode:
            self._poll_hosts(now)
        for rid in list(self._handles):
            hb = self._conn.call("HGET", FLEET_HB_PREFIX + rid, 0)
            handle = self._handles.get(rid)
            if handle is None:
                continue
            # a process-mode replica that exited is dead regardless of the
            # staleness window — don't wait out the timeout
            proc_dead = (handle.proc is not None
                         and handle.proc.poll() is not None)
            fresh = (isinstance(hb, dict)
                     and now - float(hb.get("ts", 0))
                     < self.config.fleet_failover_timeout_s
                     and hb.get("state") != "stopped")
            if fresh and not proc_dead:
                if not self._hb_seen.get(rid):
                    # first beat: tighten the liveness budget from spawn
                    # grace down to the failover timeout. Host-placed
                    # replicas get 1.5x — if the whole host died, the host
                    # component (1.0x) must expire FIRST so the failover is
                    # one host-level decision, not N per-replica races; a
                    # lone engine crash inside a live host still trips this.
                    self._hb_seen[rid] = True
                    budget = self.config.fleet_failover_timeout_s
                    if self._host_mode:
                        budget *= 1.5
                    self.registry.register(f"replica.{rid}",
                                           timeout_s=budget)
                self.registry.beat(f"replica.{rid}")
                state = str(hb.get("state", "up"))
                if state in ("draining", "drained") and not handle.restarting:
                    # the drain may have been commanded out-of-band (`cli
                    # drain` writes the control hash directly): a replica
                    # that dies mid-drain must not be respawned regardless
                    # of which path asked for the drain
                    handle.drain_requested = True
                self.router.set_liveness(
                    rid, True, state=state,
                    served=int(hb.get("served", 0)),
                    inflight=int(hb.get("inflight", 0)),
                    model_version=hb.get("model_version"),
                    errors=int(hb.get("errors", 0)),
                    lat_ms=float(hb.get("lat_ms", 0.0)),
                    svc_ms=float(hb.get("svc_ms", 0.0)),
                    swap_state=hb.get("swap_state"),
                    swap_error=hb.get("swap_error"),
                    swap_nonce=hb.get("swap_nonce"))
            elif proc_dead:
                # hard process exit: expire the component immediately by
                # re-registering with a zero budget — check_transitions
                # below turns that into the death callback
                self.registry.register(f"replica.{rid}", timeout_s=0.0)
        self.registry.check_transitions()
        self._check_rolling()
        self._autoscale_check()

    def _on_transition(self, component: str, alive: bool) -> None:
        if component.startswith("host."):
            hid = component[len("host."):]
            slot = self._hosts.get(hid)
            if slot is None:
                return
            if alive:
                # re-registering a failed-over host resurrects its registry
                # component and fires this edge too — only a FRESH heartbeat
                # (slot.alive, set by the host poll) is proof of recovery
                if slot.alive:
                    logger.info("fleet: host %s is back", hid)
                    slot.breaker.reset()
                return
            if self._stop.is_set() or slot.retiring:
                return
            if slot.state == "stopped":
                # graceful agent shutdown, not a failure
                slot.alive = False
                return
            if not slot.alive:
                return  # already failed over; edge only fires once per death
            self._host_failover(hid)
            return
        if not component.startswith("replica."):
            return
        rid = component[len("replica."):]
        if alive:
            logger.info("fleet: replica %s is back", rid)
            return
        if self._stop.is_set():
            return
        handle = self._handles.get(rid)
        if handle is not None and handle.restarting:
            return      # deliberate rolling restart owns this lifecycle
        if handle is not None and handle.host is not None:
            hslot = self._hosts.get(handle.host)
            if hslot is not None and (
                    not hslot.alive
                    or time.time() - hslot.last_hb_wall
                    > self.config.fleet_failover_timeout_s):
                # its whole host is dead/dying: the host failover owns
                # every replica there in ONE decision — no per-replica
                # failovers racing it
                return
        self._failover(rid)

    def _failover(self, rid: str) -> None:
        """A replica went silent: evict it from routing, claim-transfer its
        owed requests back to the dispatch stream, respawn it (unless it was
        deliberately draining). Zero-loss: nothing it claimed was acked, so
        everything it owed is still on the broker.

        The whole action runs inside a ``fleet.failover`` span and emits one
        decision event carrying that trace — an operator reading
        ``/debug/events`` can pull the complete failover timeline as a
        Perfetto trace."""
        t0 = time.perf_counter()
        handle = self._handles.get(rid)
        with _tm.span("fleet.failover", replica=rid) as sp:
            self.router.evict(rid)
            self.router.set_liveness(rid, False, state="dead")
            try:
                res = self._conn.call("XTRANSFER", self.router.prefix + rid,
                                      f"fleet-{rid}", self.router.stream)
                moved = (int(res.get("moved", 0))
                         if isinstance(res, dict) else 0)
            except RetryAbortedError:
                return
            except Exception:
                logger.exception("fleet: requeue for dead replica %s failed",
                                 rid)
                moved = 0
            if moved:
                _REQUEUED.inc(moved)
                self.requeued += moved
            logger.warning("fleet: replica %s dead; requeued %d claimed "
                           "request(s)", rid, moved)
            respawned = False
            if handle is not None:
                handle.kill()   # reap whatever half-dead incarnation remains
                if not handle.drain_requested:
                    chaos_point("fleet.respawn", tag=rid)
                    self._spawn_replica(rid)
                    self.respawns += 1
                    _FLEET_RESPAWNS.inc()
                    respawned = True
                else:
                    # died while draining: work requeued above; the drain
                    # decided this replica should not take traffic
                    self._handles.pop(rid, None)
                    self._hb_seen.pop(rid, None)
                    self.router.remove_replica(rid)
                    self.registry.deregister(f"replica.{rid}")
                    if handle.host is not None:
                        hslot = self._hosts.get(handle.host)
                        if hslot is not None:
                            hslot.replicas.discard(rid)
                            self._push_host_ctl(handle.host)
            dt = time.perf_counter() - t0
            self.failovers.append(dt)
            _FAILOVER.observe(dt)
            _ev.emit("fleet.failover", severity="warning",
                     trace_id=sp.trace_id, replica=rid, requeued=moved,
                     respawned=respawned, failover_s=round(dt, 4))

    def _host_failover(self, hid: str) -> None:
        """An entire host went silent: evict EVERY replica it carried,
        claim-transfer all their owed work back, and respawn each on a
        surviving host — one decision, one span, one ``fleet.host_failed``
        event. Zero-loss for the same reason single-replica failover is:
        dead engines acked nothing, so everything they owed is still on the
        broker (dedup tombstones absorb the did-the-ack-race cases).

        The parent span is tagged with THIS process's host identity; each
        per-replica child span carries the failed host's id and its last
        estimated clock offset — the exported trace therefore stitches
        spans from both machines with explicit clock-offset annotations."""
        slot = self._hosts[hid]
        t0 = time.perf_counter()
        rids = sorted(slot.replicas)
        # black-box the control inputs behind the verdict: how stale the
        # heartbeat was (on OUR clock, after skew translation) vs the budget
        now_w = time.time()
        _flight.record(
            "fleet.host_check",
            {"now": now_w, "host": hid,
             "hb_age_s": round(now_w - slot.last_hb_wall, 4),
             "timeout_s": self.config.fleet_failover_timeout_s,
             "clock_offset_s": round(slot.clock_offset_s, 6),
             "replicas": rids},
            {"action": "failover", "replicas": rids})
        with _tm.span("fleet.host_failover", host=host_identity(),
                      failed_host=hid, replicas=len(rids)) as sp:
            # fail fast from now on: dials/routes to this host short-circuit
            # through the breaker until fresh heartbeats prove recovery
            slot.breaker.trip()
            slot.alive = False
            slot.hb_seen = False
            self.registry.register(f"host.{hid}",
                                   timeout_s=self.config.fleet_spawn_grace_s)
            if slot.agent is not None:
                try:
                    slot.agent.kill()
                except Exception:
                    pass
                slot.agent = None
            if slot.proc is not None:
                try:
                    slot.proc.kill()
                    slot.proc.wait(timeout=2.0)
                except Exception:
                    pass
                slot.proc = None
            total_moved = 0
            for rid in rids:
                with _tm.span("fleet.host_failover.evict", replica=rid,
                              host=hid,
                              clock_offset_s=round(slot.clock_offset_s, 6)):
                    self.router.evict(rid)
                    self.router.set_liveness(rid, False, state="dead")
                    try:
                        res = self._conn.call(
                            "XTRANSFER", self.router.prefix + rid,
                            f"fleet-{rid}", self.router.stream)
                        moved = (int(res.get("moved", 0))
                                 if isinstance(res, dict) else 0)
                    except RetryAbortedError:
                        return
                    except Exception:
                        logger.exception("fleet: requeue for %s on dead "
                                         "host %s failed", rid, hid)
                        moved = 0
                    total_moved += moved
            if total_moved:
                _REQUEUED.inc(total_moved)
                self.requeued += total_moved
            slot.replicas.clear()
            logger.warning("fleet: host %s dead; evicted %s, requeued %d "
                           "claimed request(s)", hid, rids, total_moved)
            respawned: Dict[str, Optional[str]] = {}
            for rid in rids:
                handle = self._handles.get(rid)
                if handle is not None and handle.drain_requested:
                    self._handles.pop(rid, None)
                    self._hb_seen.pop(rid, None)
                    self.router.remove_replica(rid)
                    self.registry.deregister(f"replica.{rid}")
                    continue
                chaos_point("fleet.host_respawn", tag=rid)
                target = self._place_host(exclude=(hid,))
                if target is None:
                    # honest stall: no surviving capacity — leave the handle
                    # so a later recovery/scale-up can re-place it
                    logger.error("fleet: no surviving host can take %s "
                                 "(all at capacity or open)", rid)
                    respawned[rid] = None
                    continue
                self._assign_replica(rid, target)
                self.respawns += 1
                _FLEET_RESPAWNS.inc()
                respawned[rid] = target
            dt = time.perf_counter() - t0
            self.failovers.append(dt)
            _FAILOVER.observe(dt)
            _HOST_FAILOVERS.inc()
            self.host_failovers += 1
            _ev.emit("fleet.host_failed", severity="error",
                     trace_id=sp.trace_id, host=hid, replicas=rids,
                     requeued=total_moved, respawned=respawned,
                     failover_s=round(dt, 4),
                     clock_offset_s=round(slot.clock_offset_s, 6))

    def dial_host(self, hid: str) -> Any:
        """Probe one host through its circuit breaker. While the host is
        marked dead the breaker is OPEN and this fails fast —
        :class:`CircuitOpenError` with a computed ``retry_after_s`` —
        without touching the network path. Half-open probes judge the
        host's HEARTBEAT freshness (broker reachability proves nothing
        about the host), so a still-dead host re-opens the breaker."""
        slot = self._hosts[hid]

        def probe():
            hb = self._conn.call("HGET", HOST_HB_PREFIX + hid, 0)
            fresh = (isinstance(hb, dict)
                     and time.time() - (float(hb.get("ts", 0.0))
                                        - slot.clock_offset_s)
                     < self.config.fleet_failover_timeout_s
                     and hb.get("state") != "stopped")
            if not fresh:
                raise ConnectionError(f"host {hid}: heartbeat stale or "
                                      "missing")
            return hb

        return slot.breaker.call(probe)

    def kill_host(self, hid: str) -> None:
        """Chaos hook: SIGKILL the whole host agent (subprocess) or
        hard-kill the in-process one — every replica it carried dies at
        once, nothing acks, no goodbye heartbeat."""
        slot = self._hosts[hid]
        if slot.agent is not None:
            slot.agent.kill()
        if slot.proc is not None:
            slot.proc.kill()

    # -- autoscaling ---------------------------------------------------------

    def _fresh_rid(self) -> str:
        i = 0
        while f"r{i}" in self._handles:
            i += 1
        return f"r{i}"

    def _owed_work(self) -> Optional[int]:
        """Total work the fleet still owes, measured at the BROKER (the
        router's cached per-replica depths only refresh while it is
        actively routing, so they can hold a stale nonzero value across an
        idle gap): un-routed entries on the shared stream plus everything
        owed on every replica dispatch stream. ``None`` = broker
        unreachable this poll (treated as not-idle)."""
        try:
            total = int(self._conn.call("LEN", self.router.stream,
                                        self.router.group))
            for rid in self.router.replica_ids():
                total += int(self._conn.call(
                    "LEN", self.router.prefix + rid,
                    self.router.group_fmt.format(rid=rid)))
        except RetryAbortedError:
            raise
        except Exception:
            return None
        return total

    def _autoscale_check(self) -> None:
        """One autoscaler evaluation (runs on the monitor thread, every
        poll). The pressure signal is owed work per ELIGIBLE replica —
        exactly what ``zoo_fleet_queue_depth`` publishes — plus the router's
        deadline-shed rate (shed traffic is demand the current fleet failed
        to serve, so it counts double). Both directions are debounced
        (sustain/idle windows) and rate-limited (cooldown) so one slow
        batch never spawns a replica and a gap between bursts never drains
        one."""
        if not self.autoscale_enabled or self._as_busy \
                or self._stop.is_set():
            return
        cfg = self.config
        shed_delta = self.router.shed - self._as_last_shed
        self._as_last_shed = self.router.shed
        routed_delta = self.router.routed - self._as_last_routed
        self._as_last_routed = self.router.routed
        obs = {"now": time.monotonic(),
               "n": len(self._handles),
               "eligible": len(self.router.eligible_ids()),
               "owed": self._owed_work(),
               "shed_delta": shed_delta,
               "routed_delta": routed_delta,
               "up_depth": cfg.autoscale_up_depth,
               "sustain_s": cfg.autoscale_sustain_s,
               "idle_s": cfg.autoscale_idle_s,
               "cooldown_s": cfg.autoscale_cooldown_s,
               "min_replicas": cfg.min_replicas,
               "max_replicas": cfg.max_replicas}
        # the pre-decision debounce snapshot rides in the record, so every
        # recorded tick replays as a pure function of its own inputs
        state_before = dict(self._as_state)
        decision = _qos.autoscale_decision(obs, self._as_state)
        _flight.record("autoscale.tick", {**obs, "state": state_before},
                       decision)
        if decision["action"] == "up":
            self._scale_up()
        elif decision["action"] == "down":
            self._scale_down()

    def _scale_up(self) -> None:
        rid = self._fresh_rid()
        # deterministic fault site: a "fail" rule aborts THIS spawn attempt
        # (the monitor retries next poll while pressure persists) — the
        # kill-during-scale-up drill targets the spawned replica instead
        chaos_point("autoscale.scale", tag="up")
        scope = "host" if self._host_mode else "replica"
        with _tm.span("fleet.autoscale", direction="up", replica=rid) as sp:
            self._spawn_replica(rid)
            self.scale_events.append(("up", len(self._handles)))
            _AUTOSCALE.labels(direction="up", scope=scope).inc()
            extra = {}
            if self._host_mode:
                handle = self._handles.get(rid)
                # placement is borrow-a-machine: _place_host already chose
                # the emptiest (idlest) registered host for the new replica
                extra["host"] = handle.host if handle is not None else None
            _ev.emit("autoscale.up", trace_id=sp.trace_id, replica=rid,
                     replicas=len(self._handles), **extra)
        logger.info("autoscale: spawned replica %s (%d total) on sustained "
                    "queue pressure", rid, len(self._handles))

    def _scale_down(self) -> None:
        """Drain one replica away, zero-loss: stop routing to it (drain),
        let it finish + ack everything it claimed, then claim-transfer any
        stragglers back to the dispatch pool before deregistering. Runs on
        a side thread — the monitor must keep polling heartbeats during the
        drain."""
        if self._host_mode:
            self._scale_down_host()
            return
        victims = [rid for rid, h in self._handles.items()
                   if not h.drain_requested and not h.restarting]
        if len(victims) <= max(1, self.config.min_replicas):
            return
        rid = victims[-1]        # newest first: r0 stays the stable core
        handle = self._handles[rid]
        handle.restarting = True     # monitor hands off this lifecycle
        self._as_busy = True
        chaos_point("autoscale.scale", tag="down")

        def run():
            try:
                with _tm.span("fleet.autoscale", direction="down",
                              replica=rid) as sp:
                    self.drain(rid)
                    self.wait_state(rid, "drained",
                                    timeout_s=max(
                                        5.0, self.config
                                        .fleet_failover_timeout_s * 4))
                    handle.stop(drain_s=2.0)
                    try:
                        res = self._conn.call("XTRANSFER",
                                              self.router.prefix + rid,
                                              f"fleet-{rid}",
                                              self.router.stream)
                        moved = (int(res.get("moved", 0))
                                 if isinstance(res, dict) else 0)
                        if moved:
                            _REQUEUED.inc(moved)
                            self.requeued += moved
                    except Exception:
                        logger.exception("autoscale: straggler requeue for "
                                         "%s failed", rid)
                    self._handles.pop(rid, None)
                    self._hb_seen.pop(rid, None)
                    self.router.remove_replica(rid)
                    self.registry.deregister(f"replica.{rid}")
                    self.scale_events.append(("down", len(self._handles)))
                    _AUTOSCALE.labels(direction="down",
                                      scope="replica").inc()
                    _ev.emit("autoscale.down", trace_id=sp.trace_id,
                             replica=rid, replicas=len(self._handles))
                logger.info("autoscale: drained replica %s away (%d left)",
                            rid, len(self._handles))
            finally:
                self._as_busy = False

        threading.Thread(target=run, daemon=True,
                         name=f"zoo-autoscale-drain-{rid}").start()

    def _scale_down_host(self) -> None:
        """Host-scoped scale-down: retire a WHOLE host to idle, zero-loss.
        The least-loaded occupied host's replicas are drained (finish +
        ack everything claimed), stragglers claim-transferred back, and
        the host is left registered-but-empty — exactly the idle machine a
        later scale-up borrows first."""
        occupied = [s for s in self._hosts.values()
                    if s.replicas and s.alive and not s.retiring]
        if len(occupied) < 2:
            return      # never drain the last working host
        victim = min(occupied, key=lambda s: (len(s.replicas), s.hid))
        rids = sorted(victim.replicas)
        handles = [self._handles[r] for r in rids if r in self._handles]
        if len(self._handles) - len(rids) < max(1, self.config.min_replicas):
            return      # the fleet floor survives the retirement
        if any(h.drain_requested or h.restarting for h in handles):
            return
        for h in handles:
            h.restarting = True      # monitor hands off these lifecycles
        victim.retiring = True
        self._as_busy = True
        chaos_point("autoscale.scale", tag="down")

        def run():
            try:
                with _tm.span("fleet.autoscale", direction="down",
                              host=victim.hid, replicas=len(rids)) as sp:
                    for rid in rids:
                        self.drain(rid)
                    for rid in rids:
                        self.wait_state(rid, "drained",
                                        timeout_s=max(
                                            5.0, self.config
                                            .fleet_failover_timeout_s * 4))
                    # emptying the desired set makes the agent stop its
                    # engines on the monitor's next ctl push
                    victim.replicas.clear()
                    for rid in rids:
                        try:
                            res = self._conn.call("XTRANSFER",
                                                  self.router.prefix + rid,
                                                  f"fleet-{rid}",
                                                  self.router.stream)
                            moved = (int(res.get("moved", 0))
                                     if isinstance(res, dict) else 0)
                            if moved:
                                _REQUEUED.inc(moved)
                                self.requeued += moved
                        except Exception:
                            logger.exception("autoscale: straggler requeue "
                                             "for %s failed", rid)
                        self._handles.pop(rid, None)
                        self._hb_seen.pop(rid, None)
                        self.router.remove_replica(rid)
                        self.registry.deregister(f"replica.{rid}")
                    self.scale_events.append(("down", len(self._handles)))
                    _AUTOSCALE.labels(direction="down", scope="host").inc()
                    _ev.emit("autoscale.down", trace_id=sp.trace_id,
                             host=victim.hid, replicas_drained=rids,
                             replicas=len(self._handles))
                logger.info("autoscale: retired host %s to idle (drained "
                            "%s; %d replicas left)", victim.hid, rids,
                            len(self._handles))
            finally:
                victim.retiring = False
                self._as_busy = False

        threading.Thread(target=run, daemon=True,
                         name=f"zoo-autoscale-drain-{victim.hid}").start()

    # -- drain / rolling restart --------------------------------------------

    def drain(self, rid: str) -> None:
        """Ask one replica to stop accepting and finish in-flight work (the
        command rides the broker control hash, so `cli drain` from another
        process takes the same path)."""
        handle = self._handles.get(rid)
        if handle is not None:
            handle.drain_requested = True
        self._conn.call("HSET", FLEET_CTL_PREFIX + rid, {"state": "drain"})

    def wait_state(self, rid: str, state: str, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            hb = self._conn.call("HGET", FLEET_HB_PREFIX + rid, 0)
            if isinstance(hb, dict) and hb.get("state") == state:
                return True
            time.sleep(0.05)
        return False

    def wait_eligible(self, n: int, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if len(self.router.eligible_ids()) >= n:
                return True
            time.sleep(0.05)
        return False

    def restart_replica(self, rid: str, timeout_s: float = 30.0) -> bool:
        """One rolling-restart step: drain → stop → respawn → wait until the
        fresh incarnation is eligible again. Zero-downtime as long as the
        other replicas stay up (the router keeps dispatching to them)."""
        handle = self._handles.get(rid)
        if handle is None:
            return False
        handle.restarting = True    # monitor: hands off this lifecycle
        self.drain(rid)
        self.wait_state(rid, "drained", timeout_s=timeout_s)
        handle.stop(drain_s=2.0)
        try:
            # stragglers dispatched in the eviction race go back to the pool
            res = self._conn.call("XTRANSFER", self.router.prefix + rid,
                                  f"fleet-{rid}", self.router.stream)
            moved = int(res.get("moved", 0)) if isinstance(res, dict) else 0
            if moved:
                _REQUEUED.inc(moved)
                self.requeued += moved
        except Exception:
            logger.exception("fleet: straggler requeue for %s failed", rid)
        self._spawn_replica(rid)    # fresh handle: restarting/drain cleared
        ok = self.wait_eligible(len(self.router.replica_ids()),
                                timeout_s=timeout_s)
        logger.info("fleet: rolling-restarted replica %s (eligible=%s)",
                    rid, ok)
        return ok

    def rolling_restart(self, timeout_s: float = 60.0) -> bool:
        """Drain + restart every replica one at a time (model hot-swap /
        config rollout): at every instant N-1 replicas serve traffic."""
        ok = True
        for rid in list(self.router.replica_ids()):
            ok = self.restart_replica(rid, timeout_s=timeout_s) and ok
        return ok

    def _check_rolling(self):
        """`cli rolling-restart` writes a nonce to the rolling control hash;
        execute it once per nonce (on a side thread — the monitor loop must
        keep polling heartbeats while replicas restart)."""
        val = self._conn.call("HGET", ROLLING_KEY, 0)
        if not isinstance(val, dict) or val.get("nonce") == self._rolling_seen:
            return
        if self._rolling_busy:
            # a restart is still executing: leave the new nonce unconsumed
            # so the next poll after this run finishes picks it up (the
            # operator's command queues instead of silently vanishing)
            return
        self._rolling_seen = val.get("nonce")
        self._rolling_busy = True

        def run():
            try:
                self.rolling_restart()
            finally:
                self._rolling_busy = False

        threading.Thread(target=run, daemon=True,
                         name="zoo-fleet-rolling").start()

    # -- introspection -------------------------------------------------------

    def readiness(self) -> Tuple[bool, Dict[str, Any]]:
        """/readyz payload: ready iff >= 1 replica is eligible for dispatch
        (distinct from liveness — a fleet mid-drain is alive but not ready).
        Carries each replica's active model version and the rollout phase so
        an operator probing readiness sees a stuck rollout at a glance."""
        eligible = self.router.eligible_ids()
        detail: Dict[str, Any] = {
            "eligible": eligible,
            "replicas": self.router.replica_ids(),
            "requeued": self.requeued, "respawns": self.respawns,
            "model_versions": self.model_versions()}
        if self.autoscale_enabled:
            detail["autoscale"] = {
                "replicas": len(self._handles),
                "min": self.config.min_replicas,
                "max": self.config.max_replicas,
                "events": len(self.scale_events)}
        if self.rollout is not None:
            detail["rollout"] = self.rollout.state()
        if self._host_mode:
            detail["hosts"] = {
                hid: {"alive": s.alive, "replicas": sorted(s.replicas),
                      "clock_offset_s": round(s.clock_offset_s, 6),
                      "breaker": s.breaker.state}
                for hid, s in self._hosts.items()}
            detail["host_failovers"] = self.host_failovers
        return len(eligible) >= 1, detail

    def model_versions(self) -> Dict[str, Optional[str]]:
        """Per-replica active model version, from the heartbeat-fed slots."""
        return self.router.model_versions()

    def stats(self) -> Dict[str, Any]:
        """Aggregated engine stats + router view (feeds /metrics.json)."""
        router_stats = self.router.stats()
        out: Dict[str, Any] = {"router": router_stats,
                               "requeued": self.requeued,
                               "respawns": self.respawns,
                               "served": 0}
        if self.autoscale_enabled:
            out["autoscale"] = {"replicas": len(self._handles),
                                "events": list(self.scale_events)}
        if self.rollout is not None:
            out["rollout"] = self.rollout.state()
        if self._host_mode:
            out["hosts"] = {
                hid: {"alive": s.alive, "replicas": sorted(s.replicas),
                      "capacity": s.capacity,
                      "clock_offset_s": round(s.clock_offset_s, 6),
                      "breaker": s.breaker.state}
                for hid, s in self._hosts.items()}
            out["host_failovers"] = self.host_failovers
        slots = router_stats.get("replicas", {})
        for rid, handle in list(self._handles.items()):
            if handle.engine is not None:
                out["served"] += handle.engine.served
            else:
                # process-mode replica: no in-process engine — its served
                # counter rides the fleet:hb:<rid> heartbeat hash, polled by
                # the supervisor and cached on the router slot
                out["served"] += int(slots.get(rid, {}).get("served", 0))
        return out

    def kill_replica(self, rid: str) -> None:
        """Chaos hook: hard-kill one replica (threads stop un-acked /
        process SIGKILL). The monitor detects the silence and fails over."""
        handle = self._handles.get(rid)
        if handle is not None:
            handle.kill()

    def stop(self, drain_s: float = 5.0):
        """Ordered fleet shutdown: router first (stop claiming client
        traffic), then replicas drain + stop (in-flight work finishes and
        acks), then the monitor. Undispatched client entries stay on the
        broker for the next incarnation (AOF redelivery)."""
        if self.rollout is not None:
            self.rollout.stop()
        self.router.stop(drain_s=min(2.0, drain_s))
        if self._host_mode:
            # agents own the engines: command shutdown (they drain their
            # engines themselves), then reap whatever we manage locally
            for hid, slot in self._hosts.items():
                try:
                    self._push_host_ctl(hid, shutdown=True)
                except Exception:
                    pass
            self._stop.set()
            for slot in self._hosts.values():
                if slot.agent is not None:
                    try:
                        slot.agent.stop(drain_s=min(2.0, drain_s))
                    except Exception:
                        pass
                    slot.agent = None
                if slot.proc is not None:
                    try:
                        slot.proc.terminate()
                        slot.proc.wait(timeout=max(5.0, drain_s + 2.0))
                    except Exception:
                        try:
                            slot.proc.kill()
                        except Exception:
                            pass
                    slot.proc = None
            if self._monitor is not None:
                self._monitor.join(timeout=2.0)
                self._monitor = None
            if self._conn is not None:
                self._conn.close()
                self._conn = None
            return
        for rid, handle in list(self._handles.items()):
            if handle.engine is not None:
                handle.engine.drain()
        deadline = time.monotonic() + drain_s
        for rid, handle in list(self._handles.items()):
            if handle.engine is not None:
                while (time.monotonic() < deadline
                       and not handle.engine.drained()):
                    time.sleep(0.02)
        self._stop.set()
        for handle in list(self._handles.values()):
            handle.stop(drain_s=1.0)
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
            self._monitor = None
        if self._conn is not None:
            self._conn.close()
            self._conn = None


# ---------------------------------------------------------------------------
# subprocess replica entrypoint (fleet_spawn: process)
# ---------------------------------------------------------------------------

def main(argv=None) -> int:  # pragma: no cover - exercised as a subprocess
    ap = argparse.ArgumentParser(
        description="one fleet replica: ClusterServing consuming its own "
                    "dispatch stream, heartbeating over the broker")
    ap.add_argument("--replica", required=True, help="replica id (rN)")
    ap.add_argument("--broker-host", default="127.0.0.1")
    ap.add_argument("--broker-port", type=int, required=True)
    ap.add_argument("--config", default=None, help="ServingConfig yaml")
    ap.add_argument("--model", default=None, help="zoo model bundle path")
    ap.add_argument("--demo", action="store_true",
                    help="serve the built-in demo model")
    ap.add_argument("--platform", default=None, choices=("cpu", "tpu"))
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    cfg = (ServingConfig.from_yaml(args.config) if args.config
           else ServingConfig())
    cfg.queue_host, cfg.queue_port = args.broker_host, args.broker_port
    if args.model:
        cfg.model_path = args.model
    model = None
    if args.demo and not cfg.model_path:
        from .stack import _demo_model

        model = _demo_model()
    rid = args.replica
    engine = ClusterServing(model, config=cfg, group=f"fleet-{rid}",
                            stream=REPLICA_STREAM_PREFIX + rid,
                            replica_id=rid, dedup_results=True)
    engine.start()
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    logger.info("fleet replica %s up (stream=%s)", rid,
                REPLICA_STREAM_PREFIX + rid)
    stop.wait()
    engine.drain()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not engine.drained():
        time.sleep(0.05)
    engine.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
