"""Same-host shared-memory ring for broker↔client tensor transfer.

When both ends of a serving connection live on one host, large tensor buffers
do not need to cross the socket at all: the sender places the bytes in a
``multiprocessing.shared_memory`` segment and the binary frame (wire.py)
carries only ``(offset, nbytes)``. The segment is created by the CLIENT side
of a connection and split into two half-duplex rings:

    [0, size/2)        client writes, broker reads   (requests)
    [size/2, size)     broker writes, client reads   (results)

Negotiation: the client sends the JSON control message
``["SHMOPEN", name, size, host_identity]``; a broker that can attach AND
whose own :func:`host_identity` matches the client's replies ``"OK"`` and
both sides start placing large buffers in their ring. Any failure — remote
broker, a containerized peer with its own ``/dev/shm`` (identity mismatch),
``/dev/shm`` unavailable, an old broker answering ``{"error": ...}`` —
simply leaves the connection on the socket path (fallback-to-socket rule:
shm is an optimisation, never a requirement; see docs/serving_protocol.md).
Three-element ``SHMOPEN`` from older clients keeps the legacy attach-only
check.

Ring discipline: the serving protocol is strict request/response per
connection (the client lock serialises calls), so at most one message is in
flight per direction. Each message therefore resets its ring cursor to zero
and allocates sequentially; a buffer that does not fit in the ring falls back
to inline socket bytes (per-buffer, not per-message). No reader/writer
synchronisation is needed beyond the protocol's own alternation.
"""

from __future__ import annotations

import os
import secrets
from typing import Optional

DEFAULT_SEGMENT_BYTES = int(os.environ.get("ZOO_SERVING_SHM_BYTES",
                                           str(16 * 1024 * 1024)))
# buffers below this ride inline on the socket (header+copy cost beats a ring
# round trip for small tensors)
MIN_SHM_BUFFER_BYTES = int(os.environ.get("ZOO_SERVING_SHM_MIN_BYTES",
                                          str(64 * 1024)))


def shm_enabled() -> bool:
    return os.environ.get("ZOO_SERVING_SHM", "1") != "0"


def host_identity() -> str:
    """A token that is equal iff two processes share a kernel (and therefore
    a ``/dev/shm``). The boot id distinguishes containers and distinct
    machines even when hostnames collide (two pods both named ``localhost``);
    hostname is the fallback on kernels without it. ``ZOO_HOST_IDENTITY``
    overrides for tests and for deployments that KNOW two namespaces share an
    IPC mount."""
    env = os.environ.get("ZOO_HOST_IDENTITY")
    if env:
        return env
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        import socket

        return socket.gethostname()


def _shared_memory():
    from multiprocessing import shared_memory

    return shared_memory


# segments created by THIS process — attach() must not unregister those from
# the resource tracker (the creator's registration is the one that garbage-
# collects a leaked segment), only segments created by a peer process
_OWNED_NAMES: set = set()


class ShmChannel:
    """One end of the half-duplex ring pair inside a shared segment."""

    def __init__(self, seg, tx_base: int, tx_size: int,
                 rx_base: int, rx_size: int, owner: bool):
        self._seg = seg
        self._tx_base, self._tx_size = tx_base, tx_size
        self._rx_base, self._rx_size = rx_base, rx_size
        self._owner = owner
        self._cursor = 0
        self.min_buffer_bytes = MIN_SHM_BUFFER_BYTES

    # -- construction ---------------------------------------------------------
    @classmethod
    def create(cls, size: int = DEFAULT_SEGMENT_BYTES) -> "ShmChannel":
        """Client side: create the segment; tx = first half."""
        shared_memory = _shared_memory()
        name = f"zoo_serve_{secrets.token_hex(8)}"
        seg = shared_memory.SharedMemory(name=name, create=True, size=size)
        _OWNED_NAMES.add(seg.name)
        half = size // 2
        return cls(seg, 0, half, half, size - half, owner=True)

    @classmethod
    def attach(cls, name: str, size: int) -> "ShmChannel":
        """Broker side: attach to a client-created segment; tx = second half."""
        shared_memory = _shared_memory()
        seg = shared_memory.SharedMemory(name=name)
        # Python <3.13 registers attached segments with the resource tracker,
        # which unlinks them when THIS process exits — stealing the segment
        # from its owner. Unregister (unless WE created it in-process: then
        # the registration belongs to the creator-side unlink).
        if seg.name not in _OWNED_NAMES:
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(seg._name, "shared_memory")
            except Exception:
                pass
        half = size // 2
        return cls(seg, half, size - half, 0, half, owner=False)

    @property
    def name(self) -> str:
        return self._seg.name

    @property
    def size(self) -> int:
        return self._seg.size

    # -- ring I/O -------------------------------------------------------------
    def begin_message(self) -> None:
        """The previous message in this direction is fully consumed (protocol
        alternation guarantees it), so the whole ring is free again."""
        self._cursor = 0

    def try_write(self, mv: memoryview) -> Optional[int]:
        """Place ``mv`` in this end's tx ring; returns the absolute segment
        offset, or None when the buffer is too small to benefit or too large
        to fit (caller sends it inline)."""
        n = len(mv)
        if n < self.min_buffer_bytes or self._cursor + n > self._tx_size:
            return None
        off = self._tx_base + self._cursor
        self._seg.buf[off:off + n] = mv
        self._cursor += n
        return off

    def read(self, off: int, nbytes: int) -> memoryview:
        """View ``nbytes`` at absolute offset ``off`` (the peer's tx ring).
        The caller must copy out before its next send (wire.recv_msg does)."""
        if off < 0 or off + nbytes > self._seg.size:
            raise ValueError(f"shm read [{off}, {off + nbytes}) outside "
                             f"segment of {self._seg.size} bytes")
        return self._seg.buf[off:off + nbytes]

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        try:
            self._seg.close()
        except (OSError, BufferError):
            pass
        if self._owner:
            try:
                self._seg.unlink()
            except (OSError, FileNotFoundError):
                pass
            _OWNED_NAMES.discard(self._seg.name)
