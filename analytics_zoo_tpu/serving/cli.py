"""cluster-serving lifecycle CLI.

Parity: ``scripts/cluster-serving/cluster-serving-start|stop|restart`` in the
reference manage the Redis + Flink serving service. Here the managed process is
the queue broker (with optional append-only persistence, see broker.py); a
restart with the same ``--aof`` file recovers every acknowledged request and
re-delivers in-flight ones.

    python -m analytics_zoo_tpu.serving.cli start   --port 6380 --aof /var/zoo/serving.aof
    python -m analytics_zoo_tpu.serving.cli stop    --port 6380
    python -m analytics_zoo_tpu.serving.cli restart --port 6380 --aof /var/zoo/serving.aof
    python -m analytics_zoo_tpu.serving.cli status  --port 6380
    python -m analytics_zoo_tpu.serving.cli info    --port 6380

Fleet operations (a stack running with ``replicas > 1``, serving/fleet.py):
the commands ride broker control hashes, so they work from any host that can
reach the broker — the supervising stack process picks them up.

    python -m ... cli fleet-status     --port 6380            # roster + hb
    python -m ... cli hosts            --port 6380            # host agents
    python -m ... cli drain --replica r0 --port 6380          # graceful drain
    python -m ... cli rolling-restart  --port 6380            # zero-downtime

Observability verbs (docs/observability.md): ``events`` tails the structured
decision-event stream off the broker (autoscale/failover/rollout/breaker/
shed/chaos/slo, one JSON object per line); ``slo-status`` and ``trace`` hit
the frontend's ``/debug`` ops surface over HTTP.

    python -m ... cli events     --port 6380 [--kind autoscale] [--count 50]
    python -m ... cli slo-status --http 127.0.0.1:8080
    python -m ... cli trace      --http 127.0.0.1:8080 --trace <id> --out t.json
    python -m ... cli dump       --http 127.0.0.1:8080 --out flight.json
    python -m ... cli postmortem flight.json

``dump`` pulls the flight recorder's black-box artifact off a LIVE stack
(``/debug/flight``); ``postmortem`` pretty-prints any flight dump offline —
including one a crashed process left behind (signal/atexit hook) or one a
chaos kill auto-cut — as a timeline of decision events with SLO verdicts,
chaos firings, and the trace each decision pins.

``info`` prints the broker's data-plane gauges (wire protocol version,
per-stream depths, bytes on wire by frame kind, shm attachment) as JSON —
the operator-side view of the binary zero-copy data plane. Since the unified
telemetry layer it also carries ``aof_replayed_records`` (per-op counts of
log records replayed at the last startup), ``shm_negotiations`` (ok vs.
fallback ring attachments), and per-verb ``commands`` totals — the broker-side
slice of the shared metric registry (docs/observability.md).
"""

from __future__ import annotations

import argparse
import json
import socket
import subprocess
import sys

from ..common.resilience import ResilienceError, RetryPolicy
from .broker import recv_msg, send_msg


def _call(host: str, port: int, *req, timeout: float = 5.0):
    with socket.create_connection((host, port), timeout=timeout) as s:
        send_msg(s, list(req))
        return recv_msg(s)


def _alive(host: str, port: int) -> bool:
    try:
        return _call(host, port, "PING", timeout=2.0) == "PONG"
    except (OSError, ConnectionError, ValueError):
        return False


class _NotYet(Exception):
    """Condition not met yet (retried under a RetryPolicy deadline)."""


def _await_condition(check, wait_s: float) -> bool:
    """Poll ``check`` (raises _NotYet until satisfied) under the shared
    retry machinery: fixed 0.1s cadence, overall deadline ``wait_s``."""
    policy = RetryPolicy(max_attempts=None, base_delay_s=0.1, multiplier=1.0,
                         jitter=0.0, deadline_s=wait_s, retryable=(_NotYet,))
    try:
        policy.call(check)
        return True
    except ResilienceError:
        return False


def do_start(args) -> int:
    if _alive(args.host, args.port):
        print(f"broker already running on {args.host}:{args.port}")
        return 0
    cmd = [sys.executable, "-m", "analytics_zoo_tpu.serving.broker",
           "--host", args.host, "--port", str(args.port)]
    if args.aof:
        cmd += ["--aof", args.aof]
    proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)

    def up():
        if proc.poll() is not None:
            raise RuntimeError(f"broker exited rc={proc.returncode}")
        if not _alive(args.host, args.port):
            raise _NotYet()

    try:
        if _await_condition(up, args.wait):
            print(f"broker started on {args.host}:{args.port} (pid {proc.pid})"
                  + (f", persisting to {args.aof}" if args.aof else ""))
            return 0
    except RuntimeError as e:
        print(str(e), file=sys.stderr)
        return 1
    print("broker did not come up in time", file=sys.stderr)
    return 1


def do_stop(args) -> int:
    if not _alive(args.host, args.port):
        print(f"no broker on {args.host}:{args.port}")
        return 0
    try:
        _call(args.host, args.port, "SHUTDOWN")
    except (OSError, ConnectionError):
        pass

    def down():
        if _alive(args.host, args.port):
            raise _NotYet()

    if _await_condition(down, args.wait):
        print("broker stopped")
        return 0
    print("broker still answering after SHUTDOWN", file=sys.stderr)
    return 1


def do_restart(args) -> int:
    rc = do_stop(args)
    if rc != 0:
        return rc
    return do_start(args)


def do_status(args) -> int:
    up = _alive(args.host, args.port)
    print(f"broker on {args.host}:{args.port}: {'UP' if up else 'DOWN'}")
    return 0 if up else 3


def do_info(args) -> int:
    try:
        info = _call(args.host, args.port, "INFO")
    except (OSError, ConnectionError, ValueError) as e:
        print(f"broker on {args.host}:{args.port} unreachable: {e}",
              file=sys.stderr)
        return 3
    # hot-swap operator view: per-replica model versions + rollout phase
    # (present only when a fleet/rollout has registered on this broker)
    try:
        from .engine import FLEET_HB_PREFIX
        from .fleet import MEMBERS_KEY
        from .hotswap import ROLLOUT_KEY

        members = _call(args.host, args.port, "HGET", MEMBERS_KEY, 0)
        if isinstance(members, dict):
            versions = {}
            for rid in members.get("replicas", ()):
                hb = _call(args.host, args.port, "HGET",
                           FLEET_HB_PREFIX + rid, 0)
                if isinstance(hb, dict):
                    versions[rid] = {
                        "model_version": hb.get("model_version"),
                        "state": hb.get("state"),
                        "swap_state": hb.get("swap_state")}
            info["fleet_model_versions"] = versions
        rollout = _call(args.host, args.port, "HGET", ROLLOUT_KEY, 0)
        if isinstance(rollout, dict):
            info["rollout"] = {k: rollout.get(k) for k in
                               ("phase", "current", "target", "canary")}
    except (OSError, ConnectionError, ValueError):
        pass
    # generation operator view: the engine's source loop republishes its
    # stats hash ~1/s (GEN_STATS_PREFIX); present only when a generation
    # engine consumes from this broker
    try:
        from .generation import GEN_STATS_PREFIX

        gen = _call(args.host, args.port, "HGET",
                    GEN_STATS_PREFIX + "generation", 0)
        if isinstance(gen, dict):
            entry = {k: gen.get(k) for k in
                     ("served_streams", "active_slots", "backlog",
                      "model_version", "ts")}
            prefix = gen.get("prefix")
            if isinstance(prefix, dict):
                # shared-prefix KV cache headline: fraction of prefills
                # served (partly) from published prefix pages, plus the
                # compute + HBM those hits represent
                entry["prefix_cache"] = {k: prefix.get(k) for k in
                                         ("hit_rate", "hits", "misses",
                                          "tokens_saved", "held_pages",
                                          "budget_pages", "entries")}
            info["generation"] = entry
    except (OSError, ConnectionError, ValueError):
        pass
    print(json.dumps(info, indent=1, sort_keys=True))
    return 0


def do_fleet_status(args) -> int:
    """Roster + per-replica heartbeat view of a fleet-mode stack, including
    each replica's active model version and the rollout-controller phase —
    a stuck canary rollout is visible at a glance (one replica on the target
    version, phase != idle)."""
    from .engine import FLEET_HB_PREFIX
    from .fleet import MEMBERS_KEY
    from .hotswap import MODEL_CURRENT_KEY, ROLLOUT_KEY

    try:
        members = _call(args.host, args.port, "HGET", MEMBERS_KEY, 0)
    except (OSError, ConnectionError, ValueError) as e:
        print(f"broker on {args.host}:{args.port} unreachable: {e}",
              file=sys.stderr)
        return 3
    if not isinstance(members, dict):
        print("no fleet registered on this broker", file=sys.stderr)
        return 4
    import time

    out = {"spawn": members.get("spawn"), "replicas": {}}
    now = time.time()
    for rid in members.get("replicas", ()):
        hb = _call(args.host, args.port, "HGET", FLEET_HB_PREFIX + rid, 0)
        if isinstance(hb, dict):
            entry = {
                "state": hb.get("state"),
                "served": hb.get("served"),
                "inflight": hb.get("inflight"),
                "model_version": hb.get("model_version"),
                "swap_state": hb.get("swap_state"),
                "hb_age_s": round(now - float(hb.get("ts", 0)), 3)}
            if hb.get("swap_error"):
                entry["swap_error"] = hb["swap_error"]
            out["replicas"][rid] = entry
        else:
            out["replicas"][rid] = {"state": "no-heartbeat"}
    rollout = _call(args.host, args.port, "HGET", ROLLOUT_KEY, 0)
    if isinstance(rollout, dict):
        out["rollout"] = {k: rollout.get(k) for k in
                          ("phase", "current", "target", "canary")}
    current = _call(args.host, args.port, "HGET", MODEL_CURRENT_KEY, 0)
    if isinstance(current, dict):
        out["model_current"] = {k: current.get(k)
                                for k in ("version", "step", "path")}
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0


def do_hosts(args) -> int:
    """Host-tier view of a cross-host fleet: each registered host agent's
    heartbeat age, reported replicas, capacity, and last echoed clock
    sample — the raw evidence behind `zoo_fleet_host_clock_skew_seconds`
    and whole-host failover decisions."""
    from .fleet import MEMBERS_KEY
    from .hostagent import HOST_HB_PREFIX

    try:
        members = _call(args.host, args.port, "HGET", MEMBERS_KEY, 0)
    except (OSError, ConnectionError, ValueError) as e:
        print(f"broker on {args.host}:{args.port} unreachable: {e}",
              file=sys.stderr)
        return 3
    if not isinstance(members, dict) or not members.get("hosts"):
        print("no cross-host fleet registered on this broker",
              file=sys.stderr)
        return 4
    import time

    out = {"hosts": {}}
    now = time.time()
    for hid in members.get("hosts", ()):
        hb = _call(args.host, args.port, "HGET", HOST_HB_PREFIX + hid, 0)
        if isinstance(hb, dict):
            out["hosts"][hid] = {
                "state": hb.get("state"),
                "identity": hb.get("identity"),
                "capacity": hb.get("capacity"),
                "replicas": hb.get("replicas"),
                "pid": hb.get("pid"),
                "hb_age_s": round(now - float(hb.get("ts", 0)), 3)}
        else:
            out["hosts"][hid] = {"state": "no-heartbeat"}
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0


def do_drain(args) -> int:
    """Graceful drain of one replica: it stops claiming new requests,
    finishes + acks in-flight work, and reports state ``drained``."""
    from .engine import FLEET_CTL_PREFIX, FLEET_HB_PREFIX

    if not args.replica:
        print("drain needs --replica <id>", file=sys.stderr)
        return 2
    try:
        _call(args.host, args.port, "HSET", FLEET_CTL_PREFIX + args.replica,
              {"state": "drain"})
    except (OSError, ConnectionError, ValueError) as e:
        print(f"broker unreachable: {e}", file=sys.stderr)
        return 3

    def drained():
        hb = _call(args.host, args.port, "HGET",
                   FLEET_HB_PREFIX + args.replica, 0)
        if not (isinstance(hb, dict) and hb.get("state") == "drained"):
            raise _NotYet()

    if _await_condition(drained, args.wait):
        print(f"replica {args.replica} drained")
        return 0
    print(f"replica {args.replica} not drained after {args.wait}s "
          f"(still finishing in-flight work?)", file=sys.stderr)
    return 1


def do_events(args) -> int:
    """Print the stack's structured decision events (autoscale, failover,
    rollout, breaker, shed, chaos, slo transitions) from the broker's
    ``events`` stream — the cross-process view of ``/debug/events``. One
    JSON object per line, oldest first."""
    from ..observability.events import EVENT_STREAM

    cursor, rows = 0, []
    limit = max(1, int(args.count))
    try:
        while True:
            cursor, entries = _call(args.host, args.port, "XREAD",
                                    EVENT_STREAM, cursor, 256, 0)
            if not entries:
                break
            for _id, rec in entries:
                if args.kind and not str(rec.get("kind", "")) \
                        .startswith(args.kind):
                    continue
                rows.append(rec)
    except (OSError, ConnectionError, ValueError) as e:
        print(f"broker on {args.host}:{args.port} unreachable: {e}",
              file=sys.stderr)
        return 3
    for rec in rows[-limit:]:
        print(json.dumps(rec, sort_keys=True))
    if not rows:
        print("no decision events on this broker (stack not running with "
              "the observability plane, or nothing has happened yet)",
              file=sys.stderr)
    return 0


def _http_get(http: str, path: str, timeout: float = 5.0):
    import urllib.request

    url = f"http://{http}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def do_slo_status(args) -> int:
    """Print the SLO engine's status (objectives, burn rates, alert states)
    from the frontend's ``/debug/slo``."""
    try:
        payload = _http_get(args.http, "/debug/slo")
    except Exception as e:
        print(f"frontend on {args.http} unreachable: {e}", file=sys.stderr)
        return 3
    print(json.dumps(payload, indent=1, sort_keys=True))
    if not payload.get("enabled"):
        return 4
    return 1 if payload.get("firing") else 0


def do_rowcache(args) -> int:
    """Print host hot-row cache stats (per-tier hit rates, pinned rows,
    host/device bytes) from the frontend's ``/debug/rowcache``."""
    try:
        payload = _http_get(args.http, "/debug/rowcache")
    except Exception as e:
        print(f"frontend on {args.http} unreachable: {e}", file=sys.stderr)
        return 3
    print(json.dumps(payload, indent=1, sort_keys=True))
    return 0 if payload.get("caches") else 4


def do_trace(args) -> int:
    """Fetch one trace as Chrome/Perfetto trace-event JSON from the
    frontend's ``/debug/traces/<id>`` (load the file at ui.perfetto.dev)."""
    if not args.trace:
        print("trace needs --trace <trace_id> (see /debug/events or "
              "`cli events` for ids)", file=sys.stderr)
        return 2
    try:
        payload = _http_get(args.http, f"/debug/traces/{args.trace}")
    except Exception as e:
        print(f"frontend on {args.http} unreachable or unknown trace: {e}",
              file=sys.stderr)
        return 3
    text = json.dumps(payload, indent=1)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {len(payload.get('traceEvents', []))} span(s) to "
              f"{args.out}")
    else:
        print(text)
    return 0


def do_dump(args) -> int:
    """Pull a complete flight-recorder dump from the frontend's
    ``/debug/flight`` and write it to disk — the black-box artifact for a
    live stack, on operator request."""
    import time

    try:
        payload = _http_get(args.http, "/debug/flight", timeout=15.0)
    except Exception as e:
        print(f"frontend on {args.http} unreachable or no flight recorder "
              f"installed: {e}", file=sys.stderr)
        return 3
    if payload.get("schema") != "zoo-flight-v1":
        print(f"unexpected flight payload: {payload.get('error', payload)}",
              file=sys.stderr)
        return 1
    out = args.out or f"flight-{int(time.time())}.json"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
    print(f"wrote flight dump to {out} ({payload.get('records_held', 0)} "
          f"control records, {len(payload.get('events') or [])} events, "
          f"trigger={payload.get('trigger')})")
    return 0


def do_postmortem(args) -> int:
    """Pretty-print a flight dump offline: header, SLO verdicts, chaos
    firings, decision-record summary, and a merged timeline of the decision
    events with the trace each one pins (marked when the dump carries the
    full trace export)."""
    if not args.target:
        print("postmortem needs a dump file: cli postmortem <dump.json>",
              file=sys.stderr)
        return 2
    try:
        with open(args.target, encoding="utf-8") as f:
            dump = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot load {args.target}: {e}", file=sys.stderr)
        return 1
    if not isinstance(dump, dict) or dump.get("schema") != "zoo-flight-v1":
        print(f"{args.target} is not a zoo-flight-v1 dump", file=sys.stderr)
        return 1
    import time

    created = float(dump.get("created", 0.0))
    print(f"flight dump {args.target}")
    print(f"  schema   {dump['schema']}   trigger {dump.get('trigger')}")
    print(f"  cut      {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(created))}"
          f"   host {dump.get('host')}   pid {dump.get('pid')}")
    print(f"  records  {dump.get('records_held', 0)} held / "
          f"{dump.get('records_total', 0)} total "
          f"({dump.get('records_dropped', 0)} overwritten)")
    slo = dump.get("slo")
    if isinstance(slo, dict) and slo.get("objectives"):
        print("SLO verdicts:")
        for o in slo["objectives"]:
            print(f"  {o.get('name'):<28} {o.get('state'):<9} "
                  f"burn fast {o.get('burn_fast')} / slow "
                  f"{o.get('burn_slow')}  fired {o.get('fired_count')}x")
    chaos = dump.get("chaos") or []
    if chaos:
        print("chaos firings:")
        for c in chaos:
            print(f"  {c.get('site')}[{c.get('tag')}] x{c.get('fired')}")
    sites = {}
    for r in dump.get("records") or []:
        d = r.get("decision") or {}
        key = (r.get("site"), d.get("action"))
        sites[key] = sites.get(key, 0) + 1
    if sites:
        print("decision records:")
        for (site, action), n in sorted(sites.items(),
                                        key=lambda kv: str(kv[0])):
            print(f"  {site:<24} {str(action):<10} x{n}")
    events = dump.get("events") or []
    traces = dump.get("traces") or {}
    if events:
        t0 = float(events[0].get("ts", created))
        print(f"timeline ({len(events)} events):")
        for e in events:
            tid = e.get("trace_id")
            pin = ""
            if tid:
                pin = (f"  [trace {tid[:12]}"
                       + (", exported]" if tid in traces else "]"))
            fields = {k: v for k, v in (e.get("fields") or {}).items()}
            print(f"  +{float(e.get('ts', t0)) - t0:8.3f}s "
                  f"{e.get('severity', 'info'):<8} {e.get('kind'):<22} "
                  f"{json.dumps(fields, sort_keys=True, default=str)}{pin}")
    print(f"exported traces: {len(traces)}")
    return 0


def do_rolling_restart(args) -> int:
    """Ask the fleet supervisor for a rolling restart: each replica is
    drained, restarted and readmitted in turn — N-1 replicas keep serving
    at every instant (zero downtime)."""
    import uuid

    from .fleet import ROLLING_KEY

    try:
        _call(args.host, args.port, "HSET", ROLLING_KEY,
              {"nonce": uuid.uuid4().hex})
    except (OSError, ConnectionError, ValueError) as e:
        print(f"broker unreachable: {e}", file=sys.stderr)
        return 3
    print("rolling restart requested (watch `cli fleet-status`)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="cluster-serving lifecycle (start/stop/restart/status) "
                    "+ fleet operations (fleet-status/drain/rolling-restart)")
    ap.add_argument("action",
                    choices=["start", "stop", "restart", "status", "info",
                             "fleet-status", "hosts", "drain",
                             "rolling-restart", "events", "slo-status",
                             "rowcache", "trace", "dump", "postmortem"])
    ap.add_argument("target", nargs="?", default=None,
                    help="`postmortem`: path to a flight dump JSON "
                         "(from `cli dump`, /debug/flight, or a crash)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6380)
    ap.add_argument("--aof", default=None,
                    help="append-only persistence file (start/restart)")
    ap.add_argument("--replica", default=None,
                    help="replica id for `drain` (see fleet-status)")
    ap.add_argument("--wait", type=float, default=10.0,
                    help="seconds to wait for start/stop/drain to take effect")
    ap.add_argument("--http", default="127.0.0.1:8080",
                    help="frontend host:port for `slo-status`/`trace` "
                         "(the /debug ops surface)")
    ap.add_argument("--count", type=int, default=100,
                    help="`events`: print at most the newest N events")
    ap.add_argument("--kind", default=None,
                    help="`events`: only kinds with this prefix (e.g. "
                         "autoscale, fleet, rollout, slo, chaos)")
    ap.add_argument("--trace", default=None,
                    help="`trace`: the trace id to export (from "
                         "/debug/events or `cli events`)")
    ap.add_argument("--out", default=None,
                    help="`trace`: write the Perfetto-loadable JSON here "
                         "instead of stdout; `dump`: the flight dump path "
                         "(default flight-<ts>.json)")
    args = ap.parse_args(argv)
    return {"start": do_start, "stop": do_stop, "restart": do_restart,
            "status": do_status, "info": do_info,
            "fleet-status": do_fleet_status, "hosts": do_hosts,
            "drain": do_drain,
            "rolling-restart": do_rolling_restart, "events": do_events,
            "slo-status": do_slo_status, "rowcache": do_rowcache,
            "trace": do_trace,
            "dump": do_dump,
            "postmortem": do_postmortem}[args.action](args)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
