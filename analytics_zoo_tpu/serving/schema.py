"""Payload encoding helpers for queue transport and persistence.

Parity: /root/reference/pyzoo/zoo/serving/client.py:99-181 — the reference
serialises ndarrays/images to Arrow record batches then base64 for Redis.

The serving HOT PATH no longer goes through this module: tensors ride the
binary zero-copy frame protocol (wire.py) as raw buffers. What remains here:

* the legacy base64-JSON ndarray codec (``encode_payload``/``decode_payload``)
  — still accepted from old/JSON-only clients, and ``decode_payload`` passes
  already-decoded ndarrays (binary-frame payloads) straight through, so one
  decode call serves both wire generations;
* the append-only-file bridge (``json_default``/``json_revive``): the broker's
  AOF is line-JSON for greppability and torn-write tolerance, so ndarray
  payloads from binary frames are tagged ``{"__zoond__": <npy b64>}`` on the
  way to disk and revived to real ndarrays on replay — binary-frame requests
  survive a broker crash bit-exactly.
"""

from __future__ import annotations

import base64
import io
from typing import Any, Dict, Optional

import numpy as np

# Trace-context field carried INSIDE request/result payload dicts (the JSON
# control-plane twin of the binary frame header's "c" field): a plain
# ``{"t": trace_id, "s": span_id}`` dict, JSON- and AOF-serializable, ignored
# by peers that predate it — interop never depends on its presence.
TRACE_KEY = "trace"

# Serving-model-version field carried inside RESULT payload dicts (the
# durable twin of the binary frame header's "v" field): the version id of
# the hot-swappable model that produced the result (serving/hotswap.py),
# stamped by the engine sink, surviving the broker hash + AOF replay to the
# client. Absent from pre-hot-swap engines — consumers must tolerate that.
MODEL_VERSION_KEY = "model_version"

# Overload QoS fields carried inside REQUEST payload dicts (the durable
# twins of the binary frame header's "p"/"dl" fields — serving/qos.py):
# ``priority`` is one of critical/normal/bulk, ``deadline`` an absolute
# wall-clock epoch-seconds float. Both survive the broker stream, AOF
# replay, and XTRANSFER failover requeues — a requeued request keeps its
# ORIGINAL deadline (and is shed, not served, if it expired in flight).
# Old clients omit them; every consumer tolerates absence.
PRIORITY_KEY = "priority"
DEADLINE_KEY = "deadline"


def payload_priority(payload: Any) -> str:
    """Tolerant read of a request payload's priority class (``normal``
    when absent/malformed — old-client records stay first-class)."""
    from .qos import normalize_priority

    if isinstance(payload, dict):
        return normalize_priority(payload.get(PRIORITY_KEY))
    return normalize_priority(None)


def payload_deadline(payload: Any) -> Optional[float]:
    """Tolerant read of a request payload's absolute deadline (epoch
    seconds; ``None`` when absent/malformed)."""
    from .qos import normalize_deadline

    if isinstance(payload, dict):
        return normalize_deadline(payload.get(DEADLINE_KEY))
    return None


def payload_model_version(payload: Any) -> Optional[str]:
    """Tolerant read of a result payload's serving model version."""
    if isinstance(payload, dict):
        v = payload.get(MODEL_VERSION_KEY)
        if isinstance(v, str) and v:
            return v
    return None


def payload_trace(payload: Any) -> Optional[Dict[str, str]]:
    """Tolerant read of a payload dict's trace context (``None`` when absent
    or malformed — e.g. a record enqueued by an old client). Validation is
    delegated to ``TraceContext.from_wire`` so the payload field and the
    frame-header field accept exactly the same shapes."""
    if isinstance(payload, dict):
        from ..common.telemetry import TraceContext

        ctx = payload.get(TRACE_KEY)
        if TraceContext.from_wire(ctx) is not None:
            return ctx
    return None


def encode_ndarray(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_ndarray(s: str) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(s.encode("ascii"))),
                   allow_pickle=False)


def encode_payload(data: Dict[str, Any]) -> Dict[str, Any]:
    """ndarrays → tagged base64; scalars/strings pass through. Legacy wire
    format — the binary frame path (wire.py) sends raw arrays instead."""
    out: Dict[str, Any] = {}
    for k, v in data.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": encode_ndarray(v)}
        elif isinstance(v, (list, tuple)) and v and \
                all(isinstance(x, np.ndarray) for x in v):
            out[k] = {"__ndarray_list__": [encode_ndarray(x) for x in v]}
        else:
            out[k] = v
    return out


def decode_payload(data: Dict[str, Any]) -> Dict[str, Any]:
    """Decode a payload dict from EITHER wire generation: legacy tagged-base64
    values are decoded; raw ndarrays (binary frames) pass through untouched."""
    out: Dict[str, Any] = {}
    for k, v in data.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = decode_ndarray(v["__ndarray__"])
        elif isinstance(v, dict) and "__ndarray_list__" in v:
            out[k] = [decode_ndarray(x) for x in v["__ndarray_list__"]]
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# AOF bridge: ndarray-bearing payloads <-> line-JSON mutation records
# ---------------------------------------------------------------------------

_AOF_TAG = "__zoond__"


def json_default(o: Any):
    """``json.dumps(..., default=json_default)`` hook: tag raw ndarrays (from
    binary frames) so they survive the broker's line-JSON append-only log.
    Dtype rides by NAME (not npy) so custom dtypes — bf16/fp8 via ml_dtypes —
    replay bit-exact instead of degrading to raw void records."""
    if isinstance(o, (np.ndarray, np.generic)):
        arr = np.asarray(o)                 # keeps 0-d shape
        if isinstance(arr, np.ndarray) and not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        return {_AOF_TAG: [arr.dtype.name, list(arr.shape),
                           base64.b64encode(arr.tobytes()).decode("ascii")]}
    raise TypeError(f"Object of type {type(o).__name__} is not JSON "
                    f"serializable")


def json_revive(obj: Any) -> Any:
    """Inverse of :func:`json_default`, applied recursively to a replayed AOF
    record. Legacy ``__ndarray__``-tagged dicts are left alone — they are the
    payload a JSON-generation consumer expects to see."""
    if isinstance(obj, dict):
        if len(obj) == 1 and _AOF_TAG in obj:
            from .wire import _dtype_from_name

            name, shape, b64 = obj[_AOF_TAG]
            raw = bytearray(base64.b64decode(b64.encode("ascii")))
            return np.frombuffer(raw, dtype=_dtype_from_name(name)).reshape(
                tuple(shape))
        return {k: json_revive(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [json_revive(v) for v in obj]
    return obj
