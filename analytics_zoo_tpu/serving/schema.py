"""Payload encoding for queue transport.

Parity: /root/reference/pyzoo/zoo/serving/client.py:99-181 — the reference
serialises ndarrays/images to Arrow record batches then base64 for Redis.
Here tensors ride as raw ``.npy`` bytes (dtype+shape self-describing) base64'd
into the JSON envelope — same wire-safety property, zero extra deps.
"""

from __future__ import annotations

import base64
import io
from typing import Any, Dict

import numpy as np


def encode_ndarray(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_ndarray(s: str) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(s.encode("ascii"))),
                   allow_pickle=False)


def encode_payload(data: Dict[str, Any]) -> Dict[str, Any]:
    """ndarrays → tagged base64; scalars/strings pass through."""
    out: Dict[str, Any] = {}
    for k, v in data.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": encode_ndarray(v)}
        elif isinstance(v, (list, tuple)) and v and \
                all(isinstance(x, np.ndarray) for x in v):
            out[k] = {"__ndarray_list__": [encode_ndarray(x) for x in v]}
        else:
            out[k] = v
    return out


def decode_payload(data: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in data.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = decode_ndarray(v["__ndarray__"])
        elif isinstance(v, dict) and "__ndarray_list__" in v:
            out[k] = [decode_ndarray(x) for x in v["__ndarray_list__"]]
        else:
            out[k] = v
    return out
