"""QueueBroker — a self-contained stream broker (the Redis-streams equivalent).

Parity: the reference fronts serving with Redis: clients ``XADD`` requests onto a
stream, the Flink source consumes via a consumer group (``xgroupCreate`` +
``xreadGroup`` — /root/reference/zoo/.../serving/engine/FlinkRedisSource.scala:
44-59), and results land in per-request hashes read by ``OutputQueue``
(client.py:277-300). This broker provides exactly those primitives over the
versioned wire protocol of wire.py — tensor-bearing payloads ride binary
zero-copy frames (raw buffers read with ``recv_into``, optionally through a
negotiated same-host shared-memory ring), control messages stay
length-prefixed JSON, and both interoperate on one connection
(docs/serving_protocol.md):

    XADD stream payload              -> id
    XREADGROUP stream group n block  -> [(id, payload), ...]   (each entry to ONE consumer)
    HSET key mapping / HGET key / HDEL key
    LEN stream / PING / SHUTDOWN / INFO
    SHMOPEN name size                -> "OK"    (same-host zero-copy rings)

It runs in-process (``start_broker()`` returns a served port) or standalone
(``python -m analytics_zoo_tpu.serving.broker --port 6380``).

Durability (the reference's Redis-persistence + consumer-group recovery story —
FlinkRedisSource.scala:44-59 resumes its group cursor after a job restart, and
``scripts/cluster-serving/cluster-serving-restart`` bounces the service): pass
``aof_path`` and every mutation is appended as a JSON line and fsync'd before
the client sees the ack. On startup the log is replayed, so acknowledged
requests and results survive a broker kill. Delivered-but-unacknowledged
entries (tracked in a per-group pending list, Redis PEL semantics — consumers
``XACK`` after writing results) are re-delivered ahead of new traffic after a
crash restart. ``python -m analytics_zoo_tpu.serving.cli restart`` is the
cluster-serving-restart equivalent.
"""

from __future__ import annotations

import argparse
import collections
import contextlib
import json
import os
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common import telemetry as _tm
from ..common.locks import traced_lock
from .schema import (DEADLINE_KEY, MODEL_VERSION_KEY, PRIORITY_KEY,
                     json_default, json_revive, payload_trace)
# wire-protocol primitives live in wire.py; re-exported here because the
# historical import surface for the framing helpers is this module
from .wire import (MAX_MSG, VERSION as WIRE_VERSION,  # noqa: F401
                   _recv_exact, received_model_version, received_qos,
                   received_trace_context, recv_msg, send_msg,
                   set_wire_model_version, wire_stats)

_KNOWN_CMDS = frozenset({"XADD", "XGROUPCREATE", "XREADGROUP", "XREAD",
                         "XLAST", "XDELSTREAM", "XTRANSFER", "XACK", "HSET",
                         "HSETNX", "HGET", "HDEL", "LEN", "PING", "SHMOPEN",
                         "INFO", "SHUTDOWN"})
# unknown verbs collapse to one label value: client-supplied strings must not
# mint unbounded counter children in the process-wide registry
_CMDS = _tm.counter("zoo_broker_commands_total",
                    "Broker commands handled, by verb", labels=("cmd",))
_AOF_REPLAYED = _tm.counter(
    "zoo_broker_aof_replayed_records_total",
    "AOF records replayed at broker startup, by record op", labels=("op",))
_SHM_NEG = _tm.counter(
    "zoo_broker_shm_negotiations_total",
    "SHMOPEN ring negotiations, by outcome (fallback/denied = connection "
    "stays socket-only; denied = host-identity mismatch, a cross-host or "
    "containerized peer)", labels=("outcome",))
_AOF_COMPACT = _tm.counter(
    "zoo_broker_aof_compactions_total",
    "AOF compactions (live-state rewrite + atomic rename) triggered by the "
    "op-count or size threshold after startup")
_DUP_DROPPED = _tm.counter(
    "zoo_fleet_duplicate_results_total",
    "HSETNX writes dropped because the key was already answered (a slow-not-"
    "dead replica double-answering a requeued request)")


class _Store:
    """Streams (bounded lists w/ per-group cursors) + hashes, one lock.

    Streams are trimmed like Redis ``XADD MAXLEN ~``: beyond ``maxlen`` entries
    the oldest are dropped and every group cursor shifts accordingly, so a
    long-running deployment holds bounded memory.
    """

    ANSWERED_MAXLEN = 65536   # dedup-tombstone LRU bound (see hsetnx)

    def __init__(self, maxlen: int = 65536, aof_path: Optional[str] = None,
                 reclaim_idle_ms: int = 60_000,
                 aof_rewrite_min_bytes: int = 64 << 20):
        # every store structure mutates under the condition below (over this
        # lock); _log/fsync-under-lock is the durability contract (fsync
        # before the client sees the ack)
        # zoo-lock: guards(streams, cursors, hashes, pending)
        # zoo-lock: guards(redeliver, deliveries, trimmed, _answered)
        self.lock = traced_lock("_Store.lock")
        self.cond = threading.Condition(self.lock)
        self.maxlen = maxlen
        # size-triggered compaction floor: once the log grows past this, the
        # next mutation rewrites live state to a fresh file (long-running
        # fleet brokers must not replay days of dead records on restart)
        self.aof_rewrite_min_bytes = aof_rewrite_min_bytes
        # delivered entries idle (unacked) past this are re-delivered to the
        # next reader — XAUTOCLAIM semantics, so a consumer that died with
        # in-flight work doesn't strand it until a broker restart
        self.reclaim_idle_ms = reclaim_idle_ms
        self.streams: Dict[str, List[Tuple[str, Any]]] = collections.defaultdict(list)
        self.cursors: Dict[Tuple[str, str], int] = collections.defaultdict(int)
        self.trimmed: Dict[str, int] = collections.defaultdict(int)
        self.hashes: Dict[str, Any] = {}
        self._seq = 0
        # PEL: delivered-but-unacked entries per (stream, group); ``redeliver``
        # holds entries recovered from the log at startup — served before the
        # cursor so a crash never drops an accepted request
        self.pending: Dict[Tuple[str, str], Dict[str, Any]] = \
            collections.defaultdict(dict)
        self.redeliver: Dict[Tuple[str, str], List[Tuple[str, Any]]] = \
            collections.defaultdict(list)
        # per-request delivery counts for delivered-but-unacked entries
        # (XAUTOCLAIM/XPENDING parity: the fleet requeue verb reports how
        # often each transferred request was already handed out). In-memory
        # only — a broker restart resets counts, redelivery itself is what
        # the AOF "R" records guarantee.
        self.deliveries: Dict[Tuple[str, str], Dict[str, int]] = \
            collections.defaultdict(dict)
        # first-write-wins tombstones for HSETNX: keys ever written (even if
        # HDEL'd since) stay "answered" while inside this bounded LRU, so a
        # slow-not-dead replica's late duplicate result is dropped instead of
        # recreating a hash the client already consumed
        self._answered: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        self.compactions = 0      # post-startup AOF rewrites (INFO)
        self._aof = None
        self._aof_path = aof_path
        self._ops_since_rewrite = 0
        self._aof_base_bytes = 0  # snapshot size after the last rewrite
        # replay visibility: counts by record op, surfaced by INFO/`cli info`
        # and mirrored into the shared metric registry
        self.replayed: Dict[str, int] = {}
        if aof_path:
            if os.path.exists(aof_path):
                self._replay(aof_path)
            # compact at startup: replaying history re-runs every trim ever
            # applied; the snapshot keeps restart time bounded by LIVE state
            self._rewrite_locked(startup=True)

    # -- append-only log ------------------------------------------------------
    REWRITE_EVERY_OPS = 200_000

    def _log(self, *rec: Any) -> None:
        """Append one mutation; fsync before the caller acks the client.
        Binary-frame payloads carry raw ndarrays — ``json_default`` tags them
        so they ride the line-JSON log (revived bit-exact on replay)."""
        if self._aof is not None:
            self._aof.write(json.dumps(list(rec), default=json_default) + "\n")
            self._aof.flush()
            os.fsync(self._aof.fileno())
            self._ops_since_rewrite += 1
            # two triggers: op count (bounded replay work) and byte size
            # (bounded disk + restart time for fleet brokers whose dead
            # XDELSTREAM'd records dominate the log). The size trigger is
            # the min-bytes floor AND 2x the post-rewrite snapshot size
            # (Redis auto-aof-rewrite-percentage analog): live state bigger
            # than the floor must not make EVERY op pay a full synchronous
            # rewrite — the log has to actually grow past the snapshot
            if (self._ops_since_rewrite >= self.REWRITE_EVERY_OPS
                    or self._aof.tell() >= max(self.aof_rewrite_min_bytes,
                                               2 * self._aof_base_bytes)):
                self._rewrite_locked()

    def _rewrite_locked(self, startup: bool = False) -> None:
        """Snapshot live state into a fresh log and atomically swap it in
        (Redis BGREWRITEAOF analog, done inline — live state is bounded by
        ``maxlen`` so the rewrite is cheap). Caller holds the lock, or is the
        constructor."""
        if self._aof_path is None:
            return
        tmp = self._aof_path + ".rewrite"
        with open(tmp, "w", encoding="utf-8") as f:
            for stream, entries in self.streams.items():
                # delivered-but-unacked entries already trimmed out of the live
                # window keep their payload in the pending map; persist them as
                # "P" payload-only records (NOT appends — appending them would
                # change stream indices and misalign group cursors if maxlen
                # differs on the next start) so redelivery survives the rewrite
                live = {i for i, _ in entries}
                ghost: Dict[str, Any] = {}
                for (s, _g), ents in self.pending.items():
                    if s == stream:
                        for i, (payload, _ts) in ents.items():
                            if i not in live:
                                ghost[i] = payload
                for i in sorted(ghost, key=lambda e: int(e.split("-")[0])):
                    f.write(json.dumps(["P", stream, i, ghost[i]],
                                       default=json_default) + "\n")
                for entry_id, payload in entries:
                    f.write(json.dumps(["A", stream, entry_id, payload],
                                       default=json_default) + "\n")
            for (stream, group), cur in self.cursors.items():
                f.write(json.dumps(["G", stream, group, 0]) + "\n")
                f.write(json.dumps(["R", stream, group, cur, []]) + "\n")
            for (stream, group), ents in self.pending.items():
                if ents:
                    f.write(json.dumps(["R", stream, group,
                                        self.cursors[(stream, group)],
                                        list(ents)]) + "\n")
            for key, mapping in self.hashes.items():
                f.write(json.dumps(["H", key, mapping],
                                   default=json_default) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if self._aof is not None:
            self._aof.close()
        os.replace(tmp, self._aof_path)
        self._aof = open(self._aof_path, "a", encoding="utf-8")
        self._ops_since_rewrite = 0
        self._aof_base_bytes = self._aof.tell()
        if not startup:   # the startup snapshot is bookkeeping, not a
            self.compactions += 1            # traffic-triggered compaction
            _AOF_COMPACT.inc()

    def _replay(self, path: str) -> None:
        # payloads of replayed appends still possibly needed for redelivery,
        # keyed by id — the live stream trims to maxlen, but a delivered-but-
        # unacked entry must keep its payload even after it overflows out of
        # the stream. Acked ids are pruned (bounding replay memory by the
        # unacked set, not the whole inter-rewrite log); a later lookup for a
        # pruned id falls back to the live stream.
        all_payloads: Dict[str, Dict[str, Any]] = collections.defaultdict(dict)
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json_revive(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn final write from the crash: ignore
                op = rec[0]
                self.replayed[op] = self.replayed.get(op, 0) + 1
                _AOF_REPLAYED.labels(op=op).inc()
                if op == "A":
                    _, stream, entry_id, payload = rec
                    all_payloads[stream][entry_id] = payload
                    self._append(stream, entry_id, payload)
                    self._seq = max(self._seq, int(entry_id.split("-")[0]))
                elif op == "G":
                    self.cursors.setdefault((rec[1], rec[2]), rec[3])
                elif op == "R":
                    _, stream, group, new_cursor, ids = rec
                    key = (stream, group)
                    self.cursors[key] = new_cursor
                    by_id = all_payloads[stream]
                    live_by_id = None
                    for i in ids:
                        payload = by_id.get(i)
                        if payload is None and i not in by_id:
                            # pruned after an earlier ack but still live in
                            # the stream (another group reading it)
                            if live_by_id is None:
                                live_by_id = dict(self.streams[stream])
                            if i not in live_by_id:
                                continue
                            payload = live_by_id[i]
                        # fresh timestamp: the redeliver list below makes
                        # the first post-restart delivery; a stale ts would
                        # ALSO trip the idle-reclaim scan = double delivery
                        self.pending[key][i] = (payload, time.monotonic())
                elif op == "K":
                    _, stream, group, ids = rec
                    key = (stream, group)
                    for i in ids:
                        self.pending[key].pop(i, None)
                        # prune unless another group still holds it pending
                        if not any(i in ents for (s, g), ents
                                   in self.pending.items()
                                   if s == stream and (s, g) != key):
                            all_payloads[stream].pop(i, None)
                elif op == "P":
                    _, stream, entry_id, payload = rec
                    all_payloads[stream][entry_id] = payload
                elif op == "S":
                    stream = rec[1]
                    self.streams.pop(stream, None)
                    self.trimmed.pop(stream, None)
                    all_payloads.pop(stream, None)
                    for key in [k for k in self.cursors if k[0] == stream]:
                        del self.cursors[key]
                    for key in [k for k in self.pending if k[0] == stream]:
                        del self.pending[key]
                elif op == "H":
                    self.hashes[rec[1]] = rec[2]
                    # replayed writes re-arm the dedup tombstone: a duplicate
                    # result arriving after a broker restart is still dropped
                    self._mark_answered(rec[1])
                elif op == "D":
                    self.hashes.pop(rec[1], None)
        # anything still pending was in flight when the broker died: schedule
        # redelivery ahead of new traffic (Redis XAUTOCLAIM-on-restart analog)
        for key, ents in self.pending.items():
            if ents:
                self.redeliver[key] = [
                    (i, payload) for i, (payload, _ts) in sorted(
                        ents.items(), key=lambda kv: int(kv[0].split("-")[0]))]

    def _append(self, stream: str, entry_id: str, payload: Any) -> None:
        entries = self.streams[stream]
        entries.append((entry_id, payload))
        overflow = len(entries) - self.maxlen
        if overflow > 0:
            del entries[:overflow]
            self.trimmed[stream] += overflow
            for key in self.cursors:
                if key[0] == stream:
                    self.cursors[key] = max(0, self.cursors[key] - overflow)

    def xadd(self, stream: str, payload: Any) -> str:
        with self.cond:
            self._seq += 1
            entry_id = f"{self._seq}-0"
            self._append(stream, entry_id, payload)
            self._log("A", stream, entry_id, payload)
            self.cond.notify_all()
            return entry_id

    def xgroupcreate(self, stream: str, group: str, start: str = "$") -> None:
        """Register a consumer group. ``start='$'`` = only entries added after
        this call (Redis tail semantics); ``'0'`` = replay from the beginning.
        No-op when the group exists (cursor preserved across job restarts)."""
        with self.cond:
            key = (stream, group)
            if key not in self.cursors:
                self.cursors[key] = (len(self.streams[stream])
                                     if start == "$" else 0)
                self._log("G", stream, group, self.cursors[key])

    def xreadgroup(self, stream: str, group: str, count: int,
                   block_ms: int) -> List[Tuple[str, Any]]:
        deadline = None if block_ms <= 0 else block_ms / 1e3
        with self.cond:
            key = (stream, group)
            now = time.monotonic()
            out: List[Tuple[str, Any]] = []
            # crash-recovered in-flight entries first (stay pending until XACK)
            redo = self.redeliver.get(key)
            if redo:
                out.extend(redo[:count])
                del redo[:len(out)]
            # then idle unacked entries from a dead/stalled consumer
            # (XAUTOCLAIM semantics)
            if len(out) < count and self.reclaim_idle_ms:
                taken = {i for i, _ in out}
                for i, (payload, ts) in self.pending[key].items():
                    if len(out) >= count:
                        break
                    # `taken` guards replay double-entries: an entry served
                    # from the redeliver queue above is still in pending with
                    # its pre-serve timestamp until this call commits, so the
                    # idle scan could otherwise pick it a second time
                    if i not in taken and (now - ts) * 1e3 >= self.reclaim_idle_ms:
                        out.append((i, payload))
                        taken.add(i)

            def fresh():
                return len(self.streams[stream]) - self.cursors[key]

            if not out and fresh() == 0 and deadline:
                self.cond.wait(timeout=deadline)
            take = min(count - len(out), fresh())
            if take > 0:
                start = self.cursors[key]
                self.cursors[key] = start + take
                out.extend(self.streams[stream][start:start + take])
            if out:
                dv = self.deliveries[key]
                for i, payload in out:
                    self.pending[key][i] = (payload, now)
                    dv[i] = dv.get(i, 0) + 1
                self._log("R", stream, group, self.cursors[key],
                          [i for i, _ in out])
            return out

    def xread(self, stream: str, cursor: int, count: int,
              block_ms: int) -> Tuple[int, List[Tuple[str, Any]]]:
        """Plain cursor read (no group, no pending-entry tracking): entries
        after absolute index ``cursor``, blocking up to ``block_ms`` for new
        ones. The generation streaming path fans token-delta frames out with
        this — every reader sees every frame, cursors are client-state, and
        nothing is logged (reads mutate nothing). ``cursor`` is an absolute
        per-stream index (monotonic across trims); returns
        ``(next_cursor, entries)``."""
        deadline = None if block_ms <= 0 else block_ms / 1e3
        with self.cond:
            cursor = max(int(cursor), 0)

            # .get()-based reads: polling a not-yet-written (or deleted)
            # stream must not mint defaultdict entries that outlive it
            def avail() -> int:
                return (self.trimmed.get(stream, 0)
                        + len(self.streams.get(stream, ())) - cursor)

            if avail() <= 0 and deadline:
                self.cond.wait_for(lambda: avail() > 0, timeout=deadline)
            # entries the cursor points at that were already trimmed away are
            # skipped (the reader was too slow for the retention window)
            trimmed = self.trimmed.get(stream, 0)
            start = max(0, cursor - trimmed)
            out = self.streams.get(stream, [])[start:start + count]
            next_cursor = trimmed + start + len(out)
            return next_cursor, list(out)

    def xlast(self, stream: str) -> Optional[Tuple[str, Any]]:
        """The newest live entry of ``stream`` (or None). The catch-up peek
        for tail ('$') consumer groups: a model-update subscriber starting
        after the trainer already published sees the LATEST version without
        replaying (and re-deploying) the whole publish history."""
        with self.cond:
            entries = self.streams.get(stream)
            return tuple(entries[-1]) if entries else None

    def sdel(self, stream: str) -> None:
        """Delete a whole stream and every per-group cursor/pending record
        attached to it (the generation path's per-request ``genout:*``
        streams are deleted by their consumer after the final frame — the
        streaming twin of result-hash HDEL, keeping long-running broker
        state bounded by LIVE requests)."""
        with self.cond:
            self._sdel_locked(stream)

    def _sdel_locked(self, stream: str) -> None:
        existed = stream in self.streams
        self.streams.pop(stream, None)
        self.trimmed.pop(stream, None)
        for key in [k for k in self.cursors if k[0] == stream]:
            del self.cursors[key]
        for key in [k for k in self.pending if k[0] == stream]:
            del self.pending[key]
        for key in [k for k in self.redeliver if k[0] == stream]:
            del self.redeliver[key]
        for key in [k for k in self.deliveries if k[0] == stream]:
            del self.deliveries[key]
        if existed:
            self._log("S", stream)

    def xtransfer(self, src: str, group: str, dst: str) -> Dict[str, Any]:
        """Claim-transfer (the fleet's XAUTOCLAIM analog): atomically move
        every request still owed by ``(src, group)`` — delivered-but-unacked
        entries, crash-recovered redeliveries, and entries never delivered —
        onto ``dst`` as fresh appends, then delete ``src``. Used by the
        FleetSupervisor when a replica dies: its claimed work goes back to
        the dispatch stream instead of stranding until idle-reclaim.

        Per-entry delivery counts ride along: dict payloads are stamped with
        ``__deliveries__`` (how often the entry was already handed to a
        consumer) and the reply carries ``(new_id, deliveries)`` pairs. The
        guarantee is at-least-once — a slow-not-dead replica may still finish
        the work it claimed; result writes go through :meth:`hsetnx` so only
        the first answer per uri lands (dedup-on-uri)."""
        with self.cond:
            if src == dst:
                raise ValueError("xtransfer src and dst must differ")
            key = (src, group)
            moved: "collections.OrderedDict[str, Any]" = \
                collections.OrderedDict()
            for i, (payload, _ts) in sorted(
                    self.pending.get(key, {}).items(),
                    key=lambda kv: int(kv[0].split("-")[0])):
                moved[i] = payload
            for i, payload in self.redeliver.get(key, ()):
                moved.setdefault(i, payload)
            cur = self.cursors.get(key, 0)
            for i, payload in self.streams.get(src, [])[cur:]:
                moved.setdefault(i, payload)
            counts = dict(self.deliveries.get(key, {}))
            # delete src FIRST (logs "S"), then append to dst (logs "A"):
            # replaying that order rebuilds exactly this post-transfer state
            self._sdel_locked(src)
            out = []
            for i, payload in moved.items():
                n = counts.get(i, 0)
                if isinstance(payload, dict):
                    payload = dict(payload)
                    payload["__deliveries__"] = n
                self._seq += 1
                entry_id = f"{self._seq}-0"
                self._append(dst, entry_id, payload)
                self._log("A", dst, entry_id, payload)
                out.append((entry_id, n))
            if out:
                self.cond.notify_all()
            return {"moved": len(out), "entries": out}

    def xack(self, stream: str, group: str, ids: List[str]) -> int:
        with self.cond:
            key = (stream, group)
            n = 0
            dropped = set(ids)
            dv = self.deliveries.get(key)
            for i in ids:
                if self.pending[key].pop(i, None) is not None:
                    n += 1
                if dv:
                    dv.pop(i, None)
            # an entry acked while queued for crash redelivery (its result was
            # written before the crash) must not be served again
            redo = self.redeliver.get(key)
            if redo:
                self.redeliver[key] = [e for e in redo if e[0] not in dropped]
            if n:
                self._log("K", stream, group, list(ids))
            return n

    def _mark_answered(self, key: str) -> None:
        """Record ``key`` in the bounded first-write tombstone LRU."""
        self._answered[key] = None
        self._answered.move_to_end(key)
        while len(self._answered) > self.ANSWERED_MAXLEN:
            self._answered.popitem(last=False)

    def hset(self, key: str, mapping: Any) -> None:
        with self.cond:
            self.hashes[key] = mapping
            self._mark_answered(key)
            self._log("H", key, mapping)
            self.cond.notify_all()

    def hsetnx(self, key: str, mapping: Any) -> int:
        """First-write-wins HSET: refuses (returns 0) when ``key`` is live OR
        was EVER written within the tombstone window — even after the client
        HDEL'd it. This is the fleet's dedup-on-uri primitive: a requeued
        request answered by two replicas (the reassigned one and the slow-
        not-dead original) produces exactly one client-visible result, and
        the late duplicate can't recreate a consumed hash."""
        with self.cond:
            if key in self.hashes or key in self._answered:
                _DUP_DROPPED.inc()
                return 0
            self.hashes[key] = mapping
            self._mark_answered(key)
            self._log("H", key, mapping)
            self.cond.notify_all()
            return 1

    def hget(self, key: str, block_ms: int = 0) -> Any:
        deadline = None if block_ms <= 0 else block_ms / 1e3
        with self.cond:
            if key not in self.hashes and deadline:
                self.cond.wait_for(lambda: key in self.hashes, timeout=deadline)
            return self.hashes.get(key)

    def hdel(self, key: str) -> None:
        with self.cond:
            self.hashes.pop(key, None)
            self._log("D", key)

    def info_counts(self) -> Tuple[Dict[str, int], int, Dict[str, int]]:
        """INFO's store slice, snapshotted under the store lock:
        ``(per-stream live lengths, hash count, AOF replay counts)`` — the
        handler must not reach into the store's guarded dicts directly."""
        with self.cond:
            return ({s: len(e) for s, e in self.streams.items()},
                    len(self.hashes), dict(self.replayed))

    def slen(self, stream: str, group: Optional[str] = None) -> int:
        """Stream depth. With ``group``, counts the work OWED to that
        group's consumer: entries not yet delivered (past the group cursor,
        or queued for crash redelivery) plus delivered-but-unacked (pending)
        ones — the fleet router's least_pending signal (a replica that
        claimed a deep batch and died/stalled still owes it). The raw stream
        list retains delivered-and-acked entries until maxlen-trim, so it
        must NOT be counted wholesale: that would report cumulative dispatch
        history as load and starve replicas whose stream was reset (e.g.
        freshly respawned after an XTRANSFER)."""
        with self.cond:
            n = len(self.streams.get(stream, ()))
            if group is not None:
                key = (stream, group)
                n = max(0, n - self.cursors.get(key, 0))
                # redeliver entries stay in pending until acked; count the
                # union so neither map's stragglers are missed or doubled
                owed = set(self.pending.get(key, ()))
                owed.update(i for i, _ in self.redeliver.get(key, ()))
                n += len(owed)
            return n


# connection-scoped command sentinels (returned by _dispatch, acted on by
# handle() which owns the per-connection state)
_SHMOPEN = object()
_SHUTDOWN = object()


def _stamp_qos(payload: Any) -> Any:
    """Fold frame-header overload-QoS fields ("p"/"dl") into an XADD payload
    that does not already carry the durable twins: a sender that tags only
    the wire header still yields a priority/deadline-attributed record in
    the stream (and through AOF replay / XTRANSFER requeue — the payload is
    the copy that survives)."""
    pri, dl = received_qos()
    if (pri is None and dl is None) or not isinstance(payload, dict):
        return payload
    stamped = None
    if pri is not None and PRIORITY_KEY not in payload:
        stamped = dict(payload)
        stamped[PRIORITY_KEY] = pri
    if dl is not None and DEADLINE_KEY not in payload:
        stamped = dict(payload) if stamped is None else stamped
        stamped[DEADLINE_KEY] = dl
    return payload if stamped is None else stamped


def _stamp_version(payload: Any) -> Any:
    """Fold a frame-header model version ("v") into a hash write whose
    payload does not already carry one: an engine that tags only the wire
    header still yields version-attributed results in the durable store."""
    ver = received_model_version()
    if ver is not None and isinstance(payload, dict) \
            and MODEL_VERSION_KEY not in payload:
        payload = dict(payload)
        payload[MODEL_VERSION_KEY] = ver
    return payload


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        super().setup()
        # reply frames are small and latency-bound (see client.py _connect):
        # Nagle + the client's delayed ACK costs ~40ms per round trip
        try:
            self.request.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
        except OSError:
            pass

    def handle(self):
        from ..common.chaos import chaos_point

        store: _Store = self.server.store  # type: ignore[attr-defined]
        shm_ch = None   # per-connection shared-memory ring (client-created)
        try:
            while True:
                req = recv_msg(self.request, shm=shm_ch)
                cmd = req[0]
                verb = (cmd if isinstance(cmd, str) and cmd in _KNOWN_CMDS
                        else "unknown")   # unhashable/garbage cmd must still
                                          # get the unknown-command reply
                _CMDS.labels(cmd=verb).inc()
                self.server.count_command(verb)  # type: ignore[attr-defined]
                # parent the broker-side span on the client's trace: binary
                # frames carry it in the header, JSON XADDs inside the payload
                # dict; commands without one (old clients, polls) skip the
                # span — no orphan traces from XREADGROUP idle loops
                ctx = received_trace_context()
                if ctx is None and cmd == "XADD" and len(req) > 2:
                    ctx = payload_trace(req[2])
                span_cm = (_tm.span("serving.broker.handle", remote=ctx,
                                    cmd=str(cmd)) if ctx is not None
                           else contextlib.nullcontext())
                # deterministic fault site: a "fail" rule severs this client's
                # connection mid-protocol (the except below closes it); a
                # "delay" rule models a slow broker reply
                chaos_point("broker.handle", tag=cmd)
                with span_cm:
                    resp = self._dispatch(cmd, req, store)
                    if resp is _SHMOPEN:
                        # same-host zero-copy negotiation: attach the client's
                        # ring; any failure leaves this connection on the
                        # socket path (client falls back on a non-"OK" reply).
                        # A 4-element SHMOPEN carries the client's host
                        # identity — refuse a peer in another kernel/ipc
                        # namespace BEFORE touching /dev/shm: attach() can
                        # spuriously succeed against a same-named segment in
                        # our namespace that is NOT the client's memory
                        from .shm import ShmChannel, host_identity

                        peer = req[3] if len(req) > 3 else None
                        if peer is not None and peer != host_identity():
                            _SHM_NEG.labels(outcome="denied").inc()
                            self.server.count_shm(  # type: ignore[attr-defined]
                                "denied")
                            resp = {"error": "shm denied: cross-host peer "
                                             f"{peer!r}"}
                        else:
                            try:
                                new_ch = ShmChannel.attach(req[1],
                                                           int(req[2]))
                            except Exception as e:
                                _SHM_NEG.labels(outcome="fallback").inc()
                                self.server.count_shm(  # type: ignore[attr-defined]
                                    "fallback")
                                resp = {"error": f"shm attach failed: {e}"}
                            else:
                                if shm_ch is not None:
                                    shm_ch.close()
                                shm_ch = new_ch
                                _SHM_NEG.labels(outcome="ok").inc()
                                self.server.count_shm(  # type: ignore[attr-defined]
                                    "ok")
                                resp = "OK"
                    elif resp is _SHUTDOWN:
                        send_msg(self.request, "OK")
                        threading.Thread(target=self.server.shutdown,
                                         daemon=True).start()
                        return
                    elif cmd == "INFO":
                        resp["shm_attached"] = shm_ch is not None
                # result-fetch replies re-carry the stored payload's serving
                # model version in the frame header (hot-swap end-to-end
                # tagging: engine header → stored payload → client header)
                set_wire_model_version(
                    resp.get(MODEL_VERSION_KEY)
                    if isinstance(resp, dict) else None)
                send_msg(self.request, resp, shm=shm_ch)
        except (ConnectionError, OSError):
            return
        finally:
            if shm_ch is not None:
                shm_ch.close()

    def _dispatch(self, cmd, req, store: "_Store"):
        """Store-level command handling; connection-scoped commands (SHMOPEN,
        SHUTDOWN) return sentinels for :meth:`handle` to act on."""
        if cmd == "XADD":
            return store.xadd(req[1], _stamp_qos(req[2]))
        if cmd == "XGROUPCREATE":
            store.xgroupcreate(req[1], req[2],
                               req[3] if len(req) > 3 else "$")
            return "OK"
        if cmd == "XREADGROUP":
            return store.xreadgroup(req[1], req[2], req[3], req[4])
        if cmd == "XREAD":
            return store.xread(req[1], req[2], req[3],
                               req[4] if len(req) > 4 else 0)
        if cmd == "XLAST":
            return store.xlast(req[1])
        if cmd == "XDELSTREAM":
            store.sdel(req[1])
            return "OK"
        if cmd == "XTRANSFER":
            return store.xtransfer(req[1], req[2], req[3])
        if cmd == "XACK":
            return store.xack(req[1], req[2], req[3])
        if cmd == "HSET":
            store.hset(req[1], _stamp_version(req[2]))
            return "OK"
        if cmd == "HSETNX":
            return store.hsetnx(req[1], _stamp_version(req[2]))
        if cmd == "HGET":
            return store.hget(req[1], req[2] if len(req) > 2 else 0)
        if cmd == "HDEL":
            store.hdel(req[1])
            return "OK"
        if cmd == "LEN":
            return store.slen(req[1], req[2] if len(req) > 2 else None)
        if cmd == "PING":
            return "PONG"
        if cmd == "SHMOPEN":
            return _SHMOPEN
        if cmd == "INFO":
            streams, n_hashes, replayed = store.info_counts()
            server = self.server  # type: ignore[attr-defined]
            return {"wire_version": WIRE_VERSION,
                    "streams": streams, "hashes": n_hashes,
                    "wire": wire_stats(),
                    # observability satellites: replay + ring-negotiation
                    # visibility, printed verbatim by `cli info`. These are
                    # per-BROKER-INSTANCE counts (like streams/hashes) — the
                    # registry's zoo_broker_* counters aggregate the process
                    "aof_replayed_records": replayed,
                    "aof_compactions": store.compactions,
                    "shm_negotiations": server.shm_counts(),
                    "commands": server.command_counts()}
        if cmd == "SHUTDOWN":
            return _SHUTDOWN
        return {"error": f"unknown command {cmd!r}"}


class QueueBroker(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 aof_path: Optional[str] = None,
                 reclaim_idle_ms: int = 60_000,
                 aof_rewrite_min_bytes: int = 64 << 20):
        super().__init__((host, port), _Handler)
        self.store = _Store(aof_path=aof_path, reclaim_idle_ms=reclaim_idle_ms,
                            aof_rewrite_min_bytes=aof_rewrite_min_bytes)
        # per-instance observability counts for INFO (a process can host
        # several brokers; the registry counters aggregate across them)
        # zoo-lock: guards(_commands, _shm_neg)
        self._counts_lock = traced_lock("QueueBroker._counts_lock")
        self._commands: Dict[str, int] = {}
        self._shm_neg = {"ok": 0, "fallback": 0, "denied": 0}

    def count_command(self, verb: str) -> None:
        with self._counts_lock:
            self._commands[verb] = self._commands.get(verb, 0) + 1

    def count_shm(self, outcome: str) -> None:
        with self._counts_lock:
            self._shm_neg[outcome] = self._shm_neg.get(outcome, 0) + 1

    def command_counts(self) -> Dict[str, int]:
        with self._counts_lock:
            return dict(self._commands)

    def shm_counts(self) -> Dict[str, int]:
        with self._counts_lock:
            return dict(self._shm_neg)

    @property
    def port(self) -> int:
        return self.server_address[1]


def start_broker(host: str = "127.0.0.1", port: int = 0,
                 aof_path: Optional[str] = None) -> QueueBroker:
    """Start a broker on a daemon thread; returns it (``.port`` is bound)."""
    broker = QueueBroker(host, port, aof_path=aof_path)
    threading.Thread(target=broker.serve_forever, daemon=True,
                     name="zoo-queue-broker").start()
    return broker


def main():  # pragma: no cover - exercised as a subprocess
    ap = argparse.ArgumentParser(description="analytics_zoo_tpu queue broker")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6380)
    ap.add_argument("--aof", default=None,
                    help="append-only persistence file (replayed on start)")
    ap.add_argument("--reclaim-idle-ms", type=int, default=60_000,
                    help="redeliver entries unacked for this long (XAUTOCLAIM)")
    ap.add_argument("--aof-rewrite-min-bytes", type=int, default=64 << 20,
                    help="compact the AOF (rewrite live state, atomic rename) "
                         "once it grows past this many bytes")
    args = ap.parse_args()
    broker = QueueBroker(args.host, args.port, aof_path=args.aof,
                         reclaim_idle_ms=args.reclaim_idle_ms,
                         aof_rewrite_min_bytes=args.aof_rewrite_min_bytes)
    print(f"queue broker listening on {args.host}:{broker.port}", flush=True)
    broker.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
