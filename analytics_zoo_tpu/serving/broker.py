"""QueueBroker — a self-contained stream broker (the Redis-streams equivalent).

Parity: the reference fronts serving with Redis: clients ``XADD`` requests onto a
stream, the Flink source consumes via a consumer group (``xgroupCreate`` +
``xreadGroup`` — /root/reference/zoo/.../serving/engine/FlinkRedisSource.scala:
44-59), and results land in per-request hashes read by ``OutputQueue``
(client.py:277-300). This broker provides exactly those primitives over a
length-prefixed-JSON TCP protocol:

    XADD stream payload              -> id
    XREADGROUP stream group n block  -> [(id, payload), ...]   (each entry to ONE consumer)
    HSET key mapping / HGET key / HDEL key
    LEN stream / PING / SHUTDOWN

It runs in-process (``start_broker()`` returns a served port) or standalone
(``python -m analytics_zoo_tpu.serving.broker --port 6380``).
"""

from __future__ import annotations

import argparse
import collections
import json
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

_HDR = struct.Struct(">I")
MAX_MSG = 512 * 1024 * 1024


def send_msg(sock: socket.socket, obj: Any) -> None:
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(_HDR.pack(len(data)) + data)


def recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, _HDR.size)
    (n,) = _HDR.unpack(hdr)
    if n > MAX_MSG:
        raise ValueError(f"message of {n} bytes exceeds limit")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class _Store:
    """Streams (bounded lists w/ per-group cursors) + hashes, one lock.

    Streams are trimmed like Redis ``XADD MAXLEN ~``: beyond ``maxlen`` entries
    the oldest are dropped and every group cursor shifts accordingly, so a
    long-running deployment holds bounded memory.
    """

    def __init__(self, maxlen: int = 65536):
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.maxlen = maxlen
        self.streams: Dict[str, List[Tuple[str, Any]]] = collections.defaultdict(list)
        self.cursors: Dict[Tuple[str, str], int] = collections.defaultdict(int)
        self.trimmed: Dict[str, int] = collections.defaultdict(int)
        self.hashes: Dict[str, Any] = {}
        self._seq = 0

    def xadd(self, stream: str, payload: Any) -> str:
        with self.cond:
            self._seq += 1
            entry_id = f"{self._seq}-0"
            entries = self.streams[stream]
            entries.append((entry_id, payload))
            overflow = len(entries) - self.maxlen
            if overflow > 0:
                del entries[:overflow]
                self.trimmed[stream] += overflow
                for key in self.cursors:
                    if key[0] == stream:
                        self.cursors[key] = max(0, self.cursors[key] - overflow)
            self.cond.notify_all()
            return entry_id

    def xgroupcreate(self, stream: str, group: str, start: str = "$") -> None:
        """Register a consumer group. ``start='$'`` = only entries added after
        this call (Redis tail semantics); ``'0'`` = replay from the beginning.
        No-op when the group exists (cursor preserved across job restarts)."""
        with self.cond:
            key = (stream, group)
            if key not in self.cursors:
                self.cursors[key] = (len(self.streams[stream])
                                     if start == "$" else 0)

    def xreadgroup(self, stream: str, group: str, count: int,
                   block_ms: int) -> List[Tuple[str, Any]]:
        deadline = None if block_ms <= 0 else block_ms / 1e3
        with self.cond:
            key = (stream, group)

            def pending():
                return len(self.streams[stream]) - self.cursors[key]

            if pending() == 0 and deadline:
                self.cond.wait(timeout=deadline)
            take = min(count, pending())
            if take <= 0:
                return []
            start = self.cursors[key]
            self.cursors[key] = start + take
            return self.streams[stream][start:start + take]

    def hset(self, key: str, mapping: Any) -> None:
        with self.cond:
            self.hashes[key] = mapping
            self.cond.notify_all()

    def hget(self, key: str, block_ms: int = 0) -> Any:
        deadline = None if block_ms <= 0 else block_ms / 1e3
        with self.cond:
            if key not in self.hashes and deadline:
                self.cond.wait_for(lambda: key in self.hashes, timeout=deadline)
            return self.hashes.get(key)

    def hdel(self, key: str) -> None:
        with self.cond:
            self.hashes.pop(key, None)

    def slen(self, stream: str) -> int:
        with self.cond:
            return len(self.streams[stream])


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        store: _Store = self.server.store  # type: ignore[attr-defined]
        try:
            while True:
                req = recv_msg(self.request)
                cmd = req[0]
                if cmd == "XADD":
                    resp = store.xadd(req[1], req[2])
                elif cmd == "XGROUPCREATE":
                    store.xgroupcreate(req[1], req[2],
                                       req[3] if len(req) > 3 else "$")
                    resp = "OK"
                elif cmd == "XREADGROUP":
                    resp = store.xreadgroup(req[1], req[2], req[3], req[4])
                elif cmd == "HSET":
                    store.hset(req[1], req[2])
                    resp = "OK"
                elif cmd == "HGET":
                    resp = store.hget(req[1], req[2] if len(req) > 2 else 0)
                elif cmd == "HDEL":
                    store.hdel(req[1])
                    resp = "OK"
                elif cmd == "LEN":
                    resp = store.slen(req[1])
                elif cmd == "PING":
                    resp = "PONG"
                elif cmd == "SHUTDOWN":
                    send_msg(self.request, "OK")
                    threading.Thread(target=self.server.shutdown,
                                     daemon=True).start()
                    return
                else:
                    resp = {"error": f"unknown command {cmd!r}"}
                send_msg(self.request, resp)
        except (ConnectionError, OSError):
            return


class QueueBroker(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self.store = _Store()

    @property
    def port(self) -> int:
        return self.server_address[1]


def start_broker(host: str = "127.0.0.1", port: int = 0) -> QueueBroker:
    """Start a broker on a daemon thread; returns it (``.port`` is bound)."""
    broker = QueueBroker(host, port)
    threading.Thread(target=broker.serve_forever, daemon=True,
                     name="zoo-queue-broker").start()
    return broker


def main():  # pragma: no cover - exercised as a subprocess
    ap = argparse.ArgumentParser(description="analytics_zoo_tpu queue broker")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6380)
    args = ap.parse_args()
    broker = QueueBroker(args.host, args.port)
    print(f"queue broker listening on {args.host}:{broker.port}", flush=True)
    broker.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
