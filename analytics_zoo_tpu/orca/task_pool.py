"""Task-parallel runner — the RayOnSpark *non-training* half.

The reference can run arbitrary distributed Python inside its cluster: Ray
tasks and actors bootstrapped by RayOnSpark (raycontext.py:190), used for the
async parameter server (pyzoo/zoo/examples/ray/parameter_server/
async_parameter_server.py) and RL rollouts (examples/ray/rl_pong/rl_pong.py).

TPU-native redesign: training-style SPMD jobs go through ``ClusterLauncher``
(common/cluster.py); *task-parallel* workloads (rollout workers, parameter
servers, hyperparameter eval, data prep) use this pool — N spawned worker
processes executing cloudpickled callables, plus Ray-style **actors**: a class
instantiated inside one dedicated worker where it keeps state; method calls
are serialized per actor and return futures.

    pool = TaskPool(4)
    futs = [pool.submit(lambda x=i: x * x) for i in range(8)]
    [f.result() for f in futs]

    ps = pool.actor(ParameterServer, init_weights)      # lives in worker 0
    w = ps.call("get_weights").result()
    ps.call("apply_gradients", grads)

Host spanning: each host of a ``ClusterLauncher`` job can run its own pool;
``pool_rank()`` / ``pool_world()`` expose the launcher's ``ZOO_TPU_PROCESS_ID``
/ ``ZOO_TPU_NUM_PROCESSES`` env so one script can shard work across hosts the
way Ray placement groups spread actors.
"""

from __future__ import annotations

import itertools
import os
import threading
import multiprocessing as mp
from typing import Any, Callable, Dict, List, Optional, Sequence

import cloudpickle


def pool_rank() -> int:
    """This host's rank in a ClusterLauncher job (0 standalone)."""
    return int(os.environ.get("ZOO_TPU_PROCESS_ID", "0"))


def pool_world() -> int:
    """Number of hosts in the ClusterLauncher job (1 standalone)."""
    return int(os.environ.get("ZOO_TPU_NUM_PROCESSES", "1"))


def _worker_main(inbox, outbox, init_blob):
    """Worker loop: run tasks / host actors. Always forces the CPU backend —
    task workers must never grab the TPU from the driver."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    if init_blob is not None:
        cloudpickle.loads(init_blob)()
    actors: Dict[int, Any] = {}
    while True:
        msg = inbox.get()
        if msg is None:
            return
        kind, tid = msg[0], msg[1]
        try:
            if kind == "task":
                fn, args, kw = cloudpickle.loads(msg[2])
                result = fn(*args, **kw)
            elif kind == "actor_new":
                cls, args, kw = cloudpickle.loads(msg[2])
                actors[msg[3]] = cls(*args, **kw)
                result = True
            elif kind == "actor_call":
                method, args, kw = cloudpickle.loads(msg[3])
                result = getattr(actors[msg[2]], method)(*args, **kw)
            elif kind == "actor_del":
                actors.pop(msg[2], None)
                result = True
            else:
                raise ValueError(f"unknown message {kind!r}")
            outbox.put((tid, True, cloudpickle.dumps(result)))
        except BaseException as e:  # report, keep serving
            outbox.put((tid, False, cloudpickle.dumps(
                RuntimeError(f"{type(e).__name__}: {e}"))))


class Future:
    """Result handle; ``result(timeout)`` blocks and re-raises task errors."""

    def __init__(self):
        self._ev = threading.Event()
        self._ok = None
        self._val = None

    def _set(self, ok: bool, val: Any):
        self._ok, self._val = ok, val
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._ev.wait(timeout):
            raise TimeoutError("task did not complete in time")
        if not self._ok:
            raise self._val
        return self._val


class ActorHandle:
    """Proxy to a class instance living inside one worker process. Calls on
    the same actor execute in submission order (its worker inbox is FIFO)."""

    def __init__(self, pool: "TaskPool", actor_id: int, worker: int):
        self._pool = pool
        self.actor_id = actor_id
        self.worker = worker

    def call(self, method: str, *args, **kw) -> Future:
        return self._pool._send(
            self.worker, "actor_call", self.actor_id,
            cloudpickle.dumps((method, args, kw)))

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *a, **kw: self.call(name, *a, **kw)

    def terminate(self):
        self._pool._send(self.worker, "actor_del", self.actor_id)


class TaskPool:
    """N spawned worker processes executing tasks and hosting actors.

    ``worker_init``: optional zero-arg callable run once in each worker (env
    setup, warmup). Workers are spawn-context processes — no inherited JAX
    state, CPU backend forced.
    """

    def __init__(self, num_workers: int = 4,
                 worker_init: Optional[Callable[[], None]] = None):
        import sys

        ctx = mp.get_context("spawn")
        self.num_workers = int(num_workers)
        self._inboxes = [ctx.Queue() for _ in range(self.num_workers)]
        self._outbox = ctx.Queue()
        init_blob = cloudpickle.dumps(worker_init) if worker_init else None
        self._procs = [
            ctx.Process(target=_worker_main, daemon=True,
                        args=(self._inboxes[i], self._outbox, init_blob))
            for i in range(self.num_workers)]
        # spawn re-runs __main__ from its __file__ in every child; when the
        # driver is stdin/REPL ('<stdin>') that file doesn't exist and every
        # worker dies at startup (hanging all futures). Drop the bogus
        # attribute around start() — cloudpickle serializes __main__
        # callables by value, so workers never need the real script anyway.
        main_mod = sys.modules.get("__main__")
        main_file = getattr(main_mod, "__file__", None)
        strip = main_file is not None and not os.path.exists(main_file)
        if strip:
            del main_mod.__file__
        try:
            for p in self._procs:
                p.start()
        finally:
            if strip:
                main_mod.__file__ = main_file
        self._futures: Dict[int, Future] = {}
        self._flock = threading.Lock()
        self._tid = itertools.count()
        self._aid = itertools.count()
        self._rr = itertools.count()
        self._closed = False
        self._broken: Optional[str] = None
        self._collector = threading.Thread(target=self._collect, daemon=True)
        self._collector.start()
        self._watchdog = threading.Thread(target=self._watch, daemon=True)
        self._watchdog.start()

    # ------------------------------------------------------------ internals
    def _collect(self):
        while True:
            try:
                msg = self._outbox.get()
            except (OSError, EOFError, ValueError, TypeError):
                return  # queue torn down during interpreter/pool shutdown
            if msg is None:
                return
            tid, ok, blob = msg
            with self._flock:
                fut = self._futures.pop(tid, None)
            if fut is not None:
                fut._set(ok, cloudpickle.loads(blob))

    def _watch(self):
        """Fail every outstanding future if a worker dies unexpectedly (OOM
        kill, segfault) — otherwise map()/result() would block forever on a
        message that can never arrive."""
        import time

        while not self._closed:
            for p in self._procs:
                if not p.is_alive() and not self._closed:
                    self._broken = (f"task pool worker pid={p.pid} died "
                                    f"(exitcode {p.exitcode})")
                    with self._flock:
                        futs = list(self._futures.values())
                        self._futures.clear()
                    for f in futs:
                        f._set(False, RuntimeError(self._broken))
                    return
            time.sleep(0.2)

    def _send(self, worker: int, kind: str, *payload) -> Future:
        if self._closed:
            raise RuntimeError("pool is shut down")
        if self._broken:
            raise RuntimeError(self._broken)
        tid = next(self._tid)
        fut = Future()
        with self._flock:
            self._futures[tid] = fut
        # the watchdog may have drained _futures between the _broken check
        # above and the registration — re-check so this future can't be the
        # one that hangs forever
        if self._broken:
            with self._flock:
                self._futures.pop(tid, None)
            fut._set(False, RuntimeError(self._broken))
            return fut
        self._inboxes[worker].put((kind, tid, *payload))
        return fut

    # -------------------------------------------------------------- tasks
    def submit(self, fn: Callable, *args, **kw) -> Future:
        """Run ``fn(*args, **kw)`` on the least-recently-used worker."""
        worker = next(self._rr) % self.num_workers
        return self._send(worker, "task", cloudpickle.dumps((fn, args, kw)))

    def map(self, fn: Callable, items: Sequence[Any]) -> List[Any]:
        """Parallel map; blocks for all results (ordered)."""
        futs = [self.submit(fn, it) for it in items]
        return [f.result() for f in futs]

    # -------------------------------------------------------------- actors
    def actor(self, cls: type, *args, worker: Optional[int] = None,
              **kw) -> ActorHandle:
        """Instantiate ``cls`` inside one worker; returns a handle whose
        method calls are futures (Ray ``@ray.remote`` class parity)."""
        aid = next(self._aid)
        worker = (next(self._rr) % self.num_workers) if worker is None \
            else worker % self.num_workers
        self._send(worker, "actor_new", cloudpickle.dumps((cls, args, kw)),
                   aid).result(timeout=120)
        return ActorHandle(self, aid, worker)

    # ------------------------------------------------------------- control
    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        for q in self._inboxes:
            q.put(None)
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._outbox.put(None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
