"""Task-parallel runner — the RayOnSpark *non-training* half.

The reference can run arbitrary distributed Python inside its cluster: Ray
tasks and actors bootstrapped by RayOnSpark (raycontext.py:190), used for the
async parameter server (pyzoo/zoo/examples/ray/parameter_server/
async_parameter_server.py) and RL rollouts (examples/ray/rl_pong/rl_pong.py).

TPU-native redesign: training-style SPMD jobs go through ``ClusterLauncher``
(common/cluster.py); *task-parallel* workloads (rollout workers, parameter
servers, hyperparameter eval, data prep) use this pool — N spawned worker
processes executing cloudpickled callables, plus Ray-style **actors**: a class
instantiated inside one dedicated worker where it keeps state; method calls
are serialized per actor and return futures.

    pool = TaskPool(4)
    futs = [pool.submit(lambda x=i: x * x) for i in range(8)]
    [f.result() for f in futs]

    ps = pool.actor(ParameterServer, init_weights)      # lives in worker 0
    w = ps.call("get_weights").result()
    ps.call("apply_gradients", grads)

Host spanning: each host of a ``ClusterLauncher`` job can run its own pool;
``pool_rank()`` / ``pool_world()`` expose the launcher's ``ZOO_TPU_PROCESS_ID``
/ ``ZOO_TPU_NUM_PROCESSES`` env so one script can shard work across hosts the
way Ray placement groups spread actors.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import multiprocessing as mp
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ..common.locks import traced_lock
from ..common.resilience import HealthRegistry


def pool_rank() -> int:
    """This host's rank in a ClusterLauncher job (0 standalone)."""
    return int(os.environ.get("ZOO_TPU_PROCESS_ID", "0"))


def pool_world() -> int:
    """Number of hosts in the ClusterLauncher job (1 standalone)."""
    return int(os.environ.get("ZOO_TPU_NUM_PROCESSES", "1"))


_HB = "__hb__"   # heartbeat sentinel on the shared outbox


def _worker_main(widx, inbox, outbox, init_blob, chaos_blob, hb_interval_s):
    """Worker loop: run tasks / host actors. Always forces the CPU backend —
    task workers must never grab the TPU from the driver.

    A daemon thread pumps ``(_HB, widx, None)`` heartbeats onto the outbox so
    the driver can tell a *wedged* worker (process alive, loop stuck) from a
    busy one — the GIL is released around queue waits and native compute, so
    beats keep flowing through long tasks. The driver's chaos schedule is
    re-installed here so cross-process fault plans (kill worker 1 at its 2nd
    task) stay deterministic.
    """
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    from ..common import chaos as chaos_mod

    if chaos_blob is not None:
        chaos_mod.install_chaos(cloudpickle.loads(chaos_blob))
    if init_blob is not None:
        cloudpickle.loads(init_blob)()

    stop_hb = threading.Event()

    def _beat():
        while not stop_hb.wait(hb_interval_s):
            try:
                outbox.put((_HB, widx, None))
            except Exception:
                return

    threading.Thread(target=_beat, daemon=True, name="pool-hb").start()

    actors: Dict[int, Any] = {}
    while True:
        msg = inbox.get()
        if msg is None:
            stop_hb.set()
            return
        kind, tid = msg[0], msg[1]
        try:
            if kind == "task":
                chaos_mod.chaos_point("task_pool.worker", tag=widx)
                fn, args, kw = cloudpickle.loads(msg[2])
                result = fn(*args, **kw)
            elif kind == "actor_new":
                cls, args, kw = cloudpickle.loads(msg[2])
                actors[msg[3]] = cls(*args, **kw)
                result = True
            elif kind == "actor_call":
                chaos_mod.chaos_point("task_pool.worker", tag=widx)
                method, args, kw = cloudpickle.loads(msg[3])
                result = getattr(actors[msg[2]], method)(*args, **kw)
            elif kind == "actor_del":
                actors.pop(msg[2], None)
                result = True
            else:
                raise ValueError(f"unknown message {kind!r}")
            outbox.put((tid, True, cloudpickle.dumps(result)))
        except BaseException as e:  # report, keep serving
            outbox.put((tid, False, cloudpickle.dumps(
                RuntimeError(f"{type(e).__name__}: {e}"))))


class Future:
    """Result handle; ``result(timeout)`` blocks and re-raises task errors."""

    def __init__(self):
        self._ev = threading.Event()
        self._ok = None
        self._val = None

    def _set(self, ok: bool, val: Any):
        self._ok, self._val = ok, val
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._ev.wait(timeout):
            raise TimeoutError("task did not complete in time")
        if not self._ok:
            raise self._val
        return self._val


class ActorHandle:
    """Proxy to a class instance living inside one worker process. Calls on
    the same actor execute in submission order (its worker inbox is FIFO)."""

    def __init__(self, pool: "TaskPool", actor_id: int, worker: int):
        self._pool = pool
        self.actor_id = actor_id
        self.worker = worker

    def call(self, method: str, *args, **kw) -> Future:
        return self._pool._send(
            self.worker, "actor_call", self.actor_id,
            cloudpickle.dumps((method, args, kw)))

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *a, **kw: self.call(name, *a, **kw)

    def terminate(self):
        self._pool._forget_actor(self.actor_id)
        self._pool._send(self.worker, "actor_del", self.actor_id)


class TaskPool:
    """N spawned worker processes executing tasks and hosting actors.

    ``worker_init``: optional zero-arg callable run once in each worker (env
    setup, warmup). Workers are spawn-context processes — no inherited JAX
    state, CPU backend forced.

    Fault tolerance (``respawn=True``): dead workers — detected by process
    exit OR a stale heartbeat (a wedged-but-alive process), not just pipe
    EOF — are respawned in place; every in-flight message that was assigned
    to the dead worker is automatically resubmitted (tasks are assumed
    idempotent in this mode — the Ray task model), and actors homed there
    are re-instantiated from their constructor args, with an optional
    per-actor ``on_respawn(handle)`` callback to push externally-held state
    back in. With ``respawn=False`` (default) a dead worker breaks the pool
    and fails all outstanding futures — the legacy fail-fast contract.
    """

    def __init__(self, num_workers: int = 4,
                 worker_init: Optional[Callable[[], None]] = None,
                 respawn: bool = False,
                 heartbeat_interval_s: float = 0.2,
                 heartbeat_timeout_s: float = 10.0,
                 registry: Optional[HealthRegistry] = None):
        from ..common.chaos import get_chaos

        self._ctx = mp.get_context("spawn")
        self.num_workers = int(num_workers)
        self.respawn = bool(respawn)
        self.workers_respawned = 0
        self.registry = registry if registry is not None else HealthRegistry(
            default_timeout_s=heartbeat_timeout_s)
        self._hb_interval_s = heartbeat_interval_s
        self._init_blob = (cloudpickle.dumps(worker_init) if worker_init
                           else None)
        # forward the driver's installed chaos schedule so cross-process
        # fault plans are deterministic; respawned workers run fault-free
        # (the schedule models one environment fault, not a crash loop)
        sched = get_chaos()
        self._chaos_blob = cloudpickle.dumps(sched) if sched else None
        self._futures: Dict[int, Dict[str, Any]] = {}   # tid -> pending rec
        # zoo-lock: guards(_futures, _actors)
        self._flock = traced_lock("TaskPool._flock")
        self._tid = itertools.count()
        self._aid = itertools.count()
        self._rr = itertools.count()
        self._actors: Dict[int, Dict[str, Any]] = {}
        self._closed = False
        self._broken: Optional[str] = None
        self._inboxes: List[Any] = [None] * self.num_workers
        # ONE outbox per worker, not a shared queue: a worker hard-killed
        # (os._exit / SIGKILL) mid-write would leave a shared queue's
        # cross-process write lock held forever, wedging every OTHER
        # worker's results. Per-worker queues confine the poison to the dead
        # worker; revive abandons its queue and starts a fresh one.
        self._outboxes: List[Any] = [None] * self.num_workers
        self._procs: List[Any] = [None] * self.num_workers
        for i in range(self.num_workers):
            self._make_worker(i, with_chaos=True)
        self._start_procs(list(self._procs))
        self._watchdog = threading.Thread(target=self._watch, daemon=True)
        self._watchdog.start()

    # ------------------------------------------------------------ internals
    @staticmethod
    def _wname(i: int) -> str:
        return f"pool.worker-{i}"

    def _make_worker(self, i: int, with_chaos: bool):
        """Build worker ``i``'s process + fresh inbox/outbox and its
        collector thread (process not started yet)."""
        self._inboxes[i] = self._ctx.Queue()
        outbox = self._ctx.Queue()
        self._outboxes[i] = outbox
        self._procs[i] = self._ctx.Process(
            target=_worker_main, daemon=True,
            args=(i, self._inboxes[i], outbox, self._init_blob,
                  self._chaos_blob if with_chaos else None,
                  self._hb_interval_s))
        self.registry.register(self._wname(i))
        threading.Thread(target=self._collect, args=(outbox,), daemon=True,
                         name=f"pool-collect-{i}").start()

    @staticmethod
    def _start_procs(procs):
        """Start processes with the stdin-driver guard: spawn re-runs
        __main__ from its __file__ in every child; when the driver is a
        REPL ('<stdin>') that file doesn't exist and every worker dies at
        startup (hanging all futures). Drop the bogus attribute around
        start() — cloudpickle serializes __main__ callables by value, so
        workers never need the real script anyway."""
        import sys

        main_mod = sys.modules.get("__main__")
        main_file = getattr(main_mod, "__file__", None)
        strip = main_file is not None and not os.path.exists(main_file)
        if strip:
            del main_mod.__file__
        try:
            for p in procs:
                p.start()
        finally:
            if strip:
                main_mod.__file__ = main_file

    def _collect(self, outbox):
        """Drain ONE worker's outbox (results + heartbeats). The thread ends
        on the shutdown sentinel or queue teardown; a revived worker gets a
        fresh queue + collector, and this one is simply abandoned."""
        while True:
            try:
                msg = outbox.get()
            except (OSError, EOFError, ValueError, TypeError):
                return  # queue torn down during interpreter/pool shutdown
            if msg is None:
                return
            try:
                tid, ok, blob = msg
            except (TypeError, ValueError):
                continue  # torn write from a hard-killed worker: skip
            if tid == _HB:               # worker heartbeat, not a result
                self.registry.beat(self._wname(ok))
                continue
            with self._flock:
                rec = self._futures.pop(tid, None)
            if rec is None:
                continue
            try:
                val = cloudpickle.loads(blob)
            except Exception as e:       # undecodable (torn) payload
                ok, val = False, RuntimeError(f"undecodable worker result: {e}")
            rec["fut"]._set(ok, val)

    def _watch(self):
        """Dead-worker detection: process exit (OOM kill, segfault) or — in
        respawn mode — a heartbeat stale past the timeout (wedged process).
        respawn=False: fail every outstanding future so map()/result() never
        blocks forever on a message that can never arrive. respawn=True:
        revive the worker and resubmit its in-flight work."""
        while not self._closed:
            for i in range(self.num_workers):
                if self._closed:
                    return
                p = self._procs[i]
                dead = not p.is_alive()
                # staleness only counts after the FIRST beat: spawn + JAX
                # import can exceed the timeout on a loaded box, and a worker
                # that never comes up still trips the is_alive check when it
                # exits — only a wedged-after-startup worker needs this path
                if not dead and self.respawn \
                        and self.registry.beats(self._wname(i)) > 0 \
                        and not self.registry.alive(self._wname(i)):
                    dead = True
                if not dead:
                    continue
                if self.respawn:
                    self._revive(i)
                    continue
                self._broken = (f"task pool worker pid={p.pid} died "
                                f"(exitcode {p.exitcode})")
                with self._flock:
                    recs = list(self._futures.values())
                    self._futures.clear()
                for rec in recs:
                    rec["fut"]._set(False, RuntimeError(self._broken))
                return
            time.sleep(0.1)

    def _revive(self, i: int):
        """Respawn dead worker ``i`` in place: fresh process + inbox, actors
        re-instantiated (then ``on_respawn`` state restoration), and every
        in-flight message reassigned — same tids, so the original futures
        simply resolve on the second execution."""
        old = self._procs[i]
        if old.is_alive():   # wedged, not exited: put it down first
            old.terminate()
        old.join(timeout=2.0)
        self.workers_respawned += 1
        # swap the inbox BEFORE snapshotting in-flight work: a concurrent
        # _send after the swap reaches the new worker directly (a duplicate
        # resubmission is deduped by the future pop; a message to the dead
        # inbox would be silently lost)
        self._make_worker(i, with_chaos=False)
        with self._flock:
            pending = sorted(
                (tid, rec) for tid, rec in self._futures.items()
                if rec["worker"] == i)
        self._start_procs([self._procs[i]])
        inbox = self._inboxes[i]
        # 1) rebuild actors homed on this worker (constructor args replay);
        #    snapshot under the lock — actor()/terminate() mutate the dict
        #    concurrently and an unguarded iteration could kill the watchdog
        with self._flock:
            homed = [(aid, a) for aid, a in sorted(self._actors.items())
                     if a["worker"] == i]
        for aid, a in homed:
            inbox.put(("actor_new", next(self._tid), a["blob"], aid))
        # 2) let owners push externally-held state back in; their calls are
        #    enqueued ahead of the resubmitted in-flight messages below
        for aid, a in homed:
            if a["on_respawn"] is not None:
                try:
                    a["on_respawn"](ActorHandle(self, aid, i))
                except Exception:  # user callback must not kill the watchdog
                    import logging

                    logging.getLogger("analytics_zoo_tpu.orca").exception(
                        "actor %d on_respawn callback failed", aid)
        # 3) resubmit in-flight work (idempotent-task contract)
        for tid, rec in pending:
            inbox.put(rec["msg"])

    def _forget_actor(self, actor_id: int) -> None:
        """Drop an actor from the respawn roster (handle.terminate();
        keeps the _flock acquisition inside its owning class)."""
        with self._flock:
            self._actors.pop(actor_id, None)

    def _send(self, worker: int, kind: str, *payload) -> Future:
        if self._closed:
            raise RuntimeError("pool is shut down")
        if self._broken:
            raise RuntimeError(self._broken)
        tid = next(self._tid)
        fut = Future()
        msg = (kind, tid, *payload)
        with self._flock:
            self._futures[tid] = {"fut": fut, "worker": worker, "msg": msg}
        # the watchdog may have drained _futures between the _broken check
        # above and the registration — re-check so this future can't be the
        # one that hangs forever
        if self._broken:
            with self._flock:
                self._futures.pop(tid, None)
            fut._set(False, RuntimeError(self._broken))
            return fut
        self._inboxes[worker].put(msg)
        return fut

    # -------------------------------------------------------------- tasks
    def submit(self, fn: Callable, *args, **kw) -> Future:
        """Run ``fn(*args, **kw)`` on the least-recently-used worker."""
        worker = next(self._rr) % self.num_workers
        return self._send(worker, "task", cloudpickle.dumps((fn, args, kw)))

    def map(self, fn: Callable, items: Sequence[Any]) -> List[Any]:
        """Parallel map; blocks for all results (ordered)."""
        futs = [self.submit(fn, it) for it in items]
        return [f.result() for f in futs]

    # -------------------------------------------------------------- actors
    def actor(self, cls: type, *args, worker: Optional[int] = None,
              on_respawn: Optional[Callable[[ActorHandle], None]] = None,
              **kw) -> ActorHandle:
        """Instantiate ``cls`` inside one worker; returns a handle whose
        method calls are futures (Ray ``@ray.remote`` class parity).

        ``on_respawn`` (respawn pools): called with the actor's handle after
        the actor is re-instantiated on a revived worker, so the owner can
        restore state the constructor cannot rebuild (e.g. re-push current
        parameter-server weights). The name is reserved — an ``on_respawn``
        constructor kwarg for ``cls`` itself cannot be passed through.
        """
        aid = next(self._aid)
        worker = (next(self._rr) % self.num_workers) if worker is None \
            else worker % self.num_workers
        blob = cloudpickle.dumps((cls, args, kw))
        self._send(worker, "actor_new", blob, aid).result(timeout=120)
        with self._flock:
            self._actors[aid] = {"worker": worker, "blob": blob,
                                 "on_respawn": on_respawn}
        return ActorHandle(self, aid, worker)

    # ------------------------------------------------------------- control
    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        for q in self._inboxes:
            q.put(None)
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for q in self._outboxes:   # release the per-worker collector threads
            try:
                q.put(None)
            except (OSError, ValueError):
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
