"""Orca — unified high-level Estimator + sharded data (reference
``pyzoo/zoo/orca/``: orca/learn estimators over XShards, SURVEY.md §2.7)."""

from ..data.xshards import XShards
from .learn.estimator import Estimator

__all__ = ["Estimator", "XShards"]
