"""Orca — unified high-level Estimator + sharded data (reference
``pyzoo/zoo/orca/``: orca/learn estimators over XShards, SURVEY.md §2.7)."""

from ..data.xshards import XShards
from .learn.estimator import Estimator
from .rl import CatchEnv, PPOTrainer
from .task_pool import ActorHandle, Future, TaskPool, pool_rank, pool_world

__all__ = ["ActorHandle", "CatchEnv", "Estimator", "Future", "PPOTrainer",
           "TaskPool", "XShards", "pool_rank", "pool_world"]
