"""Orca unified Estimator — one fit/evaluate/predict facade over every data form.

Reference parity: ``pyzoo/zoo/orca/learn/tf/estimator.py:29-231`` (``Estimator``
with ``from_graph``/``from_keras`` constructors, fit over XShards or TFDataset,
predict via TFNet) and the pytorch/horovod variants (orca/learn/pytorch/).

TPU-native collapse: TF-graph export and Horovod rendezvous both disappear —
every constructor lands on the same jitted train loop; the Estimator's job is
data marshalling (XShards / pandas / numpy / FeatureSet → device batches).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from ...data.xshards import XShards


def _marshal_shards(data: XShards, feature_cols, label_cols):
    """Collect XShards partitions into (x, y) arrays. Partitions may be pandas
    DataFrames (use feature/label cols), dicts with 'x'/'y', or (x, y) tuples."""
    parts = data.collect()
    xs, ys = [], []
    for p in parts:
        if isinstance(p, dict):
            xs.append(np.asarray(p["x"]))
            if "y" in p and p["y"] is not None:
                ys.append(np.asarray(p["y"]))
        elif isinstance(p, tuple) and len(p) == 2:
            xs.append(np.asarray(p[0]))
            ys.append(np.asarray(p[1]))
        else:  # pandas DataFrame
            if feature_cols is None:
                raise ValueError("feature_cols required for DataFrame shards")
            xs.append(np.stack([p[c].to_numpy(dtype=np.float32)
                                for c in feature_cols], axis=1))
            if label_cols:
                y = np.stack([p[c].to_numpy(dtype=np.float32)
                              for c in label_cols], axis=1)
                ys.append(y)
    x = np.concatenate(xs, axis=0)
    y = np.concatenate(ys, axis=0) if ys else None
    return x, y


def _marshal(data, feature_cols=None, label_cols=None):
    import pandas as pd

    if isinstance(data, XShards):
        return _marshal_shards(data, feature_cols, label_cols)
    if isinstance(data, pd.DataFrame):
        if feature_cols is None:
            raise ValueError("feature_cols required for DataFrame input")
        x = np.stack([data[c].to_numpy(dtype=np.float32)
                      for c in feature_cols], axis=1)
        y = None
        if label_cols:
            y = np.stack([data[c].to_numpy(dtype=np.float32)
                          for c in label_cols], axis=1)
        return x, y
    if isinstance(data, tuple) and len(data) == 2:
        return data
    if isinstance(data, dict):
        return data["x"], data.get("y")
    return data, None  # bare x (predict) or FeatureSet (passed through)


class Estimator:
    """Unified estimator. Build with :meth:`from_keras` (any KerasNet model) or
    :meth:`from_fn` (bare init/apply pair wrapped into a Sequential-like)."""

    def __init__(self, model, loss="mse", optimizer="adam",
                 metrics: Sequence = ()):
        self.model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = list(metrics)
        self._compiled = False

    # ------------------------------------------------------------ constructors
    @staticmethod
    def from_keras(model, loss="mse", optimizer="adam", metrics=()) -> "Estimator":
        """Any KerasNet (Sequential/Model/zoo model) → Estimator
        (orca estimator.py:37 ``from_graph``/``from_keras`` capability)."""
        return Estimator(model, loss=loss, optimizer=optimizer, metrics=metrics)

    # alias covering the reference's separate pytorch entry (the model API here
    # is framework-native either way)
    from_model = from_keras

    def _ensure_compiled(self):
        if not self._compiled:
            self.model.compile(optimizer=self._optimizer, loss=self._loss,
                               metrics=self._metrics)
            self._compiled = True

    # ------------------------------------------------------------------ verbs
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols: Optional[List[str]] = None,
            label_cols: Optional[List[str]] = None,
            validation_data=None) -> "Estimator":
        self._ensure_compiled()
        x, y = _marshal(data, feature_cols, label_cols)
        val = None
        if validation_data is not None:
            val = _marshal(validation_data, feature_cols, label_cols)
        self.model.fit(x, y, batch_size=batch_size, nb_epoch=epochs,
                       validation_data=val)
        return self

    def evaluate(self, data, batch_size: int = 32,
                 feature_cols=None, label_cols=None, metrics=None):
        self._ensure_compiled()
        x, y = _marshal(data, feature_cols, label_cols)
        return self.model.evaluate(
            x, y, batch_size=batch_size,
            metrics=metrics if metrics is not None else (self._metrics or ("mse",)))

    def predict(self, data, batch_size: int = 256, feature_cols=None):
        self._ensure_compiled()
        if isinstance(data, XShards):
            # keep shard structure: one result partition per input partition
            # (RayXShards.transform_shard parity)
            return XShards([np.asarray(self.model.predict(
                _marshal(p, feature_cols, None)[0], batch_size=batch_size))
                for p in data.collect()])
        x, _ = _marshal(data, feature_cols, None)
        return np.asarray(self.model.predict(x, batch_size=batch_size))

    # ------------------------------------------------------------- persistence
    def save(self, path: str):
        self.model.save_model(path)

    def load(self, path: str) -> "Estimator":
        self.model.load_weights(path)
        return self

    def get_model(self):
        return self.model
