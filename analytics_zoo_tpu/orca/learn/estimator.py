"""Orca unified Estimator — one fit/evaluate/predict facade over every data form.

Reference parity: ``pyzoo/zoo/orca/learn/tf/estimator.py:29-231`` (``Estimator``
with ``from_graph``/``from_keras`` constructors, fit over XShards or TFDataset,
predict via TFNet) and the pytorch/horovod variants (orca/learn/pytorch/).

TPU-native collapse: TF-graph export and Horovod rendezvous both disappear —
every constructor lands on the same jitted train loop; the Estimator's job is
data marshalling (XShards / pandas / numpy / FeatureSet → device batches).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ...common import telemetry as _tm
from ...data.xshards import XShards

_ORCA_FITS = _tm.counter("zoo_orca_fit_total",
                         "Orca Estimator.fit invocations", labels=("input",))


def _marshal_shards(data: XShards, feature_cols, label_cols):
    """Collect XShards partitions into (x, y) arrays. Partitions may be pandas
    DataFrames (use feature/label cols), dicts with 'x'/'y', or (x, y) tuples."""
    parts = data.collect()
    xs, ys = [], []
    for p in parts:
        if isinstance(p, np.ndarray):
            xs.append(p)
        elif isinstance(p, dict):
            xs.append(np.asarray(p["x"]))
            if "y" in p and p["y"] is not None:
                ys.append(np.asarray(p["y"]))
        elif isinstance(p, tuple) and len(p) == 2:
            xs.append(np.asarray(p[0]))
            ys.append(np.asarray(p[1]))
        else:  # pandas DataFrame
            if feature_cols is None:
                raise ValueError("feature_cols required for DataFrame shards")
            xs.append(np.stack([p[c].to_numpy(dtype=np.float32)
                                for c in feature_cols], axis=1))
            if label_cols:
                y = np.stack([p[c].to_numpy(dtype=np.float32)
                              for c in label_cols], axis=1)
                ys.append(y)
    x = np.concatenate(xs, axis=0)
    y = np.concatenate(ys, axis=0) if ys else None
    return x, y


def host_sharded_featureset(data: XShards, feature_cols=None, label_cols=None,
                            *, process_index: int, process_count: int):
    """This host's partitions of an XShards → ``FeatureSet.from_host_shard``.

    The multi-host ingest contract: partition ``i`` belongs to host
    ``i % process_count``; each host marshals only its slice and yields its
    local ``batch/process_count`` rows per global step. Lockstep is GUARANTEED
    here: every host deterministically computes all hosts' row counts from the
    shared partition layout and truncates its slice to the minimum, so no host
    can run a trailing step the others skip (which would hang collectives).
    """
    from ...data.featureset import FeatureSet

    def rows(p) -> int:
        if isinstance(p, dict):
            return len(p["x"])
        if isinstance(p, tuple):
            return len(p[0])
        return len(p)

    # counting needs no materialization unless a lazy chain could change
    # partition lengths; raw parts are already resident so len() is free
    parts = data.collect() if data._pending else list(data._parts)
    counts = [sum(rows(p) for p in parts[r::process_count])
              for r in range(process_count)]
    empty = [r for r, c in enumerate(counts) if c == 0]
    if empty:
        raise ValueError(
            f"hosts {empty} would receive no data: {len(parts)} partitions "
            f"over {process_count} hosts (counts={counts}); repartition the "
            f"XShards to at least one non-empty partition per host")
    n_min = min(counts)

    local = data.host_split(process_index, process_count)
    x, y = _marshal_shards(local, feature_cols, label_cols)
    x = x[:n_min]
    tree = (x,) if y is None else (x, y[:n_min])
    return FeatureSet.from_host_shard(tree, process_index=process_index,
                                      process_count=process_count)


def _marshal(data, feature_cols=None, label_cols=None):
    import pandas as pd

    if isinstance(data, XShards):
        return _marshal_shards(data, feature_cols, label_cols)
    if isinstance(data, pd.DataFrame):
        if feature_cols is None:
            raise ValueError("feature_cols required for DataFrame input")
        x = np.stack([data[c].to_numpy(dtype=np.float32)
                      for c in feature_cols], axis=1)
        y = None
        if label_cols:
            y = np.stack([data[c].to_numpy(dtype=np.float32)
                          for c in label_cols], axis=1)
        return x, y
    if isinstance(data, tuple) and len(data) == 2:
        return data
    if isinstance(data, dict):
        return data["x"], data.get("y")
    return data, None  # bare x (predict) or FeatureSet (passed through)


class Estimator:
    """Unified estimator. Build with :meth:`from_keras` (any KerasNet model) or
    :meth:`from_fn` (bare init/apply pair wrapped into a Sequential-like)."""

    def __init__(self, model, loss="mse", optimizer="adam",
                 metrics: Sequence = ()):
        self.model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = list(metrics)
        self._compiled = False

    # ------------------------------------------------------------ constructors
    @staticmethod
    def from_keras(model, loss="mse", optimizer="adam", metrics=()) -> "Estimator":
        """Any KerasNet (Sequential/Model/zoo model) → Estimator
        (orca estimator.py:37 ``from_graph``/``from_keras`` capability)."""
        return Estimator(model, loss=loss, optimizer=optimizer, metrics=metrics)

    # alias covering the reference's separate pytorch entry (the model API here
    # is framework-native either way)
    from_model = from_keras

    def _ensure_compiled(self):
        if not self._compiled:
            self.model.compile(optimizer=self._optimizer, loss=self._loss,
                               metrics=self._metrics)
            self._compiled = True

    # ------------------------------------------------------------------ verbs
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols: Optional[List[str]] = None,
            label_cols: Optional[List[str]] = None,
            validation_data=None,
            host_sharding: Optional[bool] = None,
            prefetch_depth: Optional[int] = None,
            async_checkpoint: Optional[bool] = None,
            grad_accum_steps: Optional[int] = None,
            compute_dtype: Optional[str] = None,
            update_sharding=None) -> "Estimator":
        """``host_sharding`` (default auto: on under a multi-host job): XShards
        input is split by partition across hosts and each host marshals ONLY
        its own slice into a ``FeatureSet.from_host_shard`` — the multi-host
        sharded-ingest path; no host materializes the global dataset.

        ``prefetch_depth`` / ``async_checkpoint`` override the engine
        Estimator's input-pipeline and checkpointing knobs for THIS fit only
        (``prefetch_depth=0`` forces the synchronous data path); the prior
        config values are restored on return.

        ``grad_accum_steps`` / ``compute_dtype`` / ``update_sharding`` set the
        engine's microbatch-accumulation, bf16 mixed-precision, and ZeRO-1
        weight-update-sharding knobs (parallel/update_sharding.py). Unlike
        the per-fit overrides above they are STICKY: they shape the compiled
        step and the optimizer-state/param dtype layout, which the engine
        builds once — so set them on the model's FIRST fit; changing
        ``compute_dtype`` after training started raises."""
        self._ensure_compiled()
        eng = self.model.estimator
        cfg = eng.config
        # validate BEFORE mutating: a rejected call must leave the engine
        # config (and the compiled-step/precision wiring that reads it)
        # exactly as it was
        built = eng.train_state is not None
        if built:
            for name, want, have in (
                    ("grad_accum_steps",
                     None if grad_accum_steps is None
                     else int(grad_accum_steps), cfg.grad_accum_steps),
                    ("update_sharding", update_sharding, cfg.update_sharding),
                    ("compute_dtype", compute_dtype, cfg.compute_dtype)):
                if want is not None and want != have:
                    raise RuntimeError(
                        f"{name} cannot change after training started: the "
                        f"compiled step and state layout are already built")
        saved = (cfg.prefetch_depth, cfg.async_checkpoint)
        if prefetch_depth is not None:
            cfg.prefetch_depth = int(prefetch_depth)
        if async_checkpoint is not None:
            cfg.async_checkpoint = bool(async_checkpoint)
        if (grad_accum_steps is not None
                and int(grad_accum_steps) != cfg.grad_accum_steps):
            cfg.grad_accum_steps = int(grad_accum_steps)
            eng._train_step = None
        if update_sharding is not None and update_sharding != cfg.update_sharding:
            cfg.update_sharding = update_sharding
            eng._train_step = None
        if compute_dtype is not None and compute_dtype != cfg.compute_dtype:
            cfg.compute_dtype = compute_dtype
            eng._refresh_precision()
        _ORCA_FITS.labels(input=type(data).__name__).inc()
        # the fit span shows up in xprof captures and the span recorder; the
        # per-step DataWait/Compute breakdown comes from the engine Estimator
        # underneath (model.fit) and is read back via train_stats()
        try:
            with _tm.span("orca.fit"):
                return self._fit(data, epochs, batch_size, feature_cols,
                                 label_cols, validation_data, host_sharding)
        finally:
            cfg.prefetch_depth, cfg.async_checkpoint = saved

    def _fit(self, data, epochs, batch_size, feature_cols, label_cols,
             validation_data, host_sharding) -> "Estimator":
        if isinstance(data, XShards):
            import jax

            if host_sharding is None:
                host_sharding = jax.process_count() > 1
            if host_sharding:
                fs = host_sharded_featureset(
                    data, feature_cols, label_cols,
                    process_index=jax.process_index(),
                    process_count=jax.process_count())
                val = None
                if validation_data is not None:
                    if isinstance(validation_data, XShards):
                        val = host_sharded_featureset(
                            validation_data, feature_cols, label_cols,
                            process_index=jax.process_index(),
                            process_count=jax.process_count())
                    else:  # arrays: every host evaluates the full set
                        val = _marshal(validation_data, feature_cols,
                                       label_cols)
                self.model.fit(fs, batch_size=batch_size, nb_epoch=epochs,
                               validation_data=val)
                return self
        x, y = _marshal(data, feature_cols, label_cols)
        val = None
        if validation_data is not None:
            val = _marshal(validation_data, feature_cols, label_cols)
        self.model.fit(x, y, batch_size=batch_size, nb_epoch=epochs,
                       validation_data=val)
        return self

    def train_stats(self) -> Dict[str, Any]:
        """The training-side telemetry snapshot (per-step data-wait vs.
        compute histograms, input-pipeline queue/stall/decode metrics,
        compile/rollback counters, checkpoint snapshot-vs-write split) — the
        same numbers the Prometheus endpoint and TensorBoard scalars show."""
        snap = _tm.snapshot()
        return {k: v for k, v in snap.items() if k.startswith("zoo_train_")
                or k.startswith("zoo_data_") or k == "zoo_summary_scalar"}

    def evaluate(self, data, batch_size: int = 32,
                 feature_cols=None, label_cols=None, metrics=None):
        self._ensure_compiled()
        x, y = _marshal(data, feature_cols, label_cols)
        return self.model.evaluate(
            x, y, batch_size=batch_size,
            metrics=metrics if metrics is not None else (self._metrics or ("mse",)))

    def predict(self, data, batch_size: int = 256, feature_cols=None):
        self._ensure_compiled()
        if isinstance(data, XShards):
            # keep shard structure: one result partition per input partition
            # (RayXShards.transform_shard parity)
            return XShards([np.asarray(self.model.predict(
                _marshal(p, feature_cols, None)[0], batch_size=batch_size))
                for p in data.collect()])
        x, _ = _marshal(data, feature_cols, None)
        return np.asarray(self.model.predict(x, batch_size=batch_size))

    # ------------------------------------------------------------- persistence
    def save(self, path: str):
        self.model.save_model(path)

    def load(self, path: str) -> "Estimator":
        self.model.load_weights(path)
        return self

    def get_model(self):
        return self.model
