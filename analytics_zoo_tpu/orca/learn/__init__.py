from .estimator import Estimator

__all__ = ["Estimator"]
