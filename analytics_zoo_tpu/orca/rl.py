"""RL trainers on the task pool — the RayOnSpark + RLlib workload.

The reference hosts RLlib trainers (PPO/DQN on CartPole) on the Ray cluster it
bootstraps inside Spark (`pyzoo/zoo/examples/ray/rllib/multiagent_two_trainers
.py`); the zoo's own role is the cluster runtime, the trainer API comes from
RLlib. Here both halves are native: rollout workers are ``TaskPool`` tasks
and :class:`PPOTrainer` exposes the RLlib-style ``trainer.train() -> result``
loop with a clipped-surrogate PPO update (JAX on the driver, numpy policy in
the workers).

    trainer = PPOTrainer(env_fn=CatchEnv, config={"num_workers": 4})
    for _ in range(20):
        result = trainer.train()
        print(result["episode_reward_mean"])
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

DEFAULT_CONFIG: Dict[str, Any] = {
    "num_workers": 2,            # rollout worker processes
    "episodes_per_worker": 16,   # per train() round
    "gamma": 0.99,
    "lr": 3e-3,
    "clip_param": 0.2,           # PPO clipped-surrogate epsilon
    "num_sgd_iter": 4,
    "hidden": 64,
    "entropy_coeff": 0.01,
    "seed": 0,
}


class CatchEnv:
    """Minimal gym-like env: a ball falls down an H×W grid; the bottom paddle
    moves left/stay/right; +1 for a catch, -1 for a miss. Episodes are H-1
    steps — small enough for CI, structured like the classic control tasks the
    reference's RLlib example trains on."""

    H, W = 8, 8
    n_actions = 3

    def __init__(self, seed: int = 0):
        self.rng = np.random.default_rng(seed)

    @property
    def obs_dim(self) -> int:
        return self.H * self.W

    def reset(self) -> np.ndarray:
        self.ball = [0, int(self.rng.integers(0, self.W))]
        self.paddle = self.W // 2
        return self._obs()

    def _obs(self) -> np.ndarray:
        board = np.zeros((self.H, self.W), dtype="float32")
        board[self.ball[0], self.ball[1]] = 1.0
        board[self.H - 1, self.paddle] = -1.0
        return board.ravel()

    def step(self, action: int):
        self.paddle = int(np.clip(self.paddle + (action - 1), 0, self.W - 1))
        self.ball[0] += 1
        done = self.ball[0] == self.H - 1
        reward = (1.0 if self.ball[1] == self.paddle else -1.0) if done else 0.0
        return self._obs(), reward, done, {}


def _mlp_init(obs_dim: int, hidden: int, n_actions: int, seed: int):
    rng = np.random.default_rng(seed)
    s1 = np.sqrt(2.0 / obs_dim)
    s2 = np.sqrt(2.0 / hidden)
    return {
        "w1": (rng.standard_normal((obs_dim, hidden)) * s1).astype("float32"),
        "b1": np.zeros(hidden, "float32"),
        "w2": (rng.standard_normal((hidden, n_actions)) * s2).astype("float32"),
        "b2": np.zeros(n_actions, "float32"),
        "vw": (rng.standard_normal((hidden, 1)) * s2).astype("float32"),
        "vb": np.zeros(1, "float32"),
    }


def _np_forward(w, obs):
    """Numpy policy+value forward for the rollout workers (no jit per round)."""
    h = np.tanh(obs @ w["w1"] + w["b1"])
    logits = h @ w["w2"] + w["b2"]
    z = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = z / z.sum(axis=-1, keepdims=True)
    value = (h @ w["vw"] + w["vb"])[..., 0]
    return probs, value


def collect_rollouts(weights, env_fn, n_episodes: int, gamma: float,
                     seed: int):
    """Task body: play episodes, return (obs, act, logp, returns, adv, rew)."""
    obs_l: List[np.ndarray] = []
    act_l: List[int] = []
    logp_l: List[float] = []
    ret_l: List[float] = []
    adv_l: List[float] = []
    ep_rewards: List[float] = []
    for k in range(n_episodes):
        env = env_fn(seed * 100_003 + k)
        rng = np.random.default_rng(seed * 7919 + k)
        obs = env.reset()
        ep_obs, ep_act, ep_logp, ep_val, ep_rew = [], [], [], [], []
        while True:
            probs, value = _np_forward(weights, obs[None, :])
            a = int(rng.choice(len(probs[0]), p=probs[0]))
            ep_obs.append(obs)
            ep_act.append(a)
            ep_logp.append(float(np.log(probs[0, a] + 1e-9)))
            ep_val.append(float(value[0]))
            obs, r, done, _ = env.step(a)
            ep_rew.append(float(r))
            if done:
                break
        # discounted returns + advantages vs the value baseline
        ret, g = [], 0.0
        for r in reversed(ep_rew):
            g = r + gamma * g
            ret.append(g)
        ret.reverse()
        obs_l.extend(ep_obs)
        act_l.extend(ep_act)
        logp_l.extend(ep_logp)
        ret_l.extend(ret)
        adv_l.extend(np.asarray(ret) - np.asarray(ep_val))
        ep_rewards.append(sum(ep_rew))
    return (np.asarray(obs_l, "float32"), np.asarray(act_l, "int32"),
            np.asarray(logp_l, "float32"), np.asarray(ret_l, "float32"),
            np.asarray(adv_l, "float32"), float(np.mean(ep_rewards)))


class PPOTrainer:
    """RLlib-style trainer: ``train()`` runs one round of parallel rollouts +
    clipped-surrogate PPO epochs and returns a result dict."""

    def __init__(self, env_fn: Callable[[int], Any] = CatchEnv,
                 config: Optional[Dict[str, Any]] = None, pool=None):
        import jax

        self.config = {**DEFAULT_CONFIG, **(config or {})}
        self.env_fn = env_fn
        probe = env_fn(0)
        self.weights = _mlp_init(probe.obs_dim, self.config["hidden"],
                                 probe.n_actions, self.config["seed"])
        self._pool = pool
        self._owns_pool = pool is None
        self.iteration = 0
        self._grad_fn = jax.jit(jax.grad(self._ppo_loss))
        import optax

        self._opt = optax.adam(self.config["lr"])
        self._opt_state = self._opt.init(
            {k: np.asarray(v) for k, v in self.weights.items()})

    # -- loss (driver-side JAX) ----------------------------------------------
    def _ppo_loss(self, w, obs, act, logp_old, ret, adv):
        import jax
        import jax.numpy as jnp

        h = jnp.tanh(obs @ w["w1"] + w["b1"])
        logits = h @ w["w2"] + w["b2"]
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        logp = jnp.take_along_axis(logp_all, act[:, None], axis=1)[:, 0]
        ratio = jnp.exp(logp - logp_old)
        eps = self.config["clip_param"]
        surr = jnp.minimum(ratio * adv,
                           jnp.clip(ratio, 1 - eps, 1 + eps) * adv)
        value = (h @ w["vw"] + w["vb"])[:, 0]
        v_loss = jnp.mean((value - ret) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        return (-jnp.mean(surr) + 0.5 * v_loss
                - self.config["entropy_coeff"] * entropy)

    # -- public API ----------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        from .task_pool import TaskPool

        cfg = self.config
        if self._pool is None:
            self._pool = TaskPool(cfg["num_workers"])
        futs = [self._pool.submit(collect_rollouts, self.weights, self.env_fn,
                                  cfg["episodes_per_worker"], cfg["gamma"],
                                  cfg["seed"] * 1000 + self.iteration * 17
                                  + wid)
                for wid in range(cfg["num_workers"])]
        parts = [f.result(timeout=600) for f in futs]
        obs = np.concatenate([p[0] for p in parts])
        act = np.concatenate([p[1] for p in parts])
        logp = np.concatenate([p[2] for p in parts])
        ret = np.concatenate([p[3] for p in parts])
        adv = np.concatenate([p[4] for p in parts])
        adv = (adv - adv.mean()) / (adv.std() + 1e-6)
        reward_mean = float(np.mean([p[5] for p in parts]))

        import jax
        import optax

        w = {k: np.asarray(v) for k, v in self.weights.items()}
        for _ in range(cfg["num_sgd_iter"]):
            grads = self._grad_fn(w, obs, act, logp, ret, adv)
            updates, self._opt_state = self._opt.update(grads, self._opt_state, w)
            w = optax.apply_updates(w, updates)
        self.weights = {k: np.asarray(v) for k, v in jax.device_get(w).items()}
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": reward_mean,
            "episodes_this_iter": cfg["num_workers"] * cfg["episodes_per_worker"],
            "timesteps_this_iter": int(len(obs)),
        }

    def get_weights(self):
        return dict(self.weights)

    def set_weights(self, weights):
        """Weight sync between trainers (the multiagent_two_trainers
        periodic-sync pattern)."""
        self.weights = {k: np.asarray(v) for k, v in weights.items()}

    def stop(self):
        if self._pool is not None and self._owns_pool:
            self._pool.shutdown()
        self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
