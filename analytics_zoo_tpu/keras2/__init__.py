"""Keras-2 API (reference ``zoo/.../api/keras2/layers/`` — 21 layer files —
plus ``pyzoo/zoo/pipeline/api/keras2/``).

Carries real keras-2 SEMANTICS, not just names:
* ``units``/``filters``/``rate``/``kernel_size`` argument conventions;
* separate ``kernel_initializer`` / ``bias_initializer`` /
  ``recurrent_initializer`` (plumbed into the layer library's ``init`` /
  ``bias_init`` / ``inner_init``), ``unit_forget_bias`` on LSTM;
* ``data_format='channels_first'|'channels_last'`` on conv/pooling layers —
  channels_first inputs are transposed to the TPU-native channels-last layout
  on entry and back on exit by :class:`ChannelsFirstWrapper`, so graphs written
  against either convention run unchanged;
* the keras-2 merge layers (Add/Average/Maximum/Minimum/Multiply/Concatenate).

Every reference keras2 layer file has a counterpart here: Activation, Average,
AveragePooling1D, Conv1D, Conv2D, Cropping1D, Dense, Dropout, Flatten,
GlobalAveragePooling1D/2D/3D, GlobalMaxPooling1D/2D/3D, LocallyConnected1D,
MaxPooling1D, Maximum, Minimum, Softmax (+ the 2D pooling/norm/recurrent set
the python mirror exposes).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp

from ..nn import layers as L
from ..nn.graph import Input
from ..nn.module import Layer
from ..nn.topology import Model, Sequential

__all__ = [
    "Activation", "Add", "Average", "AveragePooling1D", "AveragePooling2D",
    "BatchNormalization", "Bidirectional", "ChannelsFirstWrapper",
    "Concatenate", "Conv1D", "Conv2D", "Cropping1D", "Dense", "Dropout",
    "Embedding", "Flatten", "GRU", "GlobalAveragePooling1D",
    "GlobalAveragePooling2D", "GlobalAveragePooling3D", "GlobalMaxPooling1D",
    "GlobalMaxPooling2D", "GlobalMaxPooling3D", "Input", "InputLayer", "LSTM",
    "LayerNormalization", "LocallyConnected1D", "MaxPooling1D", "MaxPooling2D",
    "Maximum", "Minimum", "Model", "Multiply", "Reshape", "Sequential",
    "SimpleRNN", "Softmax", "TimeDistributed",
]

InputLayer = L.InputLayer
Activation = L.Activation
Flatten = L.Flatten
Reshape = L.Reshape
Bidirectional = L.Bidirectional
TimeDistributed = L.TimeDistributed
LayerNormalization = L.LayerNormalization
Softmax = L.Softmax
GlobalMaxPooling3D = L.GlobalMaxPooling3D
GlobalAveragePooling3D = L.GlobalAveragePooling3D


def _pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


class ChannelsFirstWrapper(Layer):
    """Run a channels-last inner layer on channels-first data: transpose NC* →
    N*C on entry and back on exit (keras-2 ``data_format`` semantics over the
    TPU-native layout; XLA folds the transposes into layout assignment)."""

    def __init__(self, inner: Layer, name=None, input_shape=None):
        # adopt the inner layer's input_shape hint (channels-FIRST convention
        # at the wrapper boundary) so Conv2D(..., data_format='channels_first',
        # input_shape=...) works as the first Sequential layer
        if input_shape is None and inner.input_shape_hint is not None:
            input_shape = inner.input_shape_hint
            inner.input_shape_hint = None   # inner sees channels-last shapes
        super().__init__(name=name or inner.name + "_ch_first",
                         input_shape=input_shape)
        self.inner = inner

    @staticmethod
    def _to_last(shape):
        return tuple(shape[1:]) + (shape[0],)

    def build(self, rng, input_shape):
        return self.inner.build(rng, self._to_last(input_shape))

    def apply(self, params, state, x, *, training=False, rng=None):
        nd = x.ndim
        x = jnp.transpose(x, (0,) + tuple(range(2, nd)) + (1,))
        y, state = self.inner.apply(params, state, x, training=training,
                                    rng=rng)
        if y.ndim == nd:   # global pooling collapses to (B, C): no transpose
            y = jnp.transpose(y, (0, y.ndim - 1) + tuple(range(1, y.ndim - 1)))
        return y, state

    def compute_output_shape(self, input_shape):
        out = self.inner.compute_output_shape(self._to_last(input_shape))
        if len(out) == len(input_shape):
            return (out[-1],) + tuple(out[:-1])
        return tuple(out)


def _df(layer: Layer, data_format: Optional[str]) -> Layer:
    if data_format in (None, "channels_last"):
        return layer
    if data_format == "channels_first":
        return ChannelsFirstWrapper(layer)
    raise ValueError(f"data_format must be 'channels_first'|'channels_last', "
                     f"got {data_format!r}")


# ------------------------------------------------------------------------ core

def Dense(units: int, activation=None, use_bias: bool = True,
          kernel_initializer="glorot_uniform", bias_initializer="zeros",
          kernel_regularizer=None, bias_regularizer=None, input_shape=None,
          name=None):
    return L.Dense(units, activation=activation, use_bias=use_bias,
                   init=kernel_initializer, bias_init=bias_initializer,
                   w_regularizer=kernel_regularizer,
                   b_regularizer=bias_regularizer,
                   input_shape=input_shape, name=name)


def Dropout(rate: float, name=None, input_shape=None):
    return L.Dropout(rate, name=name, input_shape=input_shape)


# ------------------------------------------------------------------------ conv

def Conv1D(filters: int, kernel_size: int, strides: int = 1,
           padding: str = "valid", activation=None, use_bias: bool = True,
           kernel_initializer="glorot_uniform", bias_initializer="zeros",
           input_shape=None, name=None):
    return L.Convolution1D(filters, kernel_size, activation=activation,
                           border_mode=padding, subsample_length=strides,
                           init=kernel_initializer,
                           bias_init=bias_initializer, use_bias=use_bias,
                           input_shape=input_shape, name=name)


def Conv2D(filters: int, kernel_size, strides=(1, 1), padding: str = "valid",
           data_format: Optional[str] = None, activation=None,
           use_bias: bool = True, kernel_initializer="glorot_uniform",
           bias_initializer="zeros", input_shape=None, name=None):
    kh, kw = _pair(kernel_size)
    return _df(L.Convolution2D(filters, kh, kw, activation=activation,
                               border_mode=padding, subsample=_pair(strides),
                               init=kernel_initializer,
                               bias_init=bias_initializer, use_bias=use_bias,
                               input_shape=input_shape, name=name),
               data_format)


def LocallyConnected1D(filters: int, kernel_size: int, strides: int = 1,
                       activation=None, use_bias: bool = True,
                       kernel_initializer="glorot_uniform", input_shape=None,
                       name=None):
    return L.LocallyConnected1D(filters, kernel_size,
                                subsample_length=strides,
                                activation=activation,
                                init=kernel_initializer, use_bias=use_bias,
                                input_shape=input_shape, name=name)


def Cropping1D(cropping=(1, 1), name=None, input_shape=None):
    return L.Cropping1D(cropping=cropping, name=name, input_shape=input_shape)


# --------------------------------------------------------------------- pooling

def MaxPooling1D(pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", name=None, input_shape=None):
    return L.MaxPooling1D(pool_length=pool_size, stride=strides,
                          border_mode=padding, name=name,
                          input_shape=input_shape)


def AveragePooling1D(pool_size: int = 2, strides: Optional[int] = None,
                     padding: str = "valid", name=None, input_shape=None):
    return L.AveragePooling1D(pool_length=pool_size, stride=strides,
                              border_mode=padding, name=name,
                              input_shape=input_shape)


def MaxPooling2D(pool_size=(2, 2), strides=None, padding: str = "valid",
                 data_format: Optional[str] = None, name=None,
                 input_shape=None):
    return _df(L.MaxPooling2D(pool_size=_pair(pool_size),
                              strides=None if strides is None else _pair(strides),
                              border_mode=padding, name=name,
                              input_shape=input_shape), data_format)


def AveragePooling2D(pool_size=(2, 2), strides=None, padding: str = "valid",
                     data_format: Optional[str] = None, name=None,
                     input_shape=None):
    return _df(L.AveragePooling2D(
        pool_size=_pair(pool_size),
        strides=None if strides is None else _pair(strides),
        border_mode=padding, name=name, input_shape=input_shape), data_format)


def GlobalAveragePooling1D(name=None, input_shape=None):
    return L.GlobalAveragePooling1D(name=name, input_shape=input_shape)


def GlobalMaxPooling1D(name=None, input_shape=None):
    return L.GlobalMaxPooling1D(name=name, input_shape=input_shape)


def GlobalAveragePooling2D(data_format: Optional[str] = None, name=None,
                           input_shape=None):
    return _df(L.GlobalAveragePooling2D(name=name, input_shape=input_shape),
               data_format)


def GlobalMaxPooling2D(data_format: Optional[str] = None, name=None,
                       input_shape=None):
    return _df(L.GlobalMaxPooling2D(name=name, input_shape=input_shape),
               data_format)


# ------------------------------------------------------------------ norm / emb

def BatchNormalization(momentum: float = 0.99, epsilon: float = 1e-3,
                       name=None, input_shape=None):
    return L.BatchNormalization(momentum=momentum, epsilon=epsilon, name=name,
                                input_shape=input_shape)


def Embedding(input_dim: int, output_dim: int, input_length=None,
              embeddings_initializer="uniform", name=None):
    shape = (input_length,) if input_length is not None else None
    return L.Embedding(input_dim, output_dim, init=embeddings_initializer,
                       name=name, input_shape=shape)


# ------------------------------------------------------------------- recurrent

def LSTM(units: int, activation="tanh", recurrent_activation="hard_sigmoid",
         kernel_initializer="glorot_uniform",
         recurrent_initializer="glorot_uniform", bias_initializer="zeros",
         unit_forget_bias: bool = True, return_sequences: bool = False,
         go_backwards: bool = False, name=None, input_shape=None):
    return L.LSTM(units, activation=activation,
                  inner_activation=recurrent_activation,
                  init=kernel_initializer, inner_init=recurrent_initializer,
                  bias_init=bias_initializer,
                  unit_forget_bias=unit_forget_bias,
                  return_sequences=return_sequences, go_backwards=go_backwards,
                  name=name, input_shape=input_shape)


def GRU(units: int, activation="tanh", recurrent_activation="hard_sigmoid",
        kernel_initializer="glorot_uniform",
        recurrent_initializer="glorot_uniform", bias_initializer="zeros",
        return_sequences: bool = False, go_backwards: bool = False,
        name=None, input_shape=None):
    return L.GRU(units, activation=activation,
                 inner_activation=recurrent_activation,
                 init=kernel_initializer, inner_init=recurrent_initializer,
                 bias_init=bias_initializer,
                 return_sequences=return_sequences, go_backwards=go_backwards,
                 name=name, input_shape=input_shape)


def SimpleRNN(units: int, activation="tanh",
              kernel_initializer="glorot_uniform",
              recurrent_initializer="glorot_uniform",
              return_sequences: bool = False, name=None, input_shape=None):
    return L.SimpleRNN(units, activation=activation,
                       init=kernel_initializer,
                       inner_init=recurrent_initializer,
                       return_sequences=return_sequences, name=name,
                       input_shape=input_shape)


# ----------------------------------------------------------------------- merge

def Concatenate(axis: int = -1, name=None):
    return L.Merge(mode="concat", concat_axis=axis, name=name)


def Add(name=None):
    return L.Merge(mode="sum", name=name)


def Multiply(name=None):
    return L.Merge(mode="mul", name=name)


def Maximum(name=None):
    return L.Merge(mode="max", name=name)


def Minimum(name=None):
    return L.Merge(mode="min", name=name)


def Average(name=None):
    return L.Merge(mode="ave", name=name)
