"""Keras-2-style API facade (reference ``zoo/.../api/keras2/`` +
``pyzoo/zoo/pipeline/api/keras2/``: the keras-2 naming/argument conventions on
top of the keras-1-style core — ``units``/``filters``/``rate``/``kernel_size``
instead of ``output_dim``/``nb_filter``/``p``).

Every symbol is a thin constructor adapter over the canonical layer library, so
keras2 and keras1 layers mix freely in one model.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

from ..nn import layers as L
from ..nn.graph import Input
from ..nn.topology import Model, Sequential

__all__ = ["Dense", "Dropout", "Activation", "Flatten", "Reshape",
           "Conv1D", "Conv2D", "MaxPooling1D", "MaxPooling2D",
           "AveragePooling1D", "AveragePooling2D", "GlobalAveragePooling2D",
           "GlobalMaxPooling2D", "BatchNormalization", "LayerNormalization",
           "Embedding", "LSTM", "GRU", "SimpleRNN", "Bidirectional",
           "TimeDistributed", "Concatenate", "Add", "Multiply", "Maximum",
           "Average", "Input", "Model", "Sequential", "InputLayer"]

InputLayer = L.InputLayer
Activation = L.Activation
Flatten = L.Flatten
Reshape = L.Reshape
Bidirectional = L.Bidirectional
TimeDistributed = L.TimeDistributed
LayerNormalization = L.LayerNormalization
GlobalAveragePooling2D = L.GlobalAveragePooling2D
GlobalMaxPooling2D = L.GlobalMaxPooling2D


def _pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def Dense(units: int, activation=None, use_bias: bool = True,
          kernel_initializer="glorot_uniform", input_shape=None, name=None):
    return L.Dense(units, activation=activation, use_bias=use_bias,
                   init=kernel_initializer, input_shape=input_shape, name=name)


def Dropout(rate: float, name=None, input_shape=None):
    return L.Dropout(rate, name=name, input_shape=input_shape)


def Conv1D(filters: int, kernel_size: int, strides: int = 1,
           padding: str = "valid", activation=None, use_bias: bool = True,
           input_shape=None, name=None):
    return L.Convolution1D(filters, kernel_size, activation=activation,
                           border_mode=padding, subsample_length=strides,
                           use_bias=use_bias, input_shape=input_shape,
                           name=name)


def Conv2D(filters: int, kernel_size, strides=(1, 1), padding: str = "valid",
           activation=None, use_bias: bool = True, input_shape=None, name=None):
    kh, kw = _pair(kernel_size)
    return L.Convolution2D(filters, kh, kw, activation=activation,
                           border_mode=padding, subsample=_pair(strides),
                           use_bias=use_bias, input_shape=input_shape,
                           name=name)


def MaxPooling1D(pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", name=None, input_shape=None):
    return L.MaxPooling1D(pool_length=pool_size, stride=strides,
                          border_mode=padding, name=name,
                          input_shape=input_shape)


def AveragePooling1D(pool_size: int = 2, strides: Optional[int] = None,
                     padding: str = "valid", name=None, input_shape=None):
    return L.AveragePooling1D(pool_length=pool_size, stride=strides,
                              border_mode=padding, name=name,
                              input_shape=input_shape)


def MaxPooling2D(pool_size=(2, 2), strides=None, padding: str = "valid",
                 name=None, input_shape=None):
    return L.MaxPooling2D(pool_size=_pair(pool_size),
                          strides=None if strides is None else _pair(strides),
                          border_mode=padding, name=name,
                          input_shape=input_shape)


def AveragePooling2D(pool_size=(2, 2), strides=None, padding: str = "valid",
                     name=None, input_shape=None):
    return L.AveragePooling2D(pool_size=_pair(pool_size),
                              strides=None if strides is None else _pair(strides),
                              border_mode=padding, name=name,
                              input_shape=input_shape)


def BatchNormalization(momentum: float = 0.99, epsilon: float = 1e-3,
                       name=None, input_shape=None):
    return L.BatchNormalization(momentum=momentum, epsilon=epsilon, name=name,
                                input_shape=input_shape)


def Embedding(input_dim: int, output_dim: int, input_length=None,
              embeddings_initializer="uniform", name=None):
    shape = (input_length,) if input_length is not None else None
    return L.Embedding(input_dim, output_dim, init=embeddings_initializer,
                       name=name, input_shape=shape)


def LSTM(units: int, activation="tanh", recurrent_activation="hard_sigmoid",
         return_sequences: bool = False, go_backwards: bool = False,
         name=None, input_shape=None):
    return L.LSTM(units, activation=activation,
                  inner_activation=recurrent_activation,
                  return_sequences=return_sequences, go_backwards=go_backwards,
                  name=name, input_shape=input_shape)


def GRU(units: int, activation="tanh", recurrent_activation="hard_sigmoid",
        return_sequences: bool = False, go_backwards: bool = False,
        name=None, input_shape=None):
    return L.GRU(units, activation=activation,
                 inner_activation=recurrent_activation,
                 return_sequences=return_sequences, go_backwards=go_backwards,
                 name=name, input_shape=input_shape)


def SimpleRNN(units: int, activation="tanh", return_sequences: bool = False,
              name=None, input_shape=None):
    return L.SimpleRNN(units, activation=activation,
                       return_sequences=return_sequences, name=name,
                       input_shape=input_shape)


def Concatenate(axis: int = -1, name=None):
    return L.Merge(mode="concat", concat_axis=axis, name=name)


def Add(name=None):
    return L.Merge(mode="sum", name=name)


def Multiply(name=None):
    return L.Merge(mode="mul", name=name)


def Maximum(name=None):
    return L.Merge(mode="max", name=name)


def Average(name=None):
    return L.Merge(mode="ave", name=name)
