"""ctypes loader + pythonic wrappers for the zoo_native C++ runtime."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import tempfile
from typing import Optional

from ..common.locks import traced_lock

import numpy as np

log = logging.getLogger("analytics_zoo_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "zoo_native.cpp")
_SO_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_SO = os.path.join(_SO_DIR, "zoo_native.so")

_lib = None
# zoo-lock: leaf
_lib_lock = traced_lock("lib._lib_lock")
_build_failed = False


def _compile() -> Optional[str]:
    os.makedirs(_SO_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _SO]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
    except (FileNotFoundError, subprocess.TimeoutExpired) as e:
        log.warning("native build unavailable (%s); using numpy fallback", e)
        return None
    if r.returncode != 0:
        log.warning("native build failed; using numpy fallback:\n%s",
                    r.stderr.decode()[-2000:])
        return None
    return _SO


def _load():
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        if os.path.exists(_SO) and (
                not os.path.exists(_SRC)  # shipped .so without sources
                or os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
            so = _SO
        elif os.path.exists(_SRC):
            so = _compile()
        else:
            log.warning("native sources and prebuilt .so both missing; "
                        "using numpy fallback")
            so = None
        if so is None:
            _build_failed = True
            return None
        try:
            _lib = _bind(ctypes.CDLL(so))
        except Exception as e:
            # bad/foreign-arch/stale-ABI .so: try one rebuild, else fall back
            log.warning("prebuilt native lib unusable (%s); %s", e,
                        "rebuilding" if os.path.exists(_SRC) else
                        "using numpy fallback")
            if os.path.exists(_SRC):
                # only discard the .so when we can rebuild it — a transient
                # dlopen failure must not destroy a shipped prebuilt forever
                try:
                    os.remove(so)
                except OSError:
                    pass
                rebuilt = _compile()
            else:
                rebuilt = None
            if rebuilt is None:
                _build_failed = True
                return None
            try:
                _lib = _bind(ctypes.CDLL(rebuilt))
            except Exception as e2:
                log.warning("rebuilt native lib unusable (%s); numpy fallback", e2)
                _build_failed = True
                return None
        return _lib


def _bind(lib):
    """Declare signatures + ABI check; raises on any mismatch (caller handles)."""
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_create.argtypes = [ctypes.c_size_t, ctypes.c_char_p]
    lib.arena_alloc.restype = ctypes.c_int64
    lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.arena_base.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.arena_base.argtypes = [ctypes.c_void_p]
    for fn in ("arena_used", "arena_capacity"):
        getattr(lib, fn).restype = ctypes.c_int64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.arena_reset.argtypes = [ctypes.c_void_p]
    lib.arena_destroy.argtypes = [ctypes.c_void_p]
    lib.arena_flush.restype = ctypes.c_int
    lib.arena_flush.argtypes = [ctypes.c_void_p]
    lib.gather_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int]
    lib.scale_shift_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_float,
        ctypes.c_float, ctypes.c_int]
    lib.zoo_native_abi_version.restype = ctypes.c_int
    if lib.zoo_native_abi_version() != 1:
        raise RuntimeError("zoo_native ABI version mismatch")
    return lib


def native_available() -> bool:
    return _load() is not None


def num_gather_threads() -> int:
    env = os.environ.get("ZOO_TPU_GATHER_THREADS")
    if env:
        return max(1, int(env))
    return max(1, min(8, (os.cpu_count() or 2) // 2))


class HostArena:
    """64-byte-aligned bump allocator over one mmap region; file-backed when
    ``backing_path`` is given (NVMe/pmem-mount tier). Allocations return numpy
    views into the arena (zero-copy)."""

    def __init__(self, capacity_bytes: int, backing_path: Optional[str] = None):
        self._lib = _load()
        self.capacity = int(capacity_bytes)
        self.backing_path = backing_path
        if self._lib is None:
            self._handle = None
            self._buf = (np.memmap(backing_path, dtype=np.uint8, mode="w+",
                                   shape=(self.capacity,))
                         if backing_path else np.zeros(self.capacity, np.uint8))
            self._used = 0
        else:
            self._handle = ctypes.c_void_p(self._lib.arena_create(
                self.capacity,
                backing_path.encode() if backing_path else None))
            if not self._handle.value:
                raise MemoryError(f"arena_create({capacity_bytes}) failed")
            base = self._lib.arena_base(self._handle)
            self._buf = np.ctypeslib.as_array(base, shape=(self.capacity,))

    def alloc(self, shape, dtype=np.float32) -> np.ndarray:
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        if self._lib is None:
            aligned = (self._used + 63) & ~63
            if aligned + nbytes > self.capacity:
                raise MemoryError("arena full")
            self._used = aligned + nbytes
            view = self._buf[aligned:aligned + nbytes]
        else:
            off = self._lib.arena_alloc(self._handle, nbytes)
            if off < 0:
                raise MemoryError("arena full")
            view = self._buf[off:off + nbytes]
        return view.view(dtype).reshape(shape)

    @property
    def used(self) -> int:
        if self._lib is None:
            return self._used
        return int(self._lib.arena_used(self._handle))

    def reset(self):
        if self._lib is None:
            self._used = 0
        else:
            self._lib.arena_reset(self._handle)

    def flush(self):
        """msync file-backed contents (durability point — pmem parity)."""
        if self._lib is None:
            if hasattr(self._buf, "flush"):
                self._buf.flush()
        else:
            if self._lib.arena_flush(self._handle) != 0:
                raise OSError("msync failed")

    def close(self):
        """EXPLICITLY unmap the arena. Every array returned by :meth:`alloc`
        becomes invalid (views point into the unmapped region — reading them
        afterwards is undefined). There is deliberately no ``__del__``: GC-time
        munmap under live numpy views would segfault; an unclosed arena is
        reclaimed at process exit instead."""
        if self._lib is not None and self._handle and self._handle.value:
            self._lib.arena_destroy(self._handle)
            self._handle = ctypes.c_void_p(None)
        self._buf = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def gather_rows(src: np.ndarray, indices: np.ndarray,
                out: Optional[np.ndarray] = None,
                threads: Optional[int] = None) -> np.ndarray:
    """``out[i] = src[indices[i]]`` over axis 0 — threaded memcpy when the
    native lib is available, ``src[indices]`` otherwise."""
    src = np.ascontiguousarray(src)
    if src.dtype.hasobject:
        # the C++ path memcpy's PyObject POINTERS without increfs — freeing
        # the gathered array would then decref objects it never owned
        res = src[np.asarray(indices, dtype=np.int64)]
        if out is not None:
            out[...] = res
            return out
        return res
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    n_rows = len(src)
    # numpy semantics for negative indices; hard bounds check BEFORE the native
    # call (C++ memcpy would read out of bounds instead of raising)
    if idx.size:
        idx = np.where(idx < 0, idx + n_rows, idx)
        lo, hi = int(idx.min()), int(idx.max())
        if lo < 0 or hi >= n_rows:
            raise IndexError(f"index {hi if hi >= n_rows else lo - n_rows} out "
                             f"of bounds for axis 0 with size {n_rows}")
    lib = _load()
    if lib is None:
        res = src[idx]
        if out is not None:
            out[...] = res
            return out
        return res
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    if out is None:
        out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    if not out.flags["C_CONTIGUOUS"]:
        raise ValueError("out must be C-contiguous")
    lib.gather_rows(src.ctypes.data, row_bytes, idx.ctypes.data, len(idx),
                    out.ctypes.data, threads or num_gather_threads())
    return out


class NativeSampleCache:
    """Arena-resident copy of an array tree with double-buffered batch staging:
    ``batch(indices)`` gathers rows into one of two reusable staging buffers
    (threaded), so consecutive batches don't allocate and the previous batch
    stays valid while the device transfer of the current one is in flight."""

    def __init__(self, arrays, backing_path: Optional[str] = None,
                 batch_capacity: int = 0):
        import jax

        leaves, self._treedef = jax.tree_util.tree_flatten(arrays)
        total = sum(a.nbytes + 64 for a in leaves)
        self.arena = HostArena(total + 4096, backing_path)
        self._store = []
        for a in leaves:
            dst = self.arena.alloc(a.shape, a.dtype)
            np.copyto(dst, a)
            self._store.append(dst)
        self._staging = [None, None]
        self._flip = 0
        self._batch_capacity = batch_capacity

    @property
    def arrays(self):
        import jax

        return jax.tree_util.tree_unflatten(self._treedef, self._store)

    def batch(self, indices: np.ndarray):
        import jax

        n = len(indices)
        cap = max(n, self._batch_capacity)
        if self._staging[self._flip] is None or \
                len(self._staging[self._flip][0]) < n:
            self._staging[self._flip] = [
                np.empty((cap,) + a.shape[1:], dtype=a.dtype)
                for a in self._store]
        bufs = self._staging[self._flip]
        self._flip ^= 1
        outs = [gather_rows(a, indices, out=b[:n])
                for a, b in zip(self._store, bufs)]
        return jax.tree_util.tree_unflatten(self._treedef, outs)

    def close(self):
        self._store = []
        self.arena.close()
