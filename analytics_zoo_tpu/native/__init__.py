"""Native host runtime — ctypes bindings over ``native/zoo_native.cpp``.

The C++ side (SURVEY.md §2.11 item 5 — the PMem/memkind allocator equivalent)
provides the mmap arena and threaded row gather; this module compiles it on
first use (g++, cached .so) and degrades gracefully to numpy when no compiler
is available (``native_available()`` → False, all APIs keep working).
"""

from .lib import (HostArena, NativeSampleCache, gather_rows, native_available,
                  num_gather_threads)

__all__ = ["HostArena", "NativeSampleCache", "gather_rows", "native_available",
           "num_gather_threads"]
