"""InferenceModel — multi-backend, concurrency-bounded predictor.

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/pipeline/
inference/InferenceModel.scala:33-499 — the reference keeps a
``LinkedBlockingQueue`` pool of model replicas (default ``concurrentNum=20``),
borrows one per ``doPredict`` call, and auto-scales by cloning on demand; loaders
cover BigDL/Caffe/OpenVINO/TF/PyTorch formats.

TPU-native design
-----------------
* One set of weights lives in device HBM; XLA executables are reentrant, so
  "replicas" collapse to a single compiled program guarded by a semaphore that
  reproduces the reference's bounded-concurrency semantics (and its pool
  metrics) without duplicating memory.
* ``jit`` specialises on shape. To keep latency predictable under ragged request
  sizes, inputs are padded up to a small ladder of batch buckets (1,2,4,...,
  ``max_batch``) so at most ``log2(max_batch)+1`` executables ever compile;
  outputs are sliced back. This replaces the reference's per-replica TF/OpenVINO
  sessions with AOT-warmed XLA programs.
* The OpenVINO-Int8 capability (InferenceModel.doLoadOpenVINOInt8) maps to
  REAL int8 compute for native modules: Dense / Convolution2D kernels pack to
  per-channel int8 and the forward runs on the MXU's int8 path with dynamic
  activation quantization (ops/int8.py) — the "up to 2×" speedup property,
  not just the 4× size cut. Imported graphs (load_fn/TF) fall back to
  weight-only packing with on-the-fly dequantization (HBM footprint /4;
  bandwidth-bound layers speed up).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import memwitness as _mw
from ..common import telemetry as _tm
from ..common.locks import traced_lock
from .summary import InferenceSummary, timing

_COMPILES = _tm.counter("zoo_infer_compiles_total",
                        "Bucketed executables built by InferenceModel "
                        "(flat under steady traffic = no mid-stream "
                        "recompiles)")
_CACHE_HITS = _tm.counter("zoo_infer_cache_hits_total",
                          "Dispatches served by a compiled-cache dict lookup")


def _buckets(max_batch: int) -> List[int]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def _pad_to(arr: np.ndarray, n: int) -> np.ndarray:
    if arr.shape[0] == n:
        return arr
    pad = [(0, n - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


@jax.jit
def _scatter_rows(table, idx, rows):
    # specializes per (leaf aval, touched-row count) — the count varies per
    # publish, but embedding deltas dominate and the scatter itself is tiny
    return table.at[idx].set(rows)


def _quantize_leaf(w: np.ndarray) -> Dict[str, np.ndarray]:
    """Per-output-channel symmetric int8 (channels = last dim)."""
    scale = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = np.maximum(scale, 1e-8) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {"q": q, "scale": scale.astype(np.float32)}


def _quantize_module_params(module, params, min_elements: int):
    """Pack the int8-computable kernels of a native module tree; returns
    ``(packed_params, n_packed)``.

    Only layers whose forward actually implements the int8 path are packed —
    the check is the UNOVERRIDDEN ``apply`` (a subclass with its own forward,
    e.g. an atrous variant, would crash on a packed kernel and stays float).
    """
    from ..nn.layers.convolution import Convolution2D
    from ..nn.layers.core import Dense
    from ..ops.int8 import quantize_weight

    int8_applies = (Dense.apply, Convolution2D.apply)
    out = dict(params)
    n_packed = 0
    for layer in getattr(module, "layers", ()) or ():
        slot = module.slot(layer) if hasattr(module, "slot") else None
        p = out.get(slot)
        if p is None:
            continue
        if hasattr(layer, "layers") and hasattr(layer, "slot"):
            out[slot], n = _quantize_module_params(layer, p, min_elements)
            n_packed += n
            continue
        if type(layer).apply not in int8_applies or "kernel" not in p:
            continue
        kernel = np.asarray(p["kernel"])
        if kernel.ndim >= 2 and kernel.size >= min_elements and \
                np.issubdtype(kernel.dtype, np.floating):
            q = dict(p)
            q["kernel"] = quantize_weight(kernel, axis=-1)
            out[slot] = q
            n_packed += 1
    return out, n_packed


class InferenceModel:
    """Bounded-concurrency predictor over a jit-compiled forward.

    Usage::

        im = InferenceModel(supported_concurrent_num=4)
        im.load_zoo("/path/to/bundle")     # .analytics-zoo-style dir bundle
        out = im.predict(np.array(...))    # thread-safe

    ``load(module, params, state)`` accepts any live module (e.g. a fitted
    ``Sequential``/``Model``/zoo model) directly.
    """

    def __init__(self, supported_concurrent_num: int = 20,
                 max_batch_size: int = 1024,
                 summary: Optional[InferenceSummary] = None):
        if supported_concurrent_num < 1:
            raise ValueError("supported_concurrent_num must be >= 1")
        self.concurrent_num = supported_concurrent_num
        self.max_batch_size = max_batch_size
        self._sem = threading.Semaphore(supported_concurrent_num)
        self._lock = traced_lock("InferenceModel._lock")
        self._apply = None          # (params, state, x) -> y
        self._params = None
        self._state = None
        self._compiled: Dict[Tuple, Any] = {}
        self._quantized = False
        self.summary = summary
        # hot-swap support (serving/hotswap.py): the load-time param-tree
        # template — treedef + per-leaf (shape, dtype-name) + signature of
        # the UNQUANTIZED params — is what a published checkpoint is
        # validated against; `version` tags every response this model serves
        self.version: Optional[str] = None
        self.load_treedef = None
        self.load_avals: Optional[List[Tuple[Tuple, str]]] = None
        self.load_signature: Optional[str] = None
        self._plain_apply = None    # pre-quantization apply (swap/requantize)
        self._quant_min_elements: Optional[int] = None
        # per-thread version snapshot taken INSIDE the concurrency slot
        # (while any slot is held a swap cannot flip params, so this is
        # exactly the version whose weights served that thread's last
        # predict — the attribution a post-predict read would race)
        self._served_version: Dict[int, Optional[str]] = {}
        # pool metrics (InferenceModel.scala keeps originalModel + clones count)
        self.borrowed_peak = 0
        self._borrowed = 0
        # bucket-cache accounting: ``compiles`` counts executables built (one
        # per distinct bucketed shape — flat under steady traffic = XLA never
        # recompiles mid-stream), ``cache_hits`` counts dict-lookup dispatches
        self.compile_count = 0
        self.cache_hit_count = 0
        # int8 packing wall time (quantize_int8) — startup cost the serving
        # engine pays at warmup instead of the first request
        self.quantize_seconds = 0.0
        # recompilation-hazard tracker: the bucket ladder promises at most
        # log2(max_batch)+1 executables per feature shape; a dispatch-key set
        # outgrowing 2x that bound means this model compiles under live
        # traffic (analysis/ graph-lint "recompile-hazard", flagged once)
        from ..analysis.graphlint import SignatureTracker

        self._sig_tracker = SignatureTracker.for_bucket_ladder(
            "inference.predict", max_batch_size, shapes_per_bucket=2)

    # ------------------------------------------------------------------ loading

    def load(self, module, params=None, state=None) -> "InferenceModel":
        """Load from a live module. If ``module`` is a compiled KerasNet/zoo
        model with trained state, params/state default to it."""
        if params is None:
            est = getattr(module, "estimator", None)
            if est is not None and est.train_state is not None:
                params = est.train_state["params"]
                state = est.train_state["model_state"]
            elif est is not None and getattr(est, "initial_weights", None):
                params, state = est.initial_weights
            else:
                raise ValueError("module has no trained state; pass params=")
        self._apply = lambda p, s, x, m=module: m.apply(p, s, x, training=False)[0]
        self._module = module
        self._params = jax.device_put(params)
        self._state = jax.device_put(state if state is not None else {})
        self._compiled.clear()
        self._record_template(params)
        return self

    def load_zoo(self, path: str, model_class=None) -> "InferenceModel":
        """Load a ``.analytics-zoo``-style directory bundle saved by
        ``ZooModel.save_model`` (InferenceModel.doLoadBigDL parity: rebuild
        architecture + weights, ready to predict)."""
        from ..models.common.zoo_model import load_model_bundle

        model, _cfg = load_model_bundle(path, model=None if model_class is None
                                        else model_class())
        # Bundle restore defers weights to compile; force materialisation now.
        if getattr(model, "estimator", None) is None:
            model.compile(optimizer="sgd", loss="mse")
        return self.load(model)

    def load_tf(self, path: str, signature: str = "serving_default",
                inputs=None, outputs=None) -> "InferenceModel":
        """Load a TF frozen graph (``.pb``) or SavedModel dir and serve it
        (InferenceModel.doLoadTF parity, InferenceModel.scala:83-300 — the
        reference embeds libtensorflow; here the graph executes as a traced
        jnp program via importers.tf_net)."""
        import os

        from ..importers.tf_net import from_frozen_graph, from_saved_model

        if os.path.isdir(path):
            net = from_saved_model(path, signature=signature, inputs=inputs,
                                   outputs=outputs)
        else:
            net = from_frozen_graph(path, inputs=inputs, outputs=outputs)

        # SavedModel variables ride the params pytree so quantize_int8 applies
        # to them; frozen-graph weights are Const nodes inside the traced
        # program and stay full-precision (params is empty then)
        def apply(p, s, x, net=net):
            xs = list(x) if isinstance(x, (list, tuple)) else [x]
            return net._run(*xs, variables=p)

        return self.load_fn(apply, params=dict(net.variables), state=None)

    def load_fn(self, fn, params, state=None) -> "InferenceModel":
        """Load a bare ``fn(params, state, x) -> y`` (escape hatch for imported
        graphs — the TFNet/TorchNet capability lands here via importers)."""
        self._apply = fn
        self._module = None
        self._params = jax.device_put(params)
        self._state = jax.device_put(state if state is not None else {})
        self._compiled.clear()
        self._record_template(params)
        return self

    def _record_template(self, params) -> None:
        """Remember the as-loaded (unquantized) param-tree shape: treedef +
        per-leaf avals + signature. The hot-swap staging path validates a
        published checkpoint against this BEFORE touching live params —
        equal signature ⇒ same avals ⇒ the live executables keep serving
        the new weights without a recompile."""
        from ..engine.checkpoint import param_tree_signature

        leaves, treedef = jax.tree_util.tree_flatten(params)
        self._plain_apply = self._apply
        self.load_treedef = treedef
        self.load_avals = [
            (tuple(np.shape(l)),
             np.dtype(getattr(l, "dtype", np.asarray(l).dtype)).name)
            for l in leaves]
        self.load_signature = param_tree_signature(leaves)
        self.version = None

    # ------------------------------------------------------------- quantization

    def quantize_int8(self, min_elements: int = 4096) -> "InferenceModel":
        """Int8 quantization (InferenceModel.doLoadOpenVINOInt8 capability,
        OpenVinoInferenceSupportive.scala:32-55 / wp-bigdl.md:192).

        Native modules: Dense / Convolution2D kernels >= ``min_elements`` pack
        to per-output-channel int8 and the forward COMPUTES in int8 on the MXU
        (dynamic activation quantization fused into the pallas kernel tier on
        TPU; lax fallback elsewhere — ops/int8.py router). Imported-graph
        loads (no module): weight-only packing, dequantized inside the
        compiled program (size cut only).

        The packing cost is timed into ``compile_stats()['quantize_seconds']``
        so callers (the serving engine's startup warmup) can account for it
        off the first-request path.
        """
        if self._params is None:
            raise RuntimeError("load a model before quantizing")
        t0 = time.perf_counter()
        self._quant_min_elements = min_elements
        host_params = jax.device_get(self._params)
        new_apply, packed = self._build_quantized(host_params, min_elements)
        self._apply = new_apply
        self._params = jax.device_put(packed)
        self._compiled.clear()
        self._quantized = True
        self.quantize_seconds += time.perf_counter() - t0
        return self

    def _build_quantized(self, host_params, min_elements: int):
        """Pack ``host_params`` (an UNQUANTIZED host tree in the load-time
        layout) for int8 serving; returns ``(apply_fn, packed_host_params)``.
        Shared by :meth:`quantize_int8` and the hot-swap requantize path —
        the swap flips apply+params as one consistent pair."""
        module = getattr(self, "_module", None)
        if module is not None and hasattr(module, "layers"):
            packed_params, n_native = _quantize_module_params(
                module, host_params, min_elements)
            if n_native:
                return self._plain_apply, packed_params
            # no int8-computable layer (LSTM/embedding/custom models): fall
            # through to the generic weight-only path so the 4x size cut —
            # the minimum doLoadOpenVINOInt8 property — still happens

        flat, treedef = jax.tree_util.tree_flatten(host_params)
        packed = []
        for leaf in flat:
            arr = np.asarray(jax.device_get(leaf))
            if arr.ndim >= 2 and arr.size >= min_elements and \
                    np.issubdtype(arr.dtype, np.floating):
                packed.append(_quantize_leaf(arr))
            else:
                packed.append(arr)
        # wrap the PLAIN apply (not the current one): requantizing after a
        # swap must not stack a second dequant layer
        inner_apply = self._plain_apply

        def dequant(p):
            flat_q, td = jax.tree_util.tree_flatten(
                p, is_leaf=lambda x: isinstance(x, dict) and "q" in x)
            deq = [x["q"].astype(jnp.float32) * x["scale"]
                   if isinstance(x, dict) and "q" in x else x for x in flat_q]
            return jax.tree_util.tree_unflatten(td, deq)

        apply_fn = lambda p, s, x: inner_apply(dequant(p), s, x)  # noqa: E731
        return apply_fn, jax.tree_util.tree_unflatten(treedef, packed)

    # ----------------------------------------------------------------- hot-swap

    def host_params(self):
        """The live params as a HOST tree in the load-time (unquantized)
        layout — the rollback retention snapshot. For a quantized model the
        packed int8 kernels are dequantized back to float host-side; the
        re-quantize on rollback reproduces the same packed values (the
        round trip is idempotent for already-quantized weights)."""
        if self._params is None:
            raise RuntimeError("no model loaded")
        host = jax.device_get(self._params)
        if not self._quantized:
            return host

        def deq(x):
            if isinstance(x, dict) and "q" in x and "scale" in x:
                return np.asarray(x["q"], np.float32) * np.asarray(x["scale"])
            return x

        flat, td = jax.tree_util.tree_flatten(
            host, is_leaf=lambda x: isinstance(x, dict) and "q" in x)
        return jax.tree_util.tree_unflatten(td, [deq(x) for x in flat])

    def probe_forward(self, params, x):
        """Run the load-time forward with CANDIDATE params (host or device
        tree, unquantized layout) WITHOUT touching live state — the hot-swap
        warmup probe. Uses the plain apply: quantized packing happens only
        at swap time, after the probe passed. The caller owns the device
        placement (the swapper stages one device copy and reuses it for the
        flip)."""
        if self._plain_apply is None:
            raise RuntimeError("no load-time template (use load/load_fn)")
        return self._plain_apply(params, self._state, jnp.asarray(x))

    def _hold_all_slots(self):
        """Acquire every concurrency slot — nothing is mid-``predict`` while
        held, so a reference flip inside lands exactly BETWEEN dispatch
        waves and no in-flight request can see mixed weights."""
        import contextlib

        @contextlib.contextmanager
        def gate():
            for _ in range(self.concurrent_num):
                self._sem.acquire()
            try:
                yield
            finally:
                for _ in range(self.concurrent_num):
                    self._sem.release()

        return gate()

    def swap_params(self, params, version: Optional[str] = None
                    ) -> "InferenceModel":
        """Atomically replace the live params with ``params`` (a host tree
        in the load-time layout, e.g. staged from a published checkpoint).

        All expensive work — device transfer, int8 re-packing for a
        quantized model — happens BEFORE the gate; the flip itself holds
        every concurrency slot so it lands between dispatch waves. Equal
        avals (enforced by the staging validation) mean the compiled
        executables keep serving: for an unquantized model the cache
        survives untouched (params are call arguments, not captures); a
        quantized model re-packs, and its apply+params+cache flip as one
        consistent set."""
        if self._plain_apply is None:
            raise RuntimeError("swap_params needs a load-time template "
                               "(use load/load_fn)")
        if self._quantized:
            new_apply, packed = self._build_quantized(
                params, self._quant_min_elements or 4096)
            new_params = jax.device_put(packed)
        else:
            new_apply = self._plain_apply
            new_params = jax.device_put(params)
        # same apply identity (unquantized, or module-path int8 packing) ⇒
        # the compiled cache stays valid: params are call arguments, and the
        # staging validation guaranteed equal avals. A fresh generic-path
        # dequant wrapper must drop the cache with the flip.
        clear = new_apply is not self._apply
        with self._hold_all_slots():
            self._apply = new_apply
            self._params = new_params
            if clear:
                self._compiled.clear()
            self.version = version
        return self

    def apply_row_delta(self, entries, *, version: Optional[str] = None
                        ) -> "InferenceModel":
        """Patch the live params IN PLACE from a row-delta publish: scatter
        only the touched rows into each affected leaf instead of staging a
        full replacement tree. ``entries`` is ``[(leaf_index, idx, rows)]``
        in the load-time flatten order — ``idx=None`` means ``rows`` is a
        whole-leaf replacement (the delta's dense fallback).

        Only the touched rows cross host→device; each patched leaf keeps its
        aval, so the compiled executables keep serving with zero recompiles
        (params are call arguments, not captures). The scatter runs on an
        undonated copy — the pre-flip leaf may still be mid-``predict`` on
        another slot, so its buffer must stay valid until the gated flip.
        Quantized models reject the patch: rows can't be scattered into
        int8-packed kernels, so they take the full-checkpoint path."""
        if self._plain_apply is None:
            raise RuntimeError("apply_row_delta needs a load-time template "
                               "(use load/load_fn)")
        if self._quantized:
            raise RuntimeError(
                "row deltas cannot patch int8-packed params — publish a "
                "full checkpoint for quantized serving")
        if self._params is None:
            raise RuntimeError("no model loaded")
        leaves, treedef = jax.tree_util.tree_flatten(self._params)
        for leaf_idx, idx, rows in entries:
            cur = leaves[leaf_idx]
            if idx is None:
                leaves[leaf_idx] = jax.device_put(
                    jnp.asarray(rows, cur.dtype))
            else:
                leaves[leaf_idx] = _scatter_rows(
                    cur, jnp.asarray(np.asarray(idx, np.int32)),
                    jnp.asarray(rows, cur.dtype))
        new_params = jax.tree_util.tree_unflatten(treedef, leaves)
        with self._hold_all_slots():
            self._params = new_params
            if version is not None:
                self.version = version
        return self

    # ---------------------------------------------------------------- predicting

    def _executable(self, key: Tuple):
        exe = self._compiled.get(key)
        if exe is None:
            with self._lock:
                exe = self._compiled.get(key)
                if exe is None:
                    exe = jax.jit(self._apply)
                    self._compiled[key] = exe
                    self.compile_count += 1
                    _COMPILES.inc()
                    self._sig_tracker.add(key)
                    return exe
        self.cache_hit_count += 1
        _CACHE_HITS.inc()
        return exe

    def compile_stats(self) -> Dict[str, Any]:
        """Bucket-cache counters (surfaced at /metrics and by the bench):
        ``compiled_shapes``/``compiles`` bound by the bucket ladder,
        ``cache_hits`` = dispatches served by a dict lookup,
        ``quantize_seconds`` = int8 packing wall time (0.0 unquantized)."""
        return {"compiled_shapes": len(self._compiled),
                "compiles": self.compile_count,
                "cache_hits": self.cache_hit_count,
                "quantize_seconds": round(self.quantize_seconds, 4)}

    def _bucket(self, n: int) -> int:
        for b in _buckets(self.max_batch_size):
            if n <= b:
                return b
        return self.max_batch_size

    def _validate_inputs(self, inputs):
        if self._apply is None:
            raise RuntimeError("no model loaded (call load/load_zoo first)")
        multi = isinstance(inputs, (list, tuple))
        arrs = [np.asarray(a) for a in (inputs if multi else [inputs])]
        n = arrs[0].shape[0]
        if any(a.shape[0] != n for a in arrs):
            raise ValueError("all inputs must share the batch dimension")
        return arrs, multi, n

    def _dispatch_chunks(self, arrs, multi, n):
        """Pad each ≤max_batch chunk to its bucket and ENQUEUE the executable
        — returns ``[(device_result, valid_count), ...]`` without waiting.
        JAX dispatch is asynchronous, so the device (or the tunnel to it)
        starts working immediately; only fetching blocks."""
        dispatched = []
        for lo in range(0, n, self.max_batch_size):
            hi = min(lo + self.max_batch_size, n)
            bucket = self._bucket(hi - lo)
            padded = [_pad_to(a[lo:hi], bucket) for a in arrs]
            x = padded if multi else padded[0]
            key = (bucket,) + tuple((a.shape[1:], str(a.dtype))
                                    for a in padded)
            with timing("inference.forward"):
                y = self._executable(key)(self._params, self._state, x)
            dispatched.append((y, hi - lo))
        return dispatched

    @staticmethod
    def _gather_chunks(dispatched):
        outs = [jax.tree_util.tree_map(
                    lambda a: np.asarray(jax.device_get(a))[:m], y)
                for y, m in dispatched]
        if len(outs) == 1:
            return outs[0]
        return jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *outs)

    def predict(self, inputs, batch_first: bool = True):
        """Thread-safe bounded-concurrency predict (doPredict parity).

        ``inputs``: ndarray or list/tuple of ndarrays (multi-input models).
        Requests larger than ``max_batch_size`` are chunked.
        """
        arrs, multi, n = self._validate_inputs(inputs)
        t0 = time.perf_counter()
        with self._sem:
            with self._lock:
                self._borrowed += 1
                self.borrowed_peak = max(self.borrowed_peak, self._borrowed)
            # slot held ⇒ no swap can be mid-flight: this version IS the one
            # whose params the dispatch below reads
            if len(self._served_version) > 4096:   # dead-thread-id bound
                self._served_version.clear()
            self._served_version[threading.get_ident()] = self.version
            try:
                result = self._gather_chunks(
                    self._dispatch_chunks(arrs, multi, n))
            finally:
                with self._lock:
                    self._borrowed -= 1
        _mw.sample("inference.dispatch")
        if self.summary is not None:
            self.summary.add_batch(n, time.perf_counter() - t0)
        return result

    def last_served_version(self) -> Optional[str]:
        """Version of the params that served THIS thread's last ``predict``
        (None before the first call, or for never-swapped models). Race-free
        w.r.t. concurrent hot-swaps — the snapshot is taken inside the
        concurrency slot."""
        return self._served_version.get(threading.get_ident())

    def predict_async(self, inputs):
        """Dispatch a predict WITHOUT waiting; returns ``fetch() -> result``.

        The XLA execution (and its host→device transfer) is enqueued before
        this returns; ``fetch()`` blocks only on the device→host result
        transfer. On a remote accelerator this is what lets a caller overlap
        the round-trip of batch N with assembling/dispatching batch N+1 —
        the serving engine's double-buffered dispatch rides this.

        The concurrency semaphore is held from dispatch until ``fetch()``
        completes (an in-flight request IS a borrowed replica); every
        returned ``fetch`` must therefore be called exactly once.
        """
        arrs, multi, n = self._validate_inputs(inputs)
        t0 = time.perf_counter()
        self._sem.acquire()
        with self._lock:
            self._borrowed += 1
            self.borrowed_peak = max(self.borrowed_peak, self._borrowed)
        self._served_version[threading.get_ident()] = self.version
        try:
            dispatched = self._dispatch_chunks(arrs, multi, n)
        except BaseException:
            with self._lock:
                self._borrowed -= 1
            self._sem.release()
            raise
        released = [False]  # fetch-once guard; check-and-set under self._lock

        def fetch():
            try:
                return self._gather_chunks(dispatched)
            finally:
                # atomic test-and-set: two concurrent fetch() calls must not
                # both release the semaphore / decrement _borrowed, or the
                # concurrency bound silently inflates
                with self._lock:
                    first = not released[0]
                    released[0] = True
                    if first:
                        self._borrowed -= 1
                if first:
                    self._sem.release()
                    if self.summary is not None:
                        self.summary.add_batch(n, time.perf_counter() - t0)

        return fetch

    # ------------------------------------------------------- device-level access

    def device_apply(self):
        """``(apply_fn, params, state)`` — the exact computation ``predict``
        compiles, with params/state already device-resident.

        Public escape hatch for AOT export and device-resident benchmarking
        (serving_bench.py times int8-vs-bf16 through this so the measurement
        cannot silently decouple from the real predict path): after
        ``quantize_int8`` the returned ``apply_fn``/``params`` are the
        quantized ones."""
        if self._apply is None:
            raise RuntimeError("no model loaded (call load/load_zoo first)")
        return self._apply, self._params, self._state

    # ------------------------------------------------------------------- warmup

    def warm_up(self, example_inputs, graph_checks: Optional[str] = None
                ) -> None:
        """Compile the bucket ladder ahead of traffic (AOT; replaces the
        reference's replica-clone prefill). ``graph_checks`` ("warn"/"raise")
        additionally runs :meth:`check_fused_dispatch` so a quantized model
        whose fused kernels are silently not dispatching is caught here —
        at model-load time — instead of at the next bench run."""
        multi = isinstance(example_inputs, (list, tuple))
        arrs = [np.asarray(a) for a in
                (example_inputs if multi else [example_inputs])]
        for b in _buckets(self.max_batch_size):
            padded = [_pad_to(a[:1], b) for a in arrs]
            self.predict(padded if multi else padded[0])
        if graph_checks:
            self.check_fused_dispatch(example_inputs, mode=graph_checks)
            self.check_memory(example_inputs, mode=graph_checks)

    def check_fused_dispatch(self, example_inputs, mode: str = "warn"):
        """Run the ``fused-int8-dispatch`` graph rule over the exact
        computation :meth:`predict` compiles (the PR-6 regression class:
        quantized model, fused tier claimed on, but the jaxpr shows lax
        quantize ops / int8 HBM intermediates instead of pallas kernels).

        No-op unless the model is quantized AND the fused tier is routed on
        (``ops.int8_fused.fused_mode() != "off"``) — an un-quantized or
        deliberately-lax model has no fused invariant to hold. ``mode``:
        "warn" logs findings, "raise" raises
        :class:`analytics_zoo_tpu.analysis.GraphLintError`. Returns the
        findings."""
        from ..analysis import RuleContext, enforce
        from ..analysis.rules.fused_int8 import lint_fused_dispatch
        from ..ops.int8_fused import fused_mode

        if not mode or mode == "off":
            return []
        if not self._quantized or fused_mode() == "off":
            return []
        import logging

        multi = isinstance(example_inputs, (list, tuple))
        arrs = [jnp.asarray(np.asarray(a)[:1]) for a in
                (example_inputs if multi else [example_inputs])]
        x = arrs if multi else arrs[0]
        ctx = RuleContext(where="inference.load", fused_expected=True)
        findings = lint_fused_dispatch(self, x, ctx=ctx)
        return enforce(findings, mode,
                       logging.getLogger("analytics_zoo_tpu.inference"))

    def check_memory(self, example_inputs, mode: str = "warn",
                     budget_bytes: Optional[int] = None):
        """Run the memory tier over the exact computation :meth:`predict`
        compiles: ``hbm-budget`` when ``budget_bytes`` declares a per-device
        budget (``ServingConfig.hbm_budget_mb`` through ``_warm_model``) and
        ``peak-temporary`` always — the static live-range estimate of the
        dispatch, checked at model-load time exactly like the fused-dispatch
        structure. Also notes the static peak into the runtime memory
        witness (site ``inference.dispatch``) when witnessing is on.
        Returns the findings."""
        from ..analysis import RuleContext, enforce, profile_jaxpr
        from ..analysis.rules.fused_int8 import _trace_dispatch
        from ..analysis.rules.memory import lint_memory
        from ..common import memwitness as _mw

        if not mode or mode == "off":
            return []
        import logging

        multi = isinstance(example_inputs, (list, tuple))
        arrs = [jnp.asarray(np.asarray(a)[:1]) for a in
                (example_inputs if multi else [example_inputs])]
        x = arrs if multi else arrs[0]
        closed = _trace_dispatch(self, x)
        ctx = RuleContext(where="inference.load",
                          hbm_budget_bytes=budget_bytes)
        findings = lint_memory(closed, ctx=ctx,
                               rules=["hbm-budget", "peak-temporary"])
        if _mw.enabled():
            prof = profile_jaxpr(closed)
            _mw.note_static("inference.dispatch", prof.peak_live_bytes,
                            budget_bytes)
        return enforce(findings, mode,
                       logging.getLogger("analytics_zoo_tpu.inference"))

    @property
    def is_quantized(self) -> bool:
        return self._quantized

    def __repr__(self):
        return (f"InferenceModel(concurrent_num={self.concurrent_num}, "
                f"loaded={self._apply is not None}, int8={self._quantized})")
