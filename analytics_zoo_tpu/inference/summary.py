"""Inference observability: ``timing`` blocks + throughput counters.

Parity: /root/reference/zoo/.../pipeline/inference/InferenceSupportive.scala
(``timing(name){...}`` wall-time logging) and InferenceSummary.scala (throughput
scalars for TensorBoard). Here timings aggregate in-process and can be dumped as
JSON lines or TB scalars via the common summary writer.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, Optional

from ..common import telemetry as _tm
from ..common.locks import traced_lock

logger = logging.getLogger("analytics_zoo_tpu.inference")

_TIMING_HIST = _tm.histogram(
    "zoo_timing_seconds",
    "Wall time of timing() blocks (buckets give the percentiles the "
    "count/total/max dict never could)", labels=("name",))


class _TimingStats:
    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0


_STATS: Dict[str, _TimingStats] = {}
# zoo-lock: leaf
_STATS_LOCK = traced_lock("summary._STATS_LOCK")


@contextlib.contextmanager
def timing(name: str, log: bool = False):
    """``with timing("preprocess"): ...`` — records wall time under ``name``.

    InferenceSupportive.scala's ``timing`` logs every call; here logging is
    opt-in (``log=True``) and aggregation is always on.
    """
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _STATS_LOCK:
            st = _STATS.setdefault(name, _TimingStats())
            st.count += 1
            st.total_s += dt
            st.max_s = max(st.max_s, dt)
        _TIMING_HIST.labels(name=name).observe(dt)
        if log:
            logger.info("%s time elapsed [%.3f ms]", name, dt * 1e3)


def timing_stats() -> Dict[str, Dict[str, float]]:
    with _STATS_LOCK:
        return {k: {"count": v.count, "total_s": v.total_s, "max_s": v.max_s,
                    "mean_s": v.total_s / max(v.count, 1)}
                for k, v in _STATS.items()}


def reset_timing_stats() -> None:
    with _STATS_LOCK:
        _STATS.clear()


class InferenceSummary:
    """Throughput/latency counters for a serving process, optionally mirrored to
    a TensorBoard event file (InferenceSummary.scala parity)."""

    def __init__(self, log_dir: Optional[str] = None, name: str = "inference"):
        # zoo-lock: guards(records, batches, total_latency_s)
        self._lock = traced_lock("InferenceSummary._lock")
        self.records = 0
        self.batches = 0
        self.total_latency_s = 0.0
        self._writer = None
        if log_dir is not None:
            import os

            from ..common.summary import EventWriter

            self._writer = EventWriter(os.path.join(log_dir, name))

    def add_batch(self, n_records: int, latency_s: float) -> None:
        with self._lock:
            self.records += n_records
            self.batches += 1
            self.total_latency_s += latency_s
            step = self.batches
        if self._writer is not None:
            self._writer.add_scalars(step, {
                "Throughput": n_records / max(latency_s, 1e-9),
                "Latency_ms": latency_s * 1e3,
            })

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "records": self.records,
                "batches": self.batches,
                "mean_latency_s": self.total_latency_s / max(self.batches, 1),
                "throughput": self.records / max(self.total_latency_s, 1e-9),
            }

    def close(self):
        if self._writer is not None:
            self._writer.close()
