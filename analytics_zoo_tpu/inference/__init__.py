"""Inference engine — load models and serve low-latency predictions.

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/pipeline/
inference/ (InferenceModel.scala, InferenceModelFactory.scala,
AbstractInferenceModel.java, InferenceSummary.scala).
"""

from .inference_model import InferenceModel
from .summary import InferenceSummary, timing

__all__ = ["InferenceModel", "InferenceSummary", "timing"]
