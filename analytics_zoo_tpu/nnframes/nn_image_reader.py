"""NNImageReader — directory of images → DataFrame
(reference ``pyzoo/zoo/pipeline/nnframes/nn_image_reader.py:9-40`` /
``NNImageReader.scala``: readImages(path, resizeH, resizeW) returns a DataFrame
with an image struct column {origin, height, width, nChannels, mode, data}).

Here the image column holds the decoded HWC uint8/float array directly (no
OpenCV byte-struct encoding), plus origin/height/width columns for parity.
"""

from __future__ import annotations

import glob
import os
from typing import List, Optional

import numpy as np

_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif")


class NNImageReader:
    @staticmethod
    def readImages(path: str, resizeH: int = -1, resizeW: int = -1,
                   with_label_from_dirs: bool = False):
        """Read images under ``path`` (a dir, a glob, or comma-separated paths)
        into a pandas DataFrame with columns image/origin/height/width
        (+``label`` when ``with_label_from_dirs``: subdirectory name index, the
        dogs-vs-cats style layout)."""
        import pandas as pd
        from PIL import Image

        files: List[str] = []
        for part in str(path).split(","):
            part = part.strip()
            if os.path.isdir(part):
                for root, _dirs, names in os.walk(part):
                    files.extend(os.path.join(root, n) for n in names
                                 if n.lower().endswith(_EXTS))
            else:
                # explicit file or glob: the user named it — no extension
                # filtering (PIL decodes more formats than _EXTS lists), but
                # only regular files (globs like 'dir/*' also match subdirs)
                files.extend(f for f in glob.glob(part) if os.path.isfile(f))
        files = sorted(set(files))
        if not files:
            raise FileNotFoundError(f"no images found under {path!r}")

        label_names = None
        if with_label_from_dirs:
            label_names = sorted({os.path.basename(os.path.dirname(f))
                                  for f in files})

        rows = []
        for f in files:
            img = Image.open(f).convert("RGB")
            if resizeH > 0 and resizeW > 0:
                img = img.resize((resizeW, resizeH))
            arr = np.asarray(img, dtype=np.uint8)
            row = {"image": arr, "origin": f,
                   "height": arr.shape[0], "width": arr.shape[1]}
            if label_names is not None:
                row["label"] = label_names.index(
                    os.path.basename(os.path.dirname(f)))
            rows.append(row)
        return pd.DataFrame(rows)

    read_images = readImages
