"""NNEstimator / NNModel / NNClassifier — DataFrame in, fitted transformer out.

Reference: ``zoo/.../nnframes/NNEstimator.scala:198`` (fit at :414-470, transform
at :665-718) and ``pyzoo/zoo/pipeline/nnframes/nn_classifier.py:135-560``.
Setter names keep the reference's Spark-ML camelCase (``setBatchSize``) with
snake_case aliases.

Column → tensor marshalling replaces the reference's
``Preprocessing[(F, Option[L]), Sample]`` chains: a ``feature_preprocessing``
callable (row-array → array) fills the same role as SeqToTensor/ArrayToTensor.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from ..common.triggers import MaxEpoch, Trigger


def _col_to_array(df, col: Union[str, Sequence[str]],
                  preprocessing: Optional[Callable] = None) -> np.ndarray:
    """Marshal DataFrame column(s) into one contiguous float array.

    * list of columns → stacked along the last axis (one scalar per column)
    * single column of scalars → (N, 1)
    * single column of arrays/lists → stacked (N, ...) — rows must agree in shape
    """
    if isinstance(col, (list, tuple)):
        mat = np.stack([df[c].to_numpy(dtype=np.float32) for c in col], axis=1)
    else:
        first = df[col].iloc[0]
        if np.isscalar(first) or isinstance(first, (int, float, np.number)):
            mat = df[col].to_numpy(dtype=np.float32)[:, None]
        else:
            rows = [np.asarray(v, dtype=np.float32) for v in df[col]]
            shapes = {r.shape for r in rows}
            if len(shapes) > 1:
                raise ValueError(
                    f"column {col!r} rows disagree in shape: {sorted(shapes)[:3]}")
            mat = np.stack(rows)
    if preprocessing is not None:
        mat = np.stack([np.asarray(preprocessing(r), dtype=np.float32)
                        for r in mat])
    return mat


class NNEstimator:
    """``NNEstimator(model, criterion).fit(df) -> NNModel``.

    ``model`` is any KerasNet (Sequential/Model/zoo model); ``criterion`` a loss
    name or callable (the BigDL Criterion slot).
    """

    def __init__(self, model, criterion="mse",
                 feature_preprocessing: Optional[Callable] = None,
                 label_preprocessing: Optional[Callable] = None):
        self.model = model
        self.criterion = criterion
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        self.features_col: Union[str, List[str]] = "features"
        self.label_col: Union[str, List[str]] = "label"
        self.prediction_col = "prediction"
        self.batch_size = 32
        self.max_epoch = 1
        self.learning_rate = 1e-3
        self.optim_method = None
        self.end_when: Optional[Trigger] = None
        self.validation = None          # (trigger, df, metrics, batch_size)
        self.checkpoint_path = None
        self.tensorboard = None         # (log_dir, app_name)
        self.clip_norm = None
        self.clip_range = None
        self.cache_level = "DRAM"

    # ------------------------------------------------------- Spark-ML setters
    def setFeaturesCol(self, col):
        self.features_col = col
        return self

    def setLabelCol(self, col):
        self.label_col = col
        return self

    def setPredictionCol(self, col):
        self.prediction_col = col
        return self

    def setBatchSize(self, v):
        self.batch_size = int(v)
        return self

    def setMaxEpoch(self, v):
        self.max_epoch = int(v)
        return self

    def setLearningRate(self, v):
        self.learning_rate = float(v)
        return self

    def setOptimMethod(self, opt):
        self.optim_method = opt
        return self

    def setEndWhen(self, trigger: Trigger):
        self.end_when = trigger
        return self

    def setValidation(self, trigger, val_df, val_methods, batch_size=32):
        self.validation = (trigger, val_df, val_methods, batch_size)
        return self

    def setCheckpoint(self, path, trigger=None, isOverWrite=True):
        del trigger, isOverWrite  # estimator checkpoints per epoch
        self.checkpoint_path = path
        return self

    def setTrainSummary(self, log_dir, app_name="nnestimator"):
        self.tensorboard = (log_dir, app_name)
        return self

    def setGradientClippingByL2Norm(self, clip_norm):
        self.clip_norm = float(clip_norm)
        return self

    def setConstantGradientClipping(self, min_value, max_value):
        self.clip_range = (float(min_value), float(max_value))
        return self

    def clearGradientClipping(self):
        self.clip_norm = None
        self.clip_range = None
        return self

    def setDataCacheLevel(self, level, num_slice=None):
        self.cache_level = level if num_slice is None else (level, num_slice)
        return self

    # snake_case aliases
    set_features_col = setFeaturesCol
    set_label_col = setLabelCol
    set_prediction_col = setPredictionCol
    set_batch_size = setBatchSize
    set_max_epoch = setMaxEpoch
    set_learning_rate = setLearningRate
    set_optim_method = setOptimMethod
    set_end_when = setEndWhen
    set_validation = setValidation
    set_checkpoint = setCheckpoint
    set_train_summary = setTrainSummary

    # ----------------------------------------------------------------- fit
    def _marshal(self, df, with_label=True):
        x = _col_to_array(df, self.features_col, self.feature_preprocessing)
        y = None
        if with_label:
            y = _col_to_array(df, self.label_col, self.label_preprocessing)
        return x, y

    def _optimizer(self):
        if self.optim_method is not None:
            return self.optim_method
        from ..nn.optimizers import Adam

        return Adam(lr=self.learning_rate)

    def fit(self, df) -> "NNModel":
        x, y = self._marshal(df)
        self.model.compile(optimizer=self._optimizer(), loss=self.criterion)
        if self.clip_norm is not None:
            self.model.set_gradient_clipping_by_l2_norm(self.clip_norm)
        if self.clip_range is not None:
            self.model.set_constant_gradient_clipping(*self.clip_range)
        if self.tensorboard is not None:
            self.model.set_tensorboard(*self.tensorboard)
        if self.checkpoint_path is not None:
            self.model.set_checkpoint(self.checkpoint_path)
        val = None
        metrics = ()
        if self.validation is not None:
            _, val_df, metrics, _ = self.validation
            vx, vy = self._marshal(val_df)
            val = (vx, vy)
            self.model._metrics = list(metrics)
        self.model.fit(x, y, batch_size=self.batch_size,
                       nb_epoch=self.max_epoch, validation_data=val,
                       end_trigger=self.end_when or MaxEpoch(self.max_epoch))
        return self._create_model()

    def _create_model(self) -> "NNModel":
        return NNModel(self.model,
                       feature_preprocessing=self.feature_preprocessing,
                       features_col=self.features_col,
                       prediction_col=self.prediction_col,
                       batch_size=self.batch_size)


class NNModel:
    """Fitted transformer: ``transform(df)`` appends ``prediction_col``
    (NNEstimator.scala:665-718 NNModel parity)."""

    def __init__(self, model, feature_preprocessing=None,
                 features_col="features", prediction_col="prediction",
                 batch_size=256):
        self.model = model
        self.feature_preprocessing = feature_preprocessing
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.batch_size = batch_size

    def setFeaturesCol(self, col):
        self.features_col = col
        return self

    def setPredictionCol(self, col):
        self.prediction_col = col
        return self

    def setBatchSize(self, v):
        self.batch_size = int(v)
        return self

    def _predict_array(self, df) -> np.ndarray:
        x = _col_to_array(df, self.features_col, self.feature_preprocessing)
        return np.asarray(self.model.predict(x, batch_size=self.batch_size))

    def transform(self, df):
        pred = self._predict_array(df)
        out = df.copy()
        if pred.ndim > 1 and pred.shape[1] == 1:
            out[self.prediction_col] = pred[:, 0]
        elif pred.ndim > 1:
            out[self.prediction_col] = list(pred)
        else:
            out[self.prediction_col] = pred
        return out

    def save(self, path: str):
        self.model.save_model(path)

    @staticmethod
    def load(path: str, model=None) -> "NNModel":
        """Load a saved NNModel. If ``model`` is None the bundle must have been
        saved by a registered zoo model (save_model records the class)."""
        if model is None:
            from ..models.common.zoo_model import load_model_bundle

            model, _ = load_model_bundle(path)
        else:
            model.load_weights(path)
        return NNModel(model)


class NNClassifier(NNEstimator):
    """NNEstimator specialization for int class labels
    (nn_classifier.py:513-560 parity: default criterion is classification NLL;
    here sparse categorical cross-entropy)."""

    def __init__(self, model, criterion="sparse_categorical_crossentropy",
                 feature_preprocessing=None):
        super().__init__(model, criterion, feature_preprocessing)

    def _marshal(self, df, with_label=True):
        x = _col_to_array(df, self.features_col, self.feature_preprocessing)
        y = None
        if with_label:
            y = df[self.label_col].to_numpy(dtype=np.int32)
        return x, y

    def _create_model(self) -> "NNClassifierModel":
        return NNClassifierModel(self.model,
                                 feature_preprocessing=self.feature_preprocessing,
                                 features_col=self.features_col,
                                 prediction_col=self.prediction_col,
                                 batch_size=self.batch_size)


class NNClassifierModel(NNModel):
    """Transform emits the argmax class index (float, Spark-ML convention)."""

    def transform(self, df):
        probs = self._predict_array(df)
        out = df.copy()
        if probs.ndim == 1 or probs.shape[-1] == 1:
            cls = (probs.reshape(len(out)) > 0.5).astype(np.float64)
        else:
            cls = probs.argmax(axis=-1).astype(np.float64)
        out[self.prediction_col] = cls
        return out
