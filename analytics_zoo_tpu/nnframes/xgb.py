"""XGBoost passthrough for NNFrames — gradient-boosted models behind the same
DataFrame estimator/transformer API as NNEstimator/NNModel.

Reference parity: ``pyzoo/zoo/pipeline/nnframes/nn_classifier.py:584``
(``XGBClassifierModel``: setFeaturesCol/setPredictionCol/transform/loadModel)
and the Scala-side ``XGBClassifier``/``XGBRegressor`` estimators they wrap.
The reference routes to the xgboost4j-spark JVM; here the engine is the
python ``xgboost`` package when importable, else sklearn's histogram
gradient boosting (same API surface; install via the ``boost`` extra when
neither is present) — either way the
tree ensemble runs host-side: boosting is not a TPU workload, so this stays a
passthrough exactly like the reference treats it.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Optional, Sequence

import numpy as np

from .nn_estimator import _col_to_array


def _make_engine(task: str, params: Dict):
    """xgboost if installed, else sklearn HistGradientBoosting."""
    common = dict(params)
    n_round = common.pop("n_estimators", common.pop("num_round", 100))
    max_depth = common.pop("max_depth", 6)
    lr = common.pop("learning_rate", common.pop("eta", 0.3))
    try:
        import xgboost as xgb

        cls = xgb.XGBClassifier if task == "classification" else xgb.XGBRegressor
        return cls(n_estimators=n_round, max_depth=max_depth,
                   learning_rate=lr, **common), "xgboost"
    except ImportError:
        try:
            from sklearn.ensemble import (HistGradientBoostingClassifier,
                                          HistGradientBoostingRegressor)
        except ImportError as e:
            raise ImportError(
                "the XGBoost passthrough needs a boosting engine: "
                "pip install xgboost (preferred) or scikit-learn "
                "(the 'boost' extra)") from e

        cls = (HistGradientBoostingClassifier if task == "classification"
               else HistGradientBoostingRegressor)
        common.pop("nthread", None)
        common.pop("num_workers", None)
        return cls(max_iter=n_round, max_depth=max_depth, learning_rate=lr,
                   **common), "sklearn"


class _XGBEstimatorBase:
    """Shared estimator shell: camelCase setters (Spark-ML convention, like
    NNEstimator) + ``fit(df, feature_cols, label_col) -> model``."""

    task = "classification"

    def __init__(self, params: Optional[Dict] = None):
        self.params = dict(params or {})

    # -- reference XGBClassifier setter surface -------------------------------
    def setNumRound(self, n: int):
        self.params["n_estimators"] = int(n)
        return self

    def setMaxDepth(self, d: int):
        self.params["max_depth"] = int(d)
        return self

    def setEta(self, lr: float):
        self.params["learning_rate"] = float(lr)
        return self

    setLearningRate = setEta

    def setNthread(self, n: int):
        self.params["nthread"] = int(n)
        return self

    def setNumWorkers(self, n: int):
        self.params["num_workers"] = int(n)
        return self

    def fit(self, df, feature_cols: Sequence[str], label_col: str = "label"):
        x = _col_to_array(df, list(feature_cols))
        y = df[label_col].to_numpy()
        engine, backend = _make_engine(self.task, self.params)
        engine.fit(x, y)
        return self._model_cls(engine, backend=backend,
                               feature_cols=list(feature_cols))


class _XGBModelBase:
    """Fitted transformer: ``transform(df)`` appends the prediction column."""

    def __init__(self, engine, backend: str = "unknown",
                 feature_cols: Optional[Sequence[str]] = None,
                 prediction_col: str = "prediction"):
        assert engine is not None
        self.engine = engine
        self.backend = backend
        self.feature_cols = list(feature_cols or [])
        self.prediction_col = prediction_col

    def setFeaturesCol(self, features):
        self.feature_cols = (list(features) if isinstance(features, (list, tuple))
                             else [features])
        return self

    def setPredictionCol(self, prediction: str):
        self.prediction_col = prediction
        return self

    def transform(self, df):
        if not self.feature_cols:
            raise ValueError("call setFeaturesCol(...) before transform")
        x = _col_to_array(df, self.feature_cols)
        out = df.copy()
        out[self.prediction_col] = self.engine.predict(x)
        return out

    def save(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump({"engine": self.engine, "backend": self.backend,
                         "feature_cols": self.feature_cols,
                         "prediction_col": self.prediction_col,
                         "class": type(self).__name__}, f)

    @classmethod
    def _load(cls, path: str):
        with open(path, "rb") as f:
            blob = pickle.load(f)
        saved = blob.get("class")
        if saved is not None and saved != cls.__name__:
            raise ValueError(
                f"{path} holds a {saved}, not a {cls.__name__}")
        return cls(blob["engine"], backend=blob["backend"],
                   feature_cols=blob["feature_cols"],
                   prediction_col=blob["prediction_col"])


class XGBClassifierModel(_XGBModelBase):
    """Trained boosted classifier; the prediction column holds class labels
    (nn_classifier.py:584-612 parity)."""

    def predict_proba(self, df) -> np.ndarray:
        x = _col_to_array(df, self.feature_cols)
        return self.engine.predict_proba(x)

    @staticmethod
    def loadModel(path: str, numClasses: Optional[int] = None):
        """Reference signature (nn_classifier.py:606: path + numClasses);
        the class count is recovered from the pickled engine, so
        ``numClasses`` is accepted for compatibility and cross-checked."""
        model = XGBClassifierModel._load(path)
        n = getattr(model.engine, "n_classes_", None)
        if n is None:
            classes = getattr(model.engine, "classes_", None)
            n = len(classes) if classes is not None else None
        if numClasses is not None and n is not None and int(numClasses) != int(n):
            raise ValueError(f"model has {n} classes, expected {numClasses}")
        return model


class XGBRegressorModel(_XGBModelBase):
    """Trained boosted regressor (Scala XGBRegressorModel parity)."""

    @staticmethod
    def loadModel(path: str):
        return XGBRegressorModel._load(path)


class XGBClassifier(_XGBEstimatorBase):
    task = "classification"
    _model_cls = XGBClassifierModel


class XGBRegressor(_XGBEstimatorBase):
    task = "regression"
    _model_cls = XGBRegressorModel
