"""NNFrames — DataFrame-native ML pipeline integration (SURVEY.md §2.5).

Reference parity: ``zoo/.../nnframes/NNEstimator.scala:198`` (Spark-ML
``Estimator``/``Transformer`` pair) and the python mirror
``pyzoo/zoo/pipeline/nnframes/nn_classifier.py``:
``NNEstimator(model, criterion).setBatchSize(..).setMaxEpoch(..).fit(df)`` →
``NNModel.transform(df)`` appends a prediction column; ``NNClassifier`` /
``NNClassifierModel`` for class labels; ``NNImageReader.readImages`` loads a
directory of images into a DataFrame.

TPU-native redesign: the "DataFrame" is pandas/pyarrow on the host — rows are
marshalled once into contiguous numpy arrays (no per-row Sample objects, no
py4j), then the shared Estimator drives the jitted train step. Spark's
distribution role is covered by the data layer's sharding (per-host splits of
the array batch dimension).
"""

from .nn_estimator import NNEstimator, NNModel, NNClassifier, NNClassifierModel
from .nn_image_reader import NNImageReader
from .xgb import (XGBClassifier, XGBClassifierModel, XGBRegressor,
                  XGBRegressorModel)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader", "XGBClassifier", "XGBClassifierModel",
           "XGBRegressor", "XGBRegressorModel"]
