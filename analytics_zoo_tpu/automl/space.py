"""Search-space primitives (the rebuild's ``ray.tune.choice/uniform/...``).

The reference expresses search spaces as dicts of ``tune.*`` sampler objects
(config/recipe.py — e.g. SmokeRecipe.search_space uses ``tune.choice``/
``tune.uniform``). Here samplers are tiny picklable objects sampled with a
``numpy.random.Generator`` so a search is fully deterministic given a seed.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Sequence

import numpy as np


class Sampler:
    def sample(self, rng: np.random.Generator):  # pragma: no cover - interface
        raise NotImplementedError


class Choice(Sampler):
    def __init__(self, values: Sequence):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(len(self.values)))]

    def __repr__(self):
        return f"Choice({self.values})"


class Uniform(Sampler):
    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))

    def __repr__(self):
        return f"Uniform({self.low}, {self.high})"


class LogUniform(Sampler):
    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def sample(self, rng):
        return float(np.exp(rng.uniform(np.log(self.low), np.log(self.high))))


class QUniform(Sampler):
    """Uniform quantized to multiples of ``q`` (tune.quniform parity)."""

    def __init__(self, low: float, high: float, q: float = 1.0):
        self.low, self.high, self.q = float(low), float(high), float(q)

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return float(np.round(v / self.q) * self.q)


class RandInt(Sampler):
    def __init__(self, low: int, high: int):
        self.low, self.high = int(low), int(high)

    def sample(self, rng):
        return int(rng.integers(self.low, self.high))


class GridSearch:
    """Marks a dimension as exhaustively enumerated (tune.grid_search parity)."""

    def __init__(self, values: Sequence):
        self.values = list(values)


def grid_product(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Expand all GridSearch dims into the cross-product of partial configs."""
    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    if not grid_keys:
        return [{}]
    combos = itertools.product(*[space[k].values for k in grid_keys])
    return [dict(zip(grid_keys, c)) for c in combos]


def sample_config(space: Dict[str, Any], rng: np.random.Generator,
                  fixed: Dict[str, Any] = None) -> Dict[str, Any]:
    """Draw one concrete config: samplers sampled, grid dims must be in ``fixed``."""
    out = {}
    for k, v in space.items():
        if fixed and k in fixed:
            out[k] = fixed[k]
        elif isinstance(v, Sampler):
            out[k] = v.sample(rng)
        elif isinstance(v, GridSearch):
            raise ValueError(f"grid dim {k!r} must be pre-expanded (see grid_product)")
        else:
            out[k] = v
    if fixed:
        for k, v in fixed.items():
            out.setdefault(k, v)
    return out
