"""Regression/forecast metrics — reference ``zoo/automl/common/metrics.py`` parity.

``Evaluator.evaluate(metric, y_true, y_pred)`` with the metric names the
reference accepts (mse / mean_squared_error, rmse, mae, r2 / r_square, smape,
mape, plus accuracy for classification recipes).
"""

from __future__ import annotations

import numpy as np

EPS = 1e-10


def mse(y_true, y_pred):
    return float(np.mean((np.asarray(y_true) - np.asarray(y_pred)) ** 2))


def rmse(y_true, y_pred):
    return float(np.sqrt(mse(y_true, y_pred)))


def mae(y_true, y_pred):
    return float(np.mean(np.abs(np.asarray(y_true) - np.asarray(y_pred))))


def r2(y_true, y_pred):
    y_true = np.asarray(y_true, dtype=np.float64)
    y_pred = np.asarray(y_pred, dtype=np.float64)
    ss_res = np.sum((y_true - y_pred) ** 2)
    ss_tot = np.sum((y_true - np.mean(y_true)) ** 2)
    return float(1.0 - ss_res / (ss_tot + EPS))


def smape(y_true, y_pred):
    y_true = np.asarray(y_true, dtype=np.float64)
    y_pred = np.asarray(y_pred, dtype=np.float64)
    return float(100.0 * np.mean(2 * np.abs(y_pred - y_true) /
                                 (np.abs(y_true) + np.abs(y_pred) + EPS)))


def mape(y_true, y_pred):
    y_true = np.asarray(y_true, dtype=np.float64)
    y_pred = np.asarray(y_pred, dtype=np.float64)
    return float(100.0 * np.mean(np.abs((y_true - y_pred) / (y_true + EPS))))


def accuracy(y_true, y_pred):
    y_true = np.asarray(y_true)
    y_pred = np.asarray(y_pred)
    if y_pred.ndim > y_true.ndim:
        y_pred = np.argmax(y_pred, axis=-1)
    return float(np.mean(y_true == y_pred))


_METRICS = {
    "mse": mse, "mean_squared_error": mse,
    "rmse": rmse,
    "mae": mae, "mean_absolute_error": mae,
    "r2": r2, "r_square": r2,
    "smape": smape, "sMAPE": smape,
    "mape": mape,
    "accuracy": accuracy,
}

# metrics where larger is better (reward metrics need no negation)
LARGER_BETTER = {"r2", "r_square", "accuracy"}


class Evaluator:
    @staticmethod
    def check_metric(metric: str):
        if metric not in _METRICS:
            raise ValueError(f"metric {metric!r} not supported; choose from {sorted(_METRICS)}")

    @staticmethod
    def evaluate(metric: str, y_true, y_pred) -> float:
        Evaluator.check_metric(metric)
        return _METRICS[metric](y_true, y_pred)

    @staticmethod
    def reward(metric: str, value: float) -> float:
        """Map a metric value to 'larger is better' reward space."""
        return value if metric in LARGER_BETTER else -value
