"""AutoML time-series models — parity with the reference model set
(``pyzoo/zoo/automl/model/``: VanillaLSTM.py, Seq2Seq.py, MTNet_keras.py,
time_sequence.py ``TimeSequenceModel``).

All models share the trial-facing protocol the search engine drives:
``fit_eval(x, y, validation_data, **config) -> val_metric``, ``evaluate``,
``predict``, ``predict_with_uncertainty`` (MC dropout — the reference's ``mc``
mode), ``save``/``restore``.

TPU notes: every model compiles to one XLA program via the shared Estimator.
MTNet folds its ``long_num + 1`` memory blocks into the batch dimension so the
CNN/GRU encoder runs as one large batched matmul on the MXU instead of a
per-block Python loop.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import layers as L
from ..nn.module import Layer, get_initializer, param_dtype, split_rng
from ..nn.topology import Sequential
from .metrics import Evaluator


class BaseTSModel:
    """Shared trial protocol (reference model/abstract.py BaseModel parity)."""

    default_config: Dict = {}

    def __init__(self, future_seq_len: int = 1):
        self.future_seq_len = int(future_seq_len)
        self.model: Optional[Sequential] = None
        self.config: Dict = {}

    # -- subclass hook ---------------------------------------------------------
    def _build(self, input_shape: Tuple[int, int], config: Dict) -> Sequential:
        raise NotImplementedError

    # -- trial protocol --------------------------------------------------------
    def build(self, input_shape: Tuple[int, int], **config):
        cfg = dict(self.default_config)
        cfg.update(config)
        self.config = cfg
        self.config["input_shape"] = [int(s) for s in input_shape]
        self.model = self._build(tuple(input_shape), cfg)
        self.model.compile(optimizer=self._optimizer(cfg), loss="mse")
        return self

    def _optimizer(self, cfg):
        from ..nn.optimizers import Adam

        return Adam(lr=float(cfg.get("lr", 1e-3)))

    def fit_eval(self, x: np.ndarray, y: np.ndarray, validation_data=None,
                 metric: str = "mse", epochs: Optional[int] = None,
                 batch_size: Optional[int] = None, **config) -> float:
        """Train and return the validation metric (model/VanillaLSTM.py fit_eval
        parity: validation defaults to the train set). ``epochs``/``batch_size``
        are runtime knobs honored on EVERY call; structural hyperparameters in
        ``config`` only take effect at first build."""
        if y.ndim == 1:
            y = y[:, None]
        if self.model is None:
            self.build((x.shape[1], x.shape[2]), **config)
        cfg = self.config
        n_epochs = int(epochs if epochs is not None else
                       config.get("epochs", cfg.get("epochs", 1)))
        batch_size = int(batch_size if batch_size is not None else
                         config.get("batch_size", cfg.get("batch_size", 32)))
        batch_size = max(1, min(batch_size, len(x)))
        self.model.fit(x, y, batch_size=batch_size, nb_epoch=n_epochs)
        vx, vy = (x, y) if validation_data is None else validation_data
        if vy.ndim == 1:
            vy = vy[:, None]
        return Evaluator.evaluate(metric, vy, self.predict(vx))

    def evaluate(self, x, y, metrics: List[str] = ("mse",)) -> List[float]:
        y = np.asarray(y)
        if y.ndim == 1:
            y = y[:, None]
        pred = self.predict(x)
        return [Evaluator.evaluate(m, y, pred) for m in metrics]

    def predict(self, x) -> np.ndarray:
        if self.model is None:
            raise RuntimeError("model not built; call fit_eval or restore first")
        return np.asarray(self.model.predict(x))

    def predict_with_uncertainty(self, x, n_iter: int = 20):
        """MC-dropout predictive mean + epistemic std (reference ``mc=True``)."""
        est = self.model.estimator
        if est.train_state is None:
            # restored-but-never-stepped model: materialize state through the
            # standard lazy-init path (picks up est.initial_weights)
            self.predict(np.asarray(x)[:1])
        params = est.train_state["params"]
        mstate = est.train_state["model_state"]
        xj = jnp.asarray(x)

        @jax.jit
        def mc_pass(rng):
            y, _ = self.model.apply(params, mstate, xj, training=True, rng=rng)
            return y

        keys = jax.random.split(jax.random.PRNGKey(0), n_iter)
        preds = np.stack([np.asarray(mc_pass(k)) for k in keys])
        return preds.mean(axis=0), preds.std(axis=0)

    # -- persistence -----------------------------------------------------------
    def save(self, model_path: str, config_path: Optional[str] = None):
        from ..models.common.zoo_model import save_weights

        os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
        est = self.model.estimator
        if est.train_state is not None:
            params, mstate = (est.train_state["params"],
                              est.train_state["model_state"])
        elif est.initial_weights is not None:
            # built/restored but never stepped — save the loaded weights
            params, mstate = est.initial_weights
        else:
            raise RuntimeError("model has no weights to save — fit or restore "
                               "it first")
        save_weights(model_path, self.model, params, mstate)
        cfg = {k: v for k, v in self.config.items()}
        cfg["future_seq_len"] = self.future_seq_len
        with open(config_path or model_path + ".config.json", "w") as f:
            json.dump(cfg, f)

    def restore(self, model_path: str, config_path: Optional[str] = None, **config):
        with open(config_path or model_path + ".config.json") as f:
            cfg = json.load(f)
        cfg.update(config)
        self.future_seq_len = int(cfg.pop("future_seq_len", self.future_seq_len))
        in_shape = tuple(cfg.pop("input_shape"))
        self.build(in_shape, **cfg)          # compiles a fresh estimator
        self.model.load_weights(model_path)  # single restore path (topology.py)
        return self


class VanillaLSTM(BaseTSModel):
    """Two stacked LSTMs + dropout + Dense head (model/VanillaLSTM.py parity;
    config keys lstm_1_units/dropout_1/lstm_2_units/dropout_2/lr/batch_size)."""

    default_config = dict(lstm_1_units=32, dropout_1=0.2, lstm_2_units=32,
                          dropout_2=0.2, lr=1e-3, batch_size=64, epochs=1)

    def _build(self, input_shape, cfg):
        m = Sequential(name="vanilla_lstm")
        m.add(L.InputLayer(input_shape))
        m.add(L.LSTM(int(cfg["lstm_1_units"]), return_sequences=True))
        m.add(L.Dropout(float(cfg["dropout_1"])))
        m.add(L.LSTM(int(cfg["lstm_2_units"]), return_sequences=False))
        m.add(L.Dropout(float(cfg["dropout_2"])))
        m.add(L.Dense(self.future_seq_len))
        return m


class TSSeq2Seq(BaseTSModel):
    """Encoder/decoder LSTM forecaster (model/Seq2Seq.py parity): the encoder
    consumes the past window; the decoder is unrolled ``future_seq_len`` steps
    feeding back its own output (inference-mode decoding — avoids the reference's
    separate teacher-forcing graph while matching its predict behavior)."""

    default_config = dict(latent_dim=64, dropout=0.2, lr=1e-3, batch_size=64,
                          epochs=1)

    def _build(self, input_shape, cfg):
        m = Sequential(name="ts_seq2seq")
        m.add(L.InputLayer(input_shape))
        m.add(_Seq2SeqCore(int(cfg["latent_dim"]), self.future_seq_len,
                           float(cfg["dropout"])))
        return m


class _Seq2SeqCore(Layer):
    def __init__(self, latent_dim: int, future_seq_len: int, dropout: float,
                 name=None, input_shape=None):
        super().__init__(name=name, input_shape=input_shape)
        self.latent = latent_dim
        self.future = future_seq_len
        self.dropout = dropout
        self.encoder = L.LSTM(latent_dim, return_sequences=False)
        self.head = L.Dense(1)

    def build(self, rng, input_shape):
        k_enc, k_dec, k_head = jax.random.split(rng, 3)
        enc_p, _ = self.encoder.build(k_enc, input_shape)
        # decoder LSTM cell params: input is the previous scalar prediction
        self.decoder = L.LSTM(self.latent, return_sequences=False)
        dec_p, _ = self.decoder.build(k_dec, (self.future, 1))
        head_p, _ = self.head.build(k_head, (self.latent,))
        return {"enc": enc_p, "dec": dec_p, "head": head_p}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        k_enc, k_drop = split_rng(rng, 2)
        h_seq, _ = self.encoder.apply(params["enc"], {}, x, training=training,
                                      rng=k_enc)
        batch = x.shape[0]
        h = h_seq
        c = jnp.zeros_like(h)
        if training and self.dropout > 0 and k_drop is not None:
            keep = 1.0 - self.dropout
            h = h * jax.random.bernoulli(k_drop, keep, h.shape) / keep

        dec = self.decoder
        y0 = jnp.zeros((batch, 1), h.dtype)

        def step(carry, _):
            h_t, c_t, y_prev = carry
            (h2, c2), _out = dec.step(params["dec"], (h_t, c_t), y_prev)
            y, _ = self.head.apply(params["head"], {}, h2)
            return (h2, c2, y), y

        (_, _, _), ys = jax.lax.scan(step, (h, c, y0), None, length=self.future)
        return jnp.swapaxes(ys[..., 0], 0, 1), state  # (B, future)

    def compute_output_shape(self, input_shape):
        return (self.future,)


class MTNet(BaseTSModel):
    """Memory Time-series Network (model/MTNet_keras.py capability parity).

    Input ``(B, (long_num + 1) * time_step, F)``: ``long_num`` long-term memory
    blocks plus the short-term block. Encoder = Conv(time, cnn_height) + dropout +
    GRU. Attention over encoded memories selects context; concat with the query
    encoding feeds the head; an autoregressive linear term on the last
    ``ar_window`` target values is added (the Lin/AR component).
    """

    default_config = dict(time_step=4, long_num=3, cnn_height=2, cnn_hid_size=16,
                          rnn_hid_size=16, ar_window=2, cnn_dropout=0.2,
                          rnn_dropout=0.2, lr=1e-3, batch_size=64, epochs=1)

    def _build(self, input_shape, cfg):
        rnn_sizes = cfg.get("rnn_hid_sizes") or [int(cfg["rnn_hid_size"])]
        m = Sequential(name="mtnet")
        m.add(L.InputLayer(input_shape))
        m.add(_MTNetCore(time_step=int(cfg["time_step"]),
                         long_num=int(cfg["long_num"]),
                         cnn_height=int(cfg["cnn_height"]),
                         cnn_hid=int(cfg["cnn_hid_size"]),
                         rnn_hids=[int(s) for s in rnn_sizes],
                         ar_window=int(cfg["ar_window"]),
                         cnn_dropout=float(cfg["cnn_dropout"]),
                         rnn_dropout=float(cfg["rnn_dropout"]),
                         future=self.future_seq_len))
        return m


class _MTNetCore(Layer):
    def __init__(self, *, time_step, long_num, cnn_height, cnn_hid, rnn_hids,
                 ar_window, cnn_dropout, rnn_dropout, future, name=None,
                 input_shape=None):
        super().__init__(name=name, input_shape=input_shape)
        self.time_step = time_step
        self.long_num = long_num
        self.cnn_height = min(cnn_height, time_step)
        self.cnn_hid = cnn_hid
        self.rnn_hids = list(rnn_hids)
        self.rnn_hid = self.rnn_hids[-1]
        self.ar_window = ar_window
        self.cnn_dropout = cnn_dropout
        self.rnn_dropout = rnn_dropout
        self.future = future
        self.grus = [L.GRU(h, return_sequences=(i < len(self.rnn_hids) - 1))
                     for i, h in enumerate(self.rnn_hids)]

    def build(self, rng, input_shape):
        total_t, feat = input_shape
        need = (self.long_num + 1) * self.time_step
        if total_t < need:
            raise ValueError(
                f"MTNet needs past_seq_len >= (long_num+1)*time_step = {need}, "
                f"got {total_t}")
        keys = jax.random.split(rng, 4 + len(self.grus))
        k_conv, k_att, k_head, k_ar = keys[:4]
        dt = param_dtype()
        init = get_initializer("glorot_uniform")
        conv_k = init(k_conv, (self.cnn_height, feat, self.cnn_hid), dt)
        gru_ps = []
        t_len = self.time_step - self.cnn_height + 1
        in_dim = self.cnn_hid
        for gru, k in zip(self.grus, keys[4:]):
            p, _ = gru.build(k, (t_len, in_dim))
            gru_ps.append(p)
            in_dim = gru.output_dim
        att_w = init(k_att, (self.rnn_hid, self.rnn_hid), dt)
        head_w = init(k_head, (2 * self.rnn_hid, self.future), dt)
        head_b = jnp.zeros((self.future,), dt)
        ar_w = init(k_ar, (self.ar_window, self.future), dt)
        return {"conv": conv_k, "grus": gru_ps, "att": att_w,
                "head_w": head_w, "head_b": head_b, "ar": ar_w}, {}

    def _encode(self, params, blocks, training, rng):
        """blocks: (N, time_step, F) -> (N, rnn_hid). One batched conv+GRU stack."""
        ks = split_rng(rng, 1 + len(self.grus))
        # valid 1D conv over time: (N, T, F) x (H, F, C) -> (N, T-H+1, C)
        z = jax.lax.conv_general_dilated(
            blocks, params["conv"], window_strides=(1,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        z = jax.nn.relu(z)
        if training and self.cnn_dropout > 0 and ks[0] is not None:
            keep = 1.0 - self.cnn_dropout
            z = z * jax.random.bernoulli(ks[0], keep, z.shape) / keep
        h = z
        for gru, p, k in zip(self.grus, params["grus"], ks[1:]):
            h, _ = gru.apply(p, {}, h, training=training, rng=k)
        return h

    def apply(self, params, state, x, *, training=False, rng=None):
        k_mem, k_q, k_drop = split_rng(rng, 3)
        B = x.shape[0]
        need = (self.long_num + 1) * self.time_step
        x = x[:, -need:, :]
        blocks = x.reshape(B, self.long_num + 1, self.time_step, x.shape[-1])
        mem_blocks = blocks[:, :-1].reshape(B * self.long_num, self.time_step, -1)
        q_block = blocks[:, -1]

        mem = self._encode(params, mem_blocks, training, k_mem)
        mem = mem.reshape(B, self.long_num, self.rnn_hid)
        u = self._encode(params, q_block, training, k_q)

        # attention over memories: score_i = m_i^T W u
        scores = jnp.einsum("bnh,hk,bk->bn", mem, params["att"], u)
        alpha = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(u.dtype)
        ctx = jnp.einsum("bn,bnh->bh", alpha, mem)

        feat = jnp.concatenate([u, ctx], axis=-1)
        if training and self.rnn_dropout > 0 and k_drop is not None:
            keep = 1.0 - self.rnn_dropout
            feat = feat * jax.random.bernoulli(k_drop, keep, feat.shape) / keep
        y = feat @ params["head_w"] + params["head_b"]
        # AR component on the raw target column (col 0) of the last ar_window steps
        ar = jnp.einsum("bw,wf->bf", x[:, -self.ar_window:, 0], params["ar"])
        return y + ar, state

    def compute_output_shape(self, input_shape):
        return (self.future,)


MODEL_REGISTRY = {"LSTM": VanillaLSTM, "Seq2Seq": TSSeq2Seq, "MTNet": MTNet}


class TimeSequenceModel:
    """Dispatches to LSTM vs Seq2Seq vs MTNet from the trial config's ``model``
    key (reference model/time_sequence.py TimeSequenceModel parity; the default
    choice is LSTM for future_seq_len == 1 else Seq2Seq —
    time_sequence_predictor.py:83 docstring)."""

    def __init__(self, future_seq_len: int = 1):
        self.future_seq_len = int(future_seq_len)
        self.inner: Optional[BaseTSModel] = None
        self.model_name: Optional[str] = None

    def _select(self, config) -> str:
        if "model" in config:
            return config["model"]
        return "LSTM" if self.future_seq_len == 1 else "Seq2Seq"

    def fit_eval(self, x, y, validation_data=None, metric="mse", **config):
        name = self._select(config)
        if self.inner is None or name != self.model_name:
            self.model_name = name
            self.inner = MODEL_REGISTRY[name](future_seq_len=self.future_seq_len)
        cfg = {k: v for k, v in config.items() if k != "model"}
        return self.inner.fit_eval(x, y, validation_data=validation_data,
                                   metric=metric, **cfg)

    def evaluate(self, x, y, metrics=("mse",)):
        return self.inner.evaluate(x, y, metrics)

    def predict(self, x):
        return self.inner.predict(x)

    def predict_with_uncertainty(self, x, n_iter: int = 20):
        return self.inner.predict_with_uncertainty(x, n_iter)

    def save(self, model_path, config_path=None):
        self.inner.config["model"] = self.model_name
        self.inner.save(model_path, config_path)

    def restore(self, model_path, config_path=None, **config):
        with open(config_path or model_path + ".config.json") as f:
            saved = json.load(f)
        saved.update(config)
        name = saved.pop("model", self._select(saved))
        self.model_name = name
        self.inner = MODEL_REGISTRY[name](
            future_seq_len=saved.get("future_seq_len", self.future_seq_len))
        self.inner.restore(model_path, config_path)
        return self
