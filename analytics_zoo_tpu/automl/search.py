"""Trial search engine — the rebuild of ``RayTuneSearchEngine``
(reference ``pyzoo/zoo/automl/search/RayTuneSearchEngine.py:28``: builds Trainable
classes per config, schedules trials, returns the best).

TPU-native redesign: a trial's training step is a jitted XLA program, so there is
no cluster to schedule — trials run in-process, optionally on a thread pool
(compilation and host-side data prep overlap; device execution serializes on the
one chip anyway). Determinism: config sampling uses a seeded generator, and each
trial gets an independent, reproducible seed. Early stopping: median-stopping
across reporting rounds replaces Ray Tune's schedulers.
"""

from __future__ import annotations

import concurrent.futures as cf
import copy
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .metrics import Evaluator
from .space import grid_product, sample_config

log = logging.getLogger("analytics_zoo_tpu.automl")


@dataclass
class TrialResult:
    config: Dict[str, Any]
    metric: float                      # raw metric value (e.g. mse)
    reward: float                      # larger-is-better
    history: List[float] = field(default_factory=list)
    trial_id: int = 0
    stopped_early: bool = False
    error: Optional[str] = None


class Trial:
    """One trial: owns a model instance and reports a metric per round.

    ``trainable(config) -> fn()`` protocol: the factory returns a zero-arg
    callable; each invocation trains one round (``training_iteration`` parity)
    and returns the raw metric value.
    """

    def __init__(self, trial_id: int, config: Dict[str, Any],
                 round_fn: Callable[[], float], metric: str):
        self.trial_id = trial_id
        self.config = config
        self.round_fn = round_fn
        self.metric = metric
        self.history: List[float] = []

    def run_round(self) -> float:
        value = float(self.round_fn())
        self.history.append(value)
        return value


class SearchEngine:
    """Random + grid search with median stopping.

    Args:
        trainable: ``trainable(config, trial_seed) -> round_fn`` where
            ``round_fn()`` trains one round and returns the raw metric.
        metric: metric name (determines reward direction via Evaluator).
        num_samples: random samples per grid point (RayTune ``num_samples``).
        training_iteration: rounds per trial.
        max_workers: concurrent trials (threads; JAX dispatch releases the GIL).
        grace_rounds: rounds before median stopping can trigger.
    """

    def __init__(self, trainable, metric: str = "mse", num_samples: int = 1,
                 training_iteration: int = 1, max_workers: int = 1,
                 grace_rounds: int = 1, seed: int = 0,
                 search_alg: str = "random", n_initial: int = 4):
        self.trainable = trainable
        self.metric = metric
        self.num_samples = int(num_samples)
        self.training_iteration = max(1, int(training_iteration))
        self.max_workers = max(1, int(max_workers))
        self.grace_rounds = int(grace_rounds)
        self.seed = int(seed)
        if search_alg not in ("random", "tpe"):
            raise ValueError(f"unknown search_alg {search_alg!r}")
        self.search_alg = search_alg
        self.n_initial = int(n_initial)
        self.results: List[TrialResult] = []

    # ------------------------------------------------------------------ configs
    def _draw_configs(self, space: Dict[str, Any],
                      fixed: Optional[Dict[str, Any]]) -> List[Dict[str, Any]]:
        rng = np.random.default_rng(self.seed)
        configs = []
        for grid_part in grid_product(space):
            merged_fixed = dict(fixed or {})
            merged_fixed.update(grid_part)
            for _ in range(self.num_samples):
                configs.append(sample_config(space, rng, fixed=merged_fixed))
        return configs

    # ------------------------------------------------------------------- search
    def run(self, space: Dict[str, Any],
            fixed: Optional[Dict[str, Any]] = None) -> TrialResult:
        """Round-robin over trials with a barrier per reporting round: after each
        round, trials whose reward falls below the round median are pruned
        (median-stopping — the reference's Ray Tune scheduler capability).
        ``search_alg='tpe'`` instead runs trials sequentially, each config
        suggested from the history (HyperOptSearch capability)."""
        if self.search_alg == "tpe":
            return self._run_tpe(space, fixed)
        configs = self._draw_configs(space, fixed)
        n = len(configs)
        failed: List[TrialResult] = []
        trials: List[Trial] = []
        for tid, config in enumerate(configs):
            try:
                round_fn = self.trainable(copy.deepcopy(config),
                                          trial_seed=self.seed * 10007 + tid)
                trials.append(Trial(tid, config, round_fn, self.metric))
            except Exception as e:
                log.warning("trial %d setup failed: %s", tid, e)
                failed.append(TrialResult(config=config, metric=float("inf"),
                                          reward=float("-inf"), trial_id=tid,
                                          error=str(e)))

        alive = list(trials)
        stopped: Dict[int, bool] = {}

        def run_one(trial: Trial):
            try:
                return trial, trial.run_round(), None
            except Exception as e:
                log.warning("trial %d failed: %s", trial.trial_id, e)
                return trial, None, str(e)

        errors: Dict[int, str] = {}
        for rnd in range(self.training_iteration):
            if not alive:
                break
            if self.max_workers > 1 and len(alive) > 1:
                with cf.ThreadPoolExecutor(max_workers=self.max_workers) as pool:
                    outcomes = list(pool.map(run_one, alive))
            else:
                outcomes = [run_one(t) for t in alive]
            survivors, rewards = [], []
            for trial, value, err in outcomes:
                if err is not None:
                    errors[trial.trial_id] = err
                    continue
                survivors.append(trial)
                rewards.append(Evaluator.reward(self.metric, value))
            alive = survivors
            if (rnd + 1 > self.grace_rounds and len(alive) >= 3
                    and rnd + 1 < self.training_iteration):
                med = float(np.median(rewards))
                pruned = [t for t, r in zip(alive, rewards) if r < med]
                alive = [t for t, r in zip(alive, rewards) if r >= med]
                for t in pruned:
                    stopped[t.trial_id] = True

        self.results = list(failed)
        for trial in trials:
            if trial.trial_id in errors:
                self.results.append(TrialResult(
                    config=trial.config, metric=float("inf"),
                    reward=float("-inf"), history=trial.history,
                    trial_id=trial.trial_id, error=errors[trial.trial_id]))
            elif trial.history:
                final = trial.history[-1]
                self.results.append(TrialResult(
                    config=trial.config, metric=final,
                    reward=Evaluator.reward(self.metric, final),
                    history=trial.history, trial_id=trial.trial_id,
                    stopped_early=stopped.get(trial.trial_id, False)))
        self.results.sort(key=lambda r: r.trial_id)

        ok = [r for r in self.results if r.error is None]
        if not ok:
            errs = {r.trial_id: r.error for r in self.results}
            raise RuntimeError(f"all {n} trials failed: {errs}")
        best = max(ok, key=lambda r: r.reward)
        log.info("search done: %d trials, best %s=%.6g (trial %d)",
                 n, self.metric, best.metric, best.trial_id)
        return best

    # --------------------------------------------------------------------- tpe
    def _run_tpe(self, space: Dict[str, Any],
                 fixed: Optional[Dict[str, Any]]) -> TrialResult:
        """Sequential model-based search: the first ``n_initial`` configs are
        random, every later one maximizes the TPE good/bad density ratio over
        completed-trial rewards. Grid dims are expanded as usual; the trial
        budget is ``num_samples`` per grid point."""
        from .tpe import tpe_suggest

        rng = np.random.default_rng(self.seed)
        self.results = []
        tid = 0
        for grid_part in grid_product(space):
            merged_fixed = dict(fixed or {})
            merged_fixed.update(grid_part)
            history: List[tuple] = []
            for i in range(self.num_samples):
                if i < self.n_initial or len(history) < 2:
                    config = sample_config(space, rng, fixed=merged_fixed)
                else:
                    config = tpe_suggest(space, history, rng,
                                         fixed=merged_fixed)
                try:
                    round_fn = self.trainable(
                        copy.deepcopy(config),
                        trial_seed=self.seed * 10007 + tid)
                    trial = Trial(tid, config, round_fn, self.metric)
                    for _ in range(self.training_iteration):
                        value = trial.run_round()
                    reward = Evaluator.reward(self.metric, value)
                    history.append((config, reward))
                    self.results.append(TrialResult(
                        config=config, metric=value, reward=reward,
                        history=trial.history, trial_id=tid))
                except Exception as e:
                    log.warning("tpe trial %d failed: %s", tid, e)
                    self.results.append(TrialResult(
                        config=config, metric=float("inf"),
                        reward=float("-inf"), trial_id=tid, error=str(e)))
                tid += 1
        ok = [r for r in self.results if r.error is None]
        if not ok:
            raise RuntimeError(f"all {tid} tpe trials failed")
        best = max(ok, key=lambda r: r.reward)
        log.info("tpe search done: %d trials, best %s=%.6g (trial %d)",
                 tid, self.metric, best.metric, best.trial_id)
        return best
