"""AutoML subsystem — hyperparameter search over time-series (and generic) models.

Capability parity with the reference's ``pyzoo/zoo/automl/`` (SURVEY.md §2.7):
``TimeSequencePredictor.fit`` (regression/time_sequence_predictor.py:37) drives a
search engine over trial configs drawn from a ``Recipe`` search space, each trial
training a ``TimeSequenceModel`` on features produced by
``TimeSequenceFeatureTransformer`` (feature/time_sequence.py:30), and returns a
``TimeSequencePipeline`` (pipeline/time_sequence.py:28).

TPU-native redesign: trials are plain Python objects driven by a deterministic
in-process :class:`SearchEngine` (no Ray) — each trial's train step is a jitted
XLA program, so trial concurrency is a scheduling detail (threads share the one
chip) rather than a cluster service. Median-stopping replaces Ray Tune's
schedulers.
"""

from .space import Choice, Uniform, LogUniform, RandInt, QUniform, GridSearch, sample_config
from .metrics import Evaluator
from .feature import TimeSequenceFeatureTransformer
from .models import VanillaLSTM, TSSeq2Seq, MTNet, TimeSequenceModel
from .search import SearchEngine, Trial, TrialResult
from .tpe import tpe_suggest
from .recipe import (Recipe, SmokeRecipe, LSTMRandomGridRecipe, MTNetSmokeRecipe,
                     MTNetRandomGridRecipe, Seq2SeqRandomRecipe, RandomRecipe)
from .pipeline import TimeSequencePipeline, load_ts_pipeline
from .predictor import TimeSequencePredictor

__all__ = [
    "Choice", "Uniform", "LogUniform", "RandInt", "QUniform", "GridSearch",
    "sample_config", "Evaluator", "TimeSequenceFeatureTransformer",
    "VanillaLSTM", "TSSeq2Seq", "MTNet", "TimeSequenceModel",
    "SearchEngine", "Trial", "TrialResult", "tpe_suggest",
    "Recipe", "SmokeRecipe", "LSTMRandomGridRecipe", "MTNetSmokeRecipe",
    "MTNetRandomGridRecipe", "Seq2SeqRandomRecipe", "RandomRecipe",
    "TimeSequencePipeline", "load_ts_pipeline", "TimeSequencePredictor",
]
