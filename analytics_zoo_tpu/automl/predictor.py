"""TimeSequencePredictor — the AutoML entry point.

Reference parity: ``zoo/automl/regression/time_sequence_predictor.py:37-78``
(constructor args name/logs_dir/future_seq_len/dt_col/target_col/
extra_features_col/drop_missing; ``fit(input_df, validation_df, metric, recipe)``
returns a fitted TimeSequencePipeline).

Redesign: trials run through the in-process :class:`SearchEngine`; each trial
fits a fresh ``TimeSequenceModel`` on features from a per-trial
``TimeSequenceFeatureTransformer`` (feature selection is part of the config).
"""

from __future__ import annotations

import logging
from typing import Optional

from .feature import TimeSequenceFeatureTransformer
from .metrics import Evaluator
from .models import TimeSequenceModel
from .pipeline import TimeSequencePipeline
from .recipe import Recipe, SmokeRecipe
from .search import SearchEngine

log = logging.getLogger("analytics_zoo_tpu.automl")


def _effective_config(config: dict) -> dict:
    """Derive dependent keys: MTNet consumes (long_num+1)*time_step past steps,
    so its window length is implied rather than searched (MTNet_keras.py
    behavior)."""
    cfg = dict(config)
    if cfg.get("model") == "MTNet" and "past_seq_len" not in cfg:
        cfg["past_seq_len"] = ((int(cfg.get("long_num", 3)) + 1)
                               * int(cfg.get("time_step", 4)))
    return cfg


class TimeSequencePredictor:
    def __init__(self, name: str = "automl", logs_dir: str = "~/zoo_automl_logs",
                 future_seq_len: int = 1, dt_col: str = "datetime",
                 target_col: str = "value", extra_features_col=None,
                 drop_missing: bool = True):
        self.name = name
        self.logs_dir = logs_dir
        self.future_seq_len = int(future_seq_len)
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = extra_features_col
        self.drop_missing = drop_missing
        self.pipeline: Optional[TimeSequencePipeline] = None

    def _make_ft(self) -> TimeSequenceFeatureTransformer:
        return TimeSequenceFeatureTransformer(
            future_seq_len=self.future_seq_len, dt_col=self.dt_col,
            target_col=self.target_col, extra_features_col=self.extra_features_col,
            drop_missing=self.drop_missing)

    def fit(self, input_df, validation_df=None, metric: str = "mse",
            recipe: Optional[Recipe] = None,
            max_workers: int = 1, seed: int = 0,
            search_alg: str = "random") -> TimeSequencePipeline:
        """Search + refit. (The reference's ``mc`` flag is not a fit-time mode
        here — MC-dropout uncertainty is always available via
        ``pipeline.predict_with_uncertainty``.)"""
        Evaluator.check_metric(metric)
        recipe = recipe or SmokeRecipe()
        probe_ft = self._make_ft()
        features = probe_ft.get_feature_list(input_df)
        space = recipe.search_space(features)
        runtime = recipe.runtime_params()

        predictor = self

        def trainable(config, trial_seed: int = 0):
            del trial_seed  # trials are deterministic per config by design
            config = _effective_config(config)
            ft = predictor._make_ft()
            x, y = ft.fit_transform(input_df, **config)
            val = (ft.transform(validation_df, is_train=True)
                   if validation_df is not None else None)
            model = TimeSequenceModel(future_seq_len=predictor.future_seq_len)

            def round_fn():
                return model.fit_eval(x, y, validation_data=val, metric=metric,
                                      **{k: v for k, v in config.items()
                                         if k not in ("selected_features",
                                                      "past_seq_len")})

            return round_fn

        engine = SearchEngine(trainable, metric=metric,
                              num_samples=runtime.get("num_samples", 1),
                              training_iteration=runtime.get("training_iteration", 1),
                              max_workers=max_workers, seed=seed,
                              search_alg=search_alg)
        best = engine.run(space)

        # refit the best config on the full data to produce the pipeline
        best.config = _effective_config(best.config)
        ft = self._make_ft()
        x, y = ft.fit_transform(input_df, **best.config)
        val = (ft.transform(validation_df, is_train=True)
               if validation_df is not None else None)
        model = TimeSequenceModel(future_seq_len=self.future_seq_len)
        value = model.fit_eval(x, y, validation_data=val, metric=metric,
                               **{k: v for k, v in best.config.items()
                                  if k not in ("selected_features", "past_seq_len")})
        log.info("best config refit %s=%.6g", metric, value)
        self.pipeline = TimeSequencePipeline(ft, model, config=best.config,
                                             name=self.name)
        return self.pipeline

    def evaluate(self, input_df, metrics=("mse",), multioutput="uniform_average"):
        self._require_fitted()
        return self.pipeline.evaluate(input_df, metrics, multioutput)

    def predict(self, input_df):
        self._require_fitted()
        return self.pipeline.predict(input_df)

    def _require_fitted(self):
        if self.pipeline is None:
            raise RuntimeError("predictor not fitted; call fit() first")
