"""Time-sequence feature engineering — reference
``zoo/automl/feature/time_sequence.py:30`` (TimeSequenceFeatureTransformer) parity.

Pipeline: datetime feature generation → feature selection (per trial config) →
standard scaling → rolling-window tensorization:
``x: (N, past_seq_len, n_features)``, ``y: (N, future_seq_len)``.

Feature generation mirrors the reference's derived calendar features
(feature/time_sequence.py:526-556): HOUR / DAY / WEEKDAY / MONTH / MINUTE /
IS_WEEKEND / IS_AWAKE(6-23) / IS_BUSY_HOURS(7-9,16-19).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

_CAL_FEATURES = ["HOUR", "DAY", "WEEKDAY", "MONTH", "MINUTE",
                 "IS_WEEKEND", "IS_AWAKE", "IS_BUSY_HOURS"]


def _roll(data: np.ndarray, window: int) -> np.ndarray:
    """(T, F) -> (T-window+1, window, F) sliding windows (stride 1)."""
    n = data.shape[0] - window + 1
    if n <= 0:
        raise ValueError(f"series length {data.shape[0]} < window {window}")
    idx = np.arange(window)[None, :] + np.arange(n)[:, None]
    return data[idx]


class TimeSequenceFeatureTransformer:
    def __init__(self, future_seq_len: int = 1, dt_col: str = "datetime",
                 target_col: str = "value", extra_features_col: Optional[List[str]] = None,
                 drop_missing: bool = True):
        self.future_seq_len = int(future_seq_len)
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = list(extra_features_col or [])
        self.drop_missing = drop_missing
        self.past_seq_len: Optional[int] = None
        self.selected_features: Optional[List[str]] = None
        self.scale_mean: Optional[np.ndarray] = None
        self.scale_std: Optional[np.ndarray] = None

    # ------------------------------------------------------------------ features
    def get_feature_list(self, input_df) -> List[str]:
        """All candidate feature names (calendar + extra cols) for a recipe."""
        return _CAL_FEATURES + list(self.extra_features_col)

    def _check_input(self, input_df, mode: str = "train"):
        import pandas as pd
        if not isinstance(input_df, pd.DataFrame):
            raise ValueError("input must be a pandas DataFrame")
        if self.dt_col not in input_df.columns:
            raise ValueError(f"missing datetime column {self.dt_col!r}")
        # the target column is required even at predict time: column 0 of every
        # window is the past target history (autoregressive input)
        if self.target_col not in input_df.columns:
            raise ValueError(f"missing target column {self.target_col!r}")

    def _generate_calendar(self, dt) -> Dict[str, np.ndarray]:
        hour = dt.dt.hour.to_numpy()
        weekday = dt.dt.dayofweek.to_numpy()
        return {
            "HOUR": hour.astype(np.float64),
            "DAY": dt.dt.day.to_numpy().astype(np.float64),
            "WEEKDAY": weekday.astype(np.float64),
            "MONTH": dt.dt.month.to_numpy().astype(np.float64),
            "MINUTE": dt.dt.minute.to_numpy().astype(np.float64),
            "IS_WEEKEND": (weekday >= 5).astype(np.float64),
            "IS_AWAKE": ((hour >= 6) & (hour <= 23)).astype(np.float64),
            "IS_BUSY_HOURS": (((hour >= 7) & (hour <= 9)) |
                              ((hour >= 16) & (hour <= 19))).astype(np.float64),
        }

    def _feature_matrix(self, input_df, features: List[str], with_target: bool):
        import pandas as pd
        df = input_df.copy()
        if self.drop_missing:
            df = df.dropna(subset=[c for c in [self.target_col] + self.extra_features_col
                                   if c in df.columns])
        dt = pd.to_datetime(df[self.dt_col])
        cal = self._generate_calendar(dt)
        cols = []
        # column 0 is always the (past) target value — matches the reference's
        # "value plus several features" layout (time_sequence_predictor.py:42-44)
        if with_target:
            cols.append(df[self.target_col].to_numpy(dtype=np.float64))
        for f in features:
            if f in cal:
                cols.append(cal[f])
            elif f in df.columns:
                cols.append(df[f].to_numpy(dtype=np.float64))
            else:
                raise ValueError(f"unknown feature {f!r}")
        return np.stack(cols, axis=1), dt

    # ------------------------------------------------------------------ fit/transform
    def fit_transform(self, input_df, **config) -> Tuple[np.ndarray, np.ndarray]:
        self._check_input(input_df)
        self.past_seq_len = int(config.get("past_seq_len", 2))
        feats = config.get("selected_features", self.get_feature_list(input_df))
        if isinstance(feats, str):
            feats = json.loads(feats)
        self.selected_features = list(feats)
        mat, _ = self._feature_matrix(input_df, self.selected_features, with_target=True)
        self.scale_mean = mat.mean(axis=0)
        self.scale_std = mat.std(axis=0) + 1e-9
        return self._tensorize(mat, train=True)

    def transform(self, input_df, is_train: bool = True):
        if self.selected_features is None:
            raise RuntimeError("transformer not fitted")
        self._check_input(input_df, mode="train" if is_train else "predict")
        mat, _ = self._feature_matrix(input_df, self.selected_features,
                                      with_target=True)
        return self._tensorize(mat, train=is_train)

    def _tensorize(self, mat: np.ndarray, train: bool):
        scaled = (mat - self.scale_mean) / self.scale_std
        if train:
            total = self.past_seq_len + self.future_seq_len
            windows = _roll(scaled, total)
            x = windows[:, :self.past_seq_len, :]
            y = windows[:, self.past_seq_len:, 0]
            return x, y
        x = _roll(scaled, self.past_seq_len)
        return x, None

    # ------------------------------------------------------------------ inverse
    def unscale(self, y: np.ndarray) -> np.ndarray:
        """Inverse-scale predictions back to target units (column 0)."""
        return y * self.scale_std[0] + self.scale_mean[0]

    def unscale_uncertainty(self, y_std: np.ndarray) -> np.ndarray:
        return y_std * self.scale_std[0]

    def post_processing(self, input_df, y_pred: np.ndarray, is_train: bool):
        """Unscale + attach forecast datetimes (reference :230-278 behavior).

        Window i covers rows ``i..i+past_seq_len-1`` and predicts the NEXT step,
        so its timestamp is the window's last datetime plus one series period
        (matching the training alignment in :meth:`_tensorize`). Datetimes come
        from the same NaN-dropped frame the windows were built from.
        """
        import pandas as pd
        y_unscale = self.unscale(y_pred)
        if is_train:
            return y_unscale
        _, dt = self._feature_matrix(input_df, self.selected_features,
                                     with_target=True)
        delta = dt.diff().mode().iloc[0] if len(dt) > 1 else pd.Timedelta(0)
        out_dt = (dt.iloc[self.past_seq_len - 1:] + delta).reset_index(drop=True)
        cols = {self.dt_col: out_dt}
        if y_unscale.ndim == 1:
            y_unscale = y_unscale[:, None]
        for i in range(y_unscale.shape[1]):
            cols[f"{self.target_col}_{i}" if y_unscale.shape[1] > 1
                 else self.target_col] = y_unscale[:, i]
        return pd.DataFrame(cols)

    # ------------------------------------------------------------------ persistence
    def save(self, file_path: str):
        os.makedirs(os.path.dirname(file_path) or ".", exist_ok=True)
        cfg = {
            "future_seq_len": self.future_seq_len, "dt_col": self.dt_col,
            "target_col": self.target_col, "extra_features_col": self.extra_features_col,
            "drop_missing": self.drop_missing, "past_seq_len": self.past_seq_len,
            "selected_features": self.selected_features,
            "scale_mean": None if self.scale_mean is None else self.scale_mean.tolist(),
            "scale_std": None if self.scale_std is None else self.scale_std.tolist(),
        }
        with open(file_path, "w") as f:
            json.dump(cfg, f)

    def restore(self, file_path: str = None, **config):
        if file_path is not None:
            with open(file_path) as f:
                config = json.load(f)
        self.future_seq_len = config["future_seq_len"]
        self.dt_col = config["dt_col"]
        self.target_col = config["target_col"]
        self.extra_features_col = config["extra_features_col"]
        self.drop_missing = config["drop_missing"]
        self.past_seq_len = config["past_seq_len"]
        self.selected_features = config["selected_features"]
        self.scale_mean = np.asarray(config["scale_mean"]) if config["scale_mean"] else None
        self.scale_std = np.asarray(config["scale_std"]) if config["scale_std"] else None
        return self
