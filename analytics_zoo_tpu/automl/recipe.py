"""Search-space recipes — reference ``zoo/automl/config/recipe.py`` parity
(SmokeRecipe, LSTMGridRandomRecipe, MTNetGridRandomRecipe, RandomRecipe, …).

A Recipe = a search space over trial configs + runtime parameters
(num_samples, training_iteration / epochs). Spaces use the samplers in
:mod:`.space` instead of ``ray.tune`` objects.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from .space import Choice, GridSearch, Sampler, Uniform


class Recipe:
    def __init__(self):
        self.training_iteration = 1
        self.num_samples = 1

    def search_space(self, all_available_features: List[str]) -> Dict[str, Any]:
        raise NotImplementedError

    def runtime_params(self) -> Dict[str, Any]:
        return {"training_iteration": self.training_iteration,
                "num_samples": self.num_samples}


class SmokeRecipe(Recipe):
    """One-epoch single-sample sanity recipe (recipe.py SmokeRecipe parity)."""

    def search_space(self, all_available_features):
        return {
            "selected_features": json.dumps(list(all_available_features)),
            "model": "LSTM",
            "lstm_1_units": Choice([16, 32]),
            "dropout_1": Uniform(0.2, 0.5),
            "lstm_2_units": Choice([16, 32]),
            "dropout_2": Uniform(0.2, 0.5),
            "lr": 0.001,
            "batch_size": 256,
            "epochs": 1,
            "past_seq_len": 2,
        }


class LSTMRandomGridRecipe(Recipe):
    """LSTM grid over units × random dropout/lr (LSTMGridRandomRecipe parity)."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 training_iteration: int = 1,
                 past_seq_len: int = 2,
                 lstm_1_units=(16, 32, 64), lstm_2_units=(16, 32, 64),
                 batch_size=(32, 64)):
        super().__init__()
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.epochs = epochs
        self.past_seq_len = past_seq_len
        self.lstm_1_units = list(lstm_1_units)
        self.lstm_2_units = list(lstm_2_units)
        self.batch_size = list(batch_size)

    def search_space(self, all_available_features):
        return {
            "selected_features": json.dumps(list(all_available_features)),
            "model": "LSTM",
            "lstm_1_units": GridSearch(self.lstm_1_units),
            "dropout_1": Uniform(0.2, 0.5),
            "lstm_2_units": GridSearch(self.lstm_2_units),
            "dropout_2": Uniform(0.2, 0.5),
            "lr": Uniform(1e-4, 1e-2),
            "batch_size": Choice(self.batch_size),
            "epochs": self.epochs,
            "past_seq_len": self.past_seq_len,
        }


class MTNetSmokeRecipe(Recipe):
    def search_space(self, all_available_features):
        return {
            "selected_features": json.dumps(list(all_available_features)),
            "model": "MTNet",
            "lr": 0.001,
            "batch_size": 16,
            "epochs": 1,
            "cnn_dropout": 0.2,
            "rnn_dropout": 0.2,
            "time_step": Choice([3, 4]),
            "cnn_height": 2,
            "long_num": Choice([3, 4]),
            "ar_window": Choice([2, 3]),
            "cnn_hid_size": Choice([16, 32]),
            "rnn_hid_size": 16,
        }


class MTNetRandomGridRecipe(Recipe):
    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 time_step=(3, 4), long_num=(3, 4), cnn_height=(2, 3),
                 training_iteration: int = 1):
        super().__init__()
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.epochs = epochs
        self.time_step = list(time_step)
        self.long_num = list(long_num)
        self.cnn_height = list(cnn_height)

    def search_space(self, all_available_features):
        return {
            "selected_features": json.dumps(list(all_available_features)),
            "model": "MTNet",
            "lr": Uniform(1e-4, 1e-2),
            "batch_size": Choice([32, 64]),
            "epochs": self.epochs,
            "cnn_dropout": Uniform(0.1, 0.4),
            "rnn_dropout": Uniform(0.1, 0.4),
            "time_step": GridSearch(self.time_step),
            "long_num": GridSearch(self.long_num),
            "cnn_height": Choice(self.cnn_height),
            "ar_window": Choice([2, 3]),
            "cnn_hid_size": Choice([16, 32, 64]),
            "rnn_hid_size": Choice([16, 32]),
        }


class Seq2SeqRandomRecipe(Recipe):
    """Random search for the encoder/decoder forecaster (future_seq_len > 1)."""

    def __init__(self, num_rand_samples: int = 2, epochs: int = 5,
                 past_seq_len: int = 8, training_iteration: int = 1):
        super().__init__()
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.epochs = epochs
        self.past_seq_len = past_seq_len

    def search_space(self, all_available_features):
        return {
            "selected_features": json.dumps(list(all_available_features)),
            "model": "Seq2Seq",
            "latent_dim": Choice([32, 64, 128]),
            "dropout": Uniform(0.1, 0.4),
            "lr": Uniform(1e-4, 1e-2),
            "batch_size": Choice([32, 64]),
            "epochs": self.epochs,
            "past_seq_len": self.past_seq_len,
        }


class RandomRecipe(Recipe):
    """Pure random search over the LSTM space (recipe.py RandomRecipe parity),
    including random feature-subset selection."""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 look_back: int = 2, training_iteration: int = 1):
        super().__init__()
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.epochs = epochs
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": _FeatureSubset(list(all_available_features)),
            "model": "LSTM",
            "lstm_1_units": Choice([8, 16, 32, 64, 128]),
            "dropout_1": Uniform(0.2, 0.5),
            "lstm_2_units": Choice([8, 16, 32, 64, 128]),
            "dropout_2": Uniform(0.2, 0.5),
            "lr": Uniform(1e-4, 1e-1),
            "batch_size": Choice([32, 64, 1024]),
            "epochs": self.epochs,
            "past_seq_len": self.look_back,
        }


class _FeatureSubset(Sampler):
    """Sampler drawing a random non-empty subset of candidate features."""

    def __init__(self, features: List[str]):
        self.features = features

    def sample(self, rng):
        if not self.features:
            return json.dumps([])
        mask = rng.random(len(self.features)) < 0.5
        if not mask.any():
            mask[int(rng.integers(len(self.features)))] = True
        return json.dumps([f for f, m in zip(self.features, mask) if m])
