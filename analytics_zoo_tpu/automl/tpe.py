"""TPE (tree-structured Parzen estimator) suggestion — the search-algorithm
capability the reference gets from Ray Tune's ``HyperOptSearch``
(RayTuneSearchEngine accepts a ``search_alg``; zoo recipes default to random).

Dependency-free TPE-lite: past trials split into good/bad by reward quantile
``gamma``; numeric dims get Parzen windows (a mixture of normals at observed
values, log-space for LogUniform), categoricals get smoothed frequency
ratios. Candidates are drawn from the good-trial density and ranked by
``l_good(x) / l_bad(x)`` — the standard EI-proportional TPE criterion
(Bergstra et al. 2011).
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from .space import (Choice, GridSearch, LogUniform, QUniform, RandInt, Sampler,
                    Uniform, sample_config)


def _split(history: List[Tuple[Dict[str, Any], float]], gamma: float):
    ordered = sorted(history, key=lambda h: -h[1])
    n_good = max(1, int(np.ceil(gamma * len(ordered))))
    good = [c for c, _ in ordered[:n_good]]
    bad = [c for c, _ in ordered[n_good:]] or [ordered[-1][0]]
    return good, bad


def _kde_logpdf(x: float, obs: Sequence[float], lo: float, hi: float) -> float:
    obs = np.asarray(obs, dtype=np.float64)
    span = max(hi - lo, 1e-12)
    bw = max(span / max(np.sqrt(len(obs)), 1.0), 1e-3 * span)
    z = (x - obs) / bw
    dens = np.mean(np.exp(-0.5 * z * z) / (bw * np.sqrt(2 * np.pi)))
    return float(np.log(dens + 1e-300))


def _dim_bounds(dim) -> Tuple[float, float, bool]:
    """(lo, hi, in_log_space) for a numeric sampler."""
    if isinstance(dim, LogUniform):
        return np.log(dim.low), np.log(dim.high), True
    return dim.low, dim.high, False


def _to_axis(dim, v: float) -> float:
    return float(np.log(v)) if isinstance(dim, LogUniform) else float(v)


def _from_axis(dim, t: float):
    if isinstance(dim, LogUniform):
        return float(np.exp(t))
    if isinstance(dim, RandInt):
        return int(np.clip(round(t), dim.low, dim.high - 1))
    if isinstance(dim, QUniform):
        return float(np.clip(np.round(t / dim.q) * dim.q, dim.low, dim.high))
    return float(np.clip(t, dim.low, dim.high))


def tpe_suggest(space: Dict[str, Any],
                history: List[Tuple[Dict[str, Any], float]],
                rng: np.random.Generator, gamma: float = 0.25,
                n_candidates: int = 24,
                fixed: Dict[str, Any] = None) -> Dict[str, Any]:
    """Suggest one config. With fewer than 2 observations (or an empty
    numeric/categorical split) this degrades to a random sample."""
    if len(history) < 2:
        return sample_config(space, rng, fixed=fixed)
    good, bad = _split(history, gamma)
    out = dict(fixed or {})
    for key, dim in space.items():
        if key in out:
            continue
        if isinstance(dim, GridSearch):
            raise ValueError(
                f"grid dim {key!r} must be pre-expanded into `fixed` "
                "(see grid_product)")
        if not isinstance(dim, Sampler):
            out[key] = dim
            continue
        g_obs = [c[key] for c in good if key in c]
        b_obs = [c[key] for c in bad if key in c]
        if not g_obs or not b_obs:
            out[key] = dim.sample(rng)
            continue
        if isinstance(dim, Choice):
            # smoothed frequency ratio over the categorical values
            vals = dim.values
            gc = np.array([g_obs.count(v) + 1.0 for v in vals])
            bc = np.array([b_obs.count(v) + 1.0 for v in vals])
            score = (gc / gc.sum()) / (bc / bc.sum())
            # sample from the good distribution, tilted by the ratio
            p = gc / gc.sum() * score
            p /= p.sum()
            out[key] = vals[int(rng.choice(len(vals), p=p))]
            continue
        lo, hi, _logspace = _dim_bounds(dim)
        g_axis = [_to_axis(dim, v) for v in g_obs]
        b_axis = [_to_axis(dim, v) for v in b_obs]
        # candidates from the good Parzen mixture + a couple of uniform probes
        # so the search never collapses onto one mode
        span = max(hi - lo, 1e-12)
        bw = max(span / max(np.sqrt(len(g_axis)), 1.0), 1e-3 * span)
        centers = rng.choice(g_axis, size=max(n_candidates - 2, 1))
        cands = list(centers + rng.normal(0.0, bw, size=len(centers)))
        cands += list(rng.uniform(lo, hi, size=2))
        best_t, best_score = None, -np.inf
        for t in cands:
            t = float(np.clip(t, lo, hi))
            s = _kde_logpdf(t, g_axis, lo, hi) - _kde_logpdf(t, b_axis, lo, hi)
            if s > best_score:
                best_t, best_score = t, s
        out[key] = _from_axis(dim, best_t)
    return out
