"""TimeSequencePipeline — fitted transformer + model as one deployable unit.

Reference parity: ``zoo/automl/pipeline/time_sequence.py:28`` (TimeSequencePipeline:
evaluate/predict/fit(incremental)/save/load, plus ``load_ts_pipeline``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

from .feature import TimeSequenceFeatureTransformer
from .metrics import Evaluator
from .models import TimeSequenceModel


class TimeSequencePipeline:
    def __init__(self, feature_transformer: TimeSequenceFeatureTransformer,
                 model: TimeSequenceModel, config: Optional[Dict] = None,
                 name: str = "ts_pipeline"):
        self.ft = feature_transformer
        self.model = model
        self.config = dict(config or {})
        self.name = name

    # ------------------------------------------------------------------ use
    def fit(self, input_df, validation_df=None, epoch_num: int = 1):
        """Incremental fit on new data with the SAME config (pipeline
        time_sequence.py fit parity — search is NOT re-run)."""
        x, y = self.ft.transform(input_df, is_train=True)
        val = None
        if validation_df is not None:
            val = self.ft.transform(validation_df, is_train=True)
        cfg = {k: v for k, v in self.config.items()
               if k not in ("epochs", "input_shape")}
        self.model.fit_eval(x, y, validation_data=val, epochs=epoch_num, **cfg)
        return self

    def evaluate(self, input_df, metrics: List[str] = ("mse",),
                 multioutput: str = "uniform_average") -> List[float]:
        for m in metrics:
            Evaluator.check_metric(m)
        x, y = self.ft.transform(input_df, is_train=True)
        y_pred = self.model.predict(x)
        y_unscale = self.ft.unscale(y)
        y_pred_unscale = self.ft.unscale(y_pred)
        if multioutput == "raw_values" and y_unscale.ndim > 1 and y_unscale.shape[1] > 1:
            return [[Evaluator.evaluate(m, y_unscale[:, i], y_pred_unscale[:, i])
                     for i in range(y_unscale.shape[1])] for m in metrics]
        return [Evaluator.evaluate(m, y_unscale, y_pred_unscale) for m in metrics]

    def predict(self, input_df):
        """Forecast: returns a DataFrame of datetime + predicted target columns."""
        x, _ = self.ft.transform(input_df, is_train=False)
        y_pred = self.model.predict(x)
        return self.ft.post_processing(input_df, y_pred, is_train=False)

    def predict_with_uncertainty(self, input_df, n_iter: int = 20):
        x, _ = self.ft.transform(input_df, is_train=False)
        mean, std = self.model.predict_with_uncertainty(x, n_iter=n_iter)
        return (self.ft.post_processing(input_df, mean, is_train=False),
                self.ft.unscale_uncertainty(std))

    # ------------------------------------------------------------------ persist
    def save(self, pipeline_file: str):
        """Save to a directory (the reference zips; a dir keeps it simple/sharded)."""
        os.makedirs(pipeline_file, exist_ok=True)
        self.ft.save(os.path.join(pipeline_file, "feature_transformer.json"))
        self.model.save(os.path.join(pipeline_file, "model"),
                        os.path.join(pipeline_file, "model.config.json"))
        with open(os.path.join(pipeline_file, "pipeline.json"), "w") as f:
            json.dump({"name": self.name, "config": _jsonable(self.config)}, f)
        return pipeline_file


def _jsonable(d: Dict) -> Dict:
    out = {}
    for k, v in d.items():
        if isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        out[k] = v
    return out


def load_ts_pipeline(pipeline_file: str) -> TimeSequencePipeline:
    with open(os.path.join(pipeline_file, "pipeline.json")) as f:
        meta = json.load(f)
    ft = TimeSequenceFeatureTransformer()
    ft.restore(os.path.join(pipeline_file, "feature_transformer.json"))
    model = TimeSequenceModel(future_seq_len=ft.future_seq_len)
    model.restore(os.path.join(pipeline_file, "model"),
                  os.path.join(pipeline_file, "model.config.json"))
    return TimeSequencePipeline(ft, model, config=meta.get("config"),
                                name=meta.get("name", "ts_pipeline"))
