"""Blockwise (flash) attention — the pallas TPU kernel (SURVEY.md §7 hard parts:
"ring attention / SP pallas kernel").

Forward: tiled online-softmax. Grid (B·H, T_q/block_q, T_kv/block_k); each
program folds one K/V tile into fp32 VMEM accumulators (m, l, acc), writing the
normalized output on the last K tile. Q·Kᵀ and P·V hit the MXU per tile; scores
never materialize in HBM — peak memory O(block_q · block_k) per core instead of
O(T²). Causal masking skips fully-future K tiles (no wasted tiles beyond the
diagonal).

Backward: tiled pallas kernels recomputing probabilities from the saved
log-sum-exp (standard flash recompute: P = exp(S − lse)). Two passes:
``_bwd_dq_kernel`` (grid over Q tiles, folding K/V tiles) and
``_bwd_dkv_kernel`` (grid over K tiles, folding Q tiles). Like the forward,
scores/probabilities live only in VMEM — peak HBM stays O(T·D), not O(T²),
for training as well as inference. Only the non-pallas fallback materializes
full attention.

Layout: (B, T, H, D) like the other attention strategies. On non-TPU backends
the kernel runs in interpreter mode (tests) or falls back to full attention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ..common.compat import tpu_compiler_params

NEG_INF = -1e30

#: Flash-aware rematerialization policy: under ``jax.checkpoint`` save ONLY the
#: flash kernel's output + log-sum-exp (tagged in ``_flash_attention_fwd_res``),
#: so the backward pass reuses the kernel's saved statistics — the attention
#: recompute (the expensive O(T^2) part of plain remat) disappears while the
#: cheap projections/layernorms/MLP still recompute for the memory win.
FLASH_REMAT_POLICY = jax.checkpoint_policies.save_only_these_names(
    "flash_out", "flash_lse")


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                block_q: int, block_k: int):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def body():
        # operands STAY in their storage dtype: a bf16×bf16→f32 dot runs the
        # MXU at full rate, an f32 upcast would halve it; all softmax
        # statistics accumulate in f32 via preferred_element_type
        q = q_ref[0]                                # (block_q, D)
        k = k_ref[0]                                # (block_k, D)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[:, 0:1]                      # (block_q, 1)
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                      # (block_q, block_k)
        l_new = l_prev * corr + p.sum(axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip K tiles strictly in the future of every query in this Q tile
        @pl.when(kb * block_k <= qi * block_q + block_q - 1)
        def _():
            body()
    else:
        body()

    @pl.when(kb == nk - 1)
    def _finish():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse block spans the FULL row (TPU tiling: last-two block dims must
        # divide (8,128) or equal the array dims); each q-tile writes its slice
        lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = \
            m_scr[:, 0] + jnp.log(safe_l[:, 0])


try:  # pallas import kept optional: CPU-only deployments fall back to jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    _HAS_PALLAS = False


def _flash_fwd(q, k, v, *, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = 1.0 / float(np.sqrt(d))
    # (B, T, H, D) -> (B*H, T, D)
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, t_q, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, t_k, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, t_k, d)
    nq = t_q // block_q
    nk = t_k // block_k

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, kb: (bh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, 1, t_q), lambda bh, qi, kb: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, t_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        # qi is NOT parallel: the lse out-block (one full row per bh) is
        # revisited by every qi step; parallel execution over qi would give
        # each core its own copy of the row and clobber other cores' slices
        compiler_params=None if interpret else tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(qh, kh, vh)
    out4 = out.reshape(b, h, t_q, d).transpose(0, 2, 1, 3)
    lse4 = lse.reshape(b, h, t_q)
    return out4, lse4.astype(jnp.float32)


def _bwd_p_ds(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, qi, kb, *,
              scale: float, causal: bool, block_q: int, block_k: int):
    """Shared backward-tile recompute: (p, ds, q, k, g) for tile (qi, kb).

    P = exp(S − lse) from the saved log-sum-exp; dS = P ∘ (dP − δ) · scale —
    identical math in the dq and dk/dv kernels so the two passes can never
    desynchronize.
    """
    # storage dtype in, f32 accumulate out (bf16 MXU full-rate — see forward)
    q = q_ref[0]                                    # (block_q, D)
    k = k_ref[0]                                    # (block_k, D)
    v = v_ref[0]
    g = g_ref[0]                                    # (block_q, D)
    lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]    # (block_q,)
    delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lse[:, None])                   # (block_q, block_k)
    dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * scale
    return p, ds, q, k, g


def _bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale: float, causal: bool,
                   block_q: int, block_k: int):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def body():
        _, ds, _, k, _ = _bwd_p_ds(
            q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, qi, kb,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kb * block_k <= qi * block_q + block_q - 1)
        def _():
            body()
    else:
        body()

    @pl.when(kb == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, block_q: int, block_k: int):
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def body():
        p, ds, q, _, g = _bwd_p_ds(
            q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, qi, kb,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        # dV += Pᵀ · dO
        dv_scr[:] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dK += dSᵀ · Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip Q tiles strictly before this K tile (their P block is all-masked)
        @pl.when(qi * block_q + block_q - 1 >= kb * block_k)
        def _():
            body()
    else:
        body()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, *, causal: bool, block_q: int,
               block_k: int, interpret: bool):
    """Tiled flash backward: dq/dk/dv pallas kernels from the saved lse."""
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = 1.0 / float(np.sqrt(d))
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, t_q, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, t_k, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, t_k, d)
    gh = g.transpose(0, 2, 1, 3).reshape(b * h, t_q, d)
    # delta_i = rowsum(dO_i ∘ O_i), computed once in plain XLA (O(T·D))
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = delta.transpose(0, 2, 1).reshape(b * h, 1, t_q)
    lse3 = lse.reshape(b * h, 1, t_q)
    nq = t_q // block_q
    nk = t_k // block_k

    row_spec = pl.BlockSpec((1, 1, t_q), lambda bh, i, j: (bh, 0, 0))
    # unlike the forward (whose lse OUT row is revisited by every qi), lse and
    # delta are read-only here and each middle-dim index owns a disjoint out
    # block, so only the innermost fold dim must stay sequential
    dims = None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "arbitrary"))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            row_spec, row_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=dims,
        interpret=interpret,
    )(qh, kh, vh, gh, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b * h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, kb, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb, qi: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb, qi: (bh, kb, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, kb, qi: (bh, qi, 0)),
            row_spec, row_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, kb, qi: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, kb, qi: (bh, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_k, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=dims,
        interpret=interpret,
    )(qh, kh, vh, gh, lse3, delta)

    to4 = lambda a, t: a.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return (to4(dq, t_q).astype(q.dtype), to4(dk, t_k).astype(k.dtype),
            to4(dv, t_k).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Blockwise attention, (B, T, H, D) → (B, T, H, D).

    ``block_q``/``block_k`` default to :func:`default_blocks` (adaptive:
    largest power-of-two ≤512 dividing the sequence; overridable via
    ``ZOO_FLASH_BLOCK_Q/K`` — honored by EVERY call site: direct, sharded,
    ring and Ulysses). Falls back to plain fused attention when pallas is
    unavailable or the sequence does not tile evenly (the caller may pad
    instead).
    """
    out, _ = _flash_attention_fwd_res(q, k, v, causal, block_q, block_k,
                                      interpret)
    return out


def default_blocks(t_q: Optional[int] = None,
                   t_k: Optional[int] = None) -> tuple:
    """Flash tile sizes. Read at trace time — a jitted program bakes the
    values it saw. Resolution order:

    1. ``ZOO_FLASH_BLOCK_Q`` / ``ZOO_FLASH_BLOCK_K`` env (sweeps,
       dev/mfu_sweep.py) — always wins;
    2. the on-disk tuning cache (``ops.tuning.flash_lookup``, keyed by
       device kind + (T_q, T_k) — populated by ``tune_flash_blocks`` /
       ``bench.py --int8-dispatch``'s MFU sweep);
    3. ADAPTIVE: the largest power-of-two tile ≤512 that divides the
       sequence length — on a v5e the attention-only fwd+bwd runs ~4×
       faster at 512×512 than at a fixed 128×128 (LONGCTX_BENCH.json:
       55.6→14.2 ms/iter at T=16384), and at the model level 512-tiles are
       worth ~22% MFU over 256-tiles (MFU_SWEEP.json: 0.538 vs 0.44 on the
       seq-2048 TransformerLM). Falls back to 128 when the length is
       unknown; a non-dividing length keeps the callers' existing
       full-attention fallback behavior."""
    import os

    def auto(t: Optional[int]) -> int:
        if t is None:
            return 128
        b = 512
        while b > 128 and t % b:
            b //= 2
        return b

    eq = os.environ.get("ZOO_FLASH_BLOCK_Q")
    ek = os.environ.get("ZOO_FLASH_BLOCK_K")
    if not (eq and ek):
        try:      # tuned schedule for this device + sequence shape, if any
            from .tuning import flash_lookup

            tuned = flash_lookup(t_q, t_k)
        except Exception:  # cache layer must never break an attention trace
            tuned = None
        if tuned is not None:
            return (int(eq) if eq else tuned[0],
                    int(ek) if ek else tuned[1])
    return (int(eq) if eq else auto(t_q), int(ek) if ek else auto(t_k))


def _tiles_ok(q, k, block_q, block_k):
    return (q.shape[1] % block_q == 0 and k.shape[1] % block_k == 0)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _resolve(q, k, block_q, block_k, interpret):
    """Resolve env-default tile sizes, clamp them to the sequence, and resolve
    interpret mode — shared by the forward and the VJP backward so both
    always use identical tiling."""
    env_q, env_k = default_blocks(q.shape[1], k.shape[1])
    block_q = min(env_q if block_q is None else block_q, q.shape[1])
    block_k = min(env_k if block_k is None else block_k, k.shape[1])
    interpret = _interpret_default() if interpret is None else interpret
    return block_q, block_k, interpret


def _flash_attention_fwd_res(q, k, v, causal, block_q, block_k, interpret):
    from .attention import full_attention

    block_q, block_k, interpret = _resolve(q, k, block_q, block_k, interpret)
    if not _HAS_PALLAS or not _tiles_ok(q, k, block_q, block_k):
        out = full_attention(q, k, v, causal=causal)
        return out, None
    out, lse = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, interpret=interpret)
    # checkpoint_name is identity outside jax.checkpoint; under a
    # save_only_these_names policy (FLASH_REMAT_POLICY) these tags make the
    # kernel's output + log-sum-exp the SAVED residuals, so a rematerialized
    # backward reuses them instead of re-running the O(T^2) flash forward —
    # only the cheap projections/elementwise around it recompute.
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, res = _flash_attention_fwd_res(q, k, v, causal, block_q, block_k,
                                        interpret)
    if res is None:  # fallback path: save inputs, recompute via full attention
        res = (q, k, v, None, None)
    return out, res


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if lse is None:
        from .attention import full_attention

        _, vjp = jax.vjp(
            lambda q_, k_, v_: full_attention(q_, k_, v_, causal=causal),
            q, k, v)
        return vjp(g)
    block_q, block_k, interpret = _resolve(q, k, block_q, block_k, interpret)
    return _flash_bwd(q, k, v, out, lse, g, causal=causal,
                      block_q=block_q, block_k=block_k, interpret=interpret)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)
