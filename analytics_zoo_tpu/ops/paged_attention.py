"""Fused paged-attention pallas kernel (ISSUE 14).

``decode_attention`` (kv_cache.py) is a plain masked dot over a
*host-gathered contiguous view*: ``paged_read`` materializes the full
``(B, pages_per_slot * page_size, H, D)`` cache per layer per step in HBM,
so decode is bandwidth-bound on data it mostly re-reads — the gathered copy
is written once and read once, doubling cache traffic for zero FLOPs.

This kernel fuses the page-table gather INTO the attention loop: the grid
walks ``(slot, head-block, page)`` and each program's K/V tile is fetched
straight from the page pool by indexing the scalar-prefetched page table in
the BlockSpec index map (``pltpu.PrefetchScalarGridSpec`` — the table is in
SMEM before the first tile DMA issues, so the gather costs nothing extra).
QK dot, online-softmax statistics and the PV accumulate all live in VMEM;
nothing page-sized ever round-trips HBM. Supports query length 1 (the
classic decode step) AND ``q_len = k > 1`` — the speculative-decode verify
step that scores k draft tokens against the same paged cache in one pass
(:mod:`analytics_zoo_tpu.ops.speculative`).

Block schedule: ``block_h`` (heads per program) is the tunable knob —
resolved via env ``ZOO_PAGED_BLOCK_H``, then the on-disk autotuner cache
(:mod:`analytics_zoo_tpu.ops.tuning` ``PAGED`` op table, exactly like
matmul/flash), then all-heads. Routing: :func:`use_kernel` — ``auto``
(kernel on TPU, reference path elsewhere: interpret-mode pallas is a
correctness tool, not a fast path), forced ``on`` (interpret on CPU — the
parity gates), or ``off`` via ``ZOO_PAGED_ATTENTION``.

Semantics match :func:`~analytics_zoo_tpu.ops.kv_cache.decode_attention_multi`:
``lengths[b]`` counts VALID cache positions *including* the q_len new tokens
(already written by ``paged_write_multi``), and query ``i`` attends to
positions ``<= lengths[b] - q_len + i`` — causal within the step, full
prefix before it. Pages holding no valid position are skipped entirely
(``pl.when`` on the scalar-prefetched length), so cost tracks each slot's
true length, not the table capacity.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

try:  # pallas optional, same pattern as flash_attention/int8_fused
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    _HAS_PALLAS = False


def has_pallas() -> bool:
    return _HAS_PALLAS


def paged_mode() -> str:
    """``ZOO_PAGED_ATTENTION``: ``auto`` (default — kernel on TPU only),
    ``on`` (force the kernel; interpret mode off-TPU — parity testing),
    ``off`` (always the gather + plain-dot reference path)."""
    mode = os.environ.get("ZOO_PAGED_ATTENTION", "auto").lower()
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"ZOO_PAGED_ATTENTION must be auto/on/off, "
                         f"got {mode!r}")
    return mode


def use_kernel() -> bool:
    """Resolve routing at trace time (a jitted decode step bakes the answer,
    like ``flash_attention.default_blocks``)."""
    if not _HAS_PALLAS:
        return False
    mode = paged_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    return jax.default_backend() == "tpu"


def default_block_h(h: int, *, q_len: int = 1,
                    pages_per_slot: Optional[int] = None,
                    page_size: Optional[int] = None,
                    d: Optional[int] = None, dtype=None) -> int:
    """Heads per kernel program. Resolution order mirrors
    ``flash_attention.default_blocks``: ``ZOO_PAGED_BLOCK_H`` env, then the
    tuning cache's ``paged`` table, then all heads in one program (the small
    working sets of decode rarely pressure VMEM, and fewer grid steps win
    when they fit)."""
    env = os.environ.get("ZOO_PAGED_BLOCK_H")
    if env:
        bh = int(env)
        return bh if h % bh == 0 else h
    if pages_per_slot and page_size and d:
        try:
            from .tuning import paged_lookup

            tuned = paged_lookup(q_len, pages_per_slot, page_size, h, d,
                                 dtype if dtype is not None
                                 else np.dtype("float32"))
        except Exception:   # cache layer must never break a decode trace
            tuned = None
        if tuned is not None and h % tuned == 0:
            return tuned
    return h


def _paged_kernel(table_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float, page_size: int,
                  q_len: int, block_h: int, d: int):
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    rows = block_h * q_len

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = lengths_ref[b]

    def body():
        # operands stay in storage dtype (bf16 MXU full-rate), statistics
        # accumulate in f32 — same discipline as the flash kernel
        q = q_ref[0].transpose(1, 0, 2)             # (block_h, q_len, D)
        k = k_ref[0].transpose(1, 0, 2)             # (block_h, page, D)
        v = v_ref[0].transpose(1, 0, 2)
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * scale
        kv_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_h, q_len, page_size), 2)
        q_idx = jax.lax.broadcasted_iota(
            jnp.int32, (block_h, q_len, page_size), 1)
        # query i sits at absolute position length - q_len + i: it sees the
        # whole prefix AND itself/earlier drafts, never later drafts
        bound = length - q_len + q_idx
        s = jnp.where(kv_pos <= bound, s, NEG_INF)
        m_prev = m_scr[:rows, 0:1].reshape(block_h, q_len, 1)
        l_prev = l_scr[:rows, 0:1].reshape(block_h, q_len, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=2, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + p.sum(axis=2, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((2,), (1,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        acc_scr[:rows, :d] = (acc_scr[:rows, :d] * corr.reshape(rows, 1)
                              + pv.reshape(rows, d))
        m_scr[:rows, :] = jnp.broadcast_to(m_new.reshape(rows, 1),
                                           (rows, m_scr.shape[1]))
        l_scr[:rows, :] = jnp.broadcast_to(l_new.reshape(rows, 1),
                                           (rows, l_scr.shape[1]))

    # skip pages holding no valid position (table entries there are scratch)
    @pl.when(j * page_size < length)
    def _():
        body()

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:rows, 0:1]
        safe_l = jnp.where(l == 0, 1.0, l)   # masked-out rows emit zeros
        o = (acc_scr[:rows, :d] / safe_l).reshape(block_h, q_len, d)
        o_ref[0] = o.transpose(1, 0, 2).astype(o_ref.dtype)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    table: jax.Array, lengths: jax.Array, *,
                    page_size: int, block_h: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused page-gather attention.

    ``q``: (B, q_len, H, D); ``k_pages``/``v_pages``: (P, page_size, H, D)
    — ONE layer's pool; ``table``: (B, pages_per_slot) int32; ``lengths``:
    (B,) int32 valid positions INCLUDING the q_len new tokens. Returns
    (B, q_len, H, D). Falls back to the reference gather + masked-dot path
    when pallas is unavailable."""
    from .kv_cache import decode_attention_multi, paged_read

    b, q_len, h, d = q.shape
    pps = table.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_h is None:
        block_h = default_block_h(h, q_len=q_len, pages_per_slot=pps,
                                  page_size=page_size, d=d, dtype=q.dtype)
    if not _HAS_PALLAS or h % block_h:
        ks = paged_read(k_pages, table)
        vs = paged_read(v_pages, table)
        return decode_attention_multi(q, ks.astype(q.dtype),
                                      vs.astype(q.dtype), lengths)
    scale = 1.0 / float(np.sqrt(d))
    rows = max(8, block_h * q_len)
    kern = functools.partial(_paged_kernel, scale=scale, page_size=page_size,
                             q_len=q_len, block_h=block_h, d=d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h // block_h, pps),
        in_specs=[
            pl.BlockSpec((1, q_len, block_h, d),
                         lambda b, hb, j, tbl, ln: (b, 0, hb, 0)),
            # THE fusion: the K/V tile for grid step (b, ·, j) is page
            # table[b, j] of the pool, resolved in the index map from the
            # scalar-prefetched table — no contiguous copy ever exists
            pl.BlockSpec((1, page_size, block_h, d),
                         lambda b, hb, j, tbl, ln: (tbl[b, j], 0, hb, 0)),
            pl.BlockSpec((1, page_size, block_h, d),
                         lambda b, hb, j, tbl, ln: (tbl[b, j], 0, hb, 0)),
        ],
        out_specs=pl.BlockSpec((1, q_len, block_h, d),
                               lambda b, hb, j, tbl, ln: (b, 0, hb, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, 128), jnp.float32),
            pltpu.VMEM((rows, 128), jnp.float32),
            pltpu.VMEM((rows, max(d, 128)), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, q_len, h, d), q.dtype),
        # the (slot, head-block) dims each own disjoint output blocks; only
        # the page fold must stay sequential (online-softmax carry)
        compiler_params=None if interpret else _tpu_params(),
        interpret=interpret,
    )(jnp.asarray(table, jnp.int32), jnp.asarray(lengths, jnp.int32),
      q, k_pages, v_pages)


def _tpu_params():
    from ..common.compat import tpu_compiler_params

    return tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def synthetic_paged_case(n_slots: int, pages_per_slot: int, page_size: int,
                         h: int, d: int, *, q_len: int = 1,
                         dtype=np.float32, lengths=None, rng=None):
    """Random ``(q, k_pages, v_pages, table, lengths)`` laid out exactly
    like the serving cache — page 0 scratch, each slot's valid prefix on
    sequentially allocated pages, unallocated entries scratch. The ONE
    fixture builder shared by the autotuner sweep
    (:func:`~analytics_zoo_tpu.ops.tuning.tune_paged_attention`), the bench
    parity gate and the kernel tests, so none can drift from the real
    :class:`~analytics_zoo_tpu.ops.kv_cache.PagePool` layout.

    ``lengths`` (optional, (n_slots,) int): valid positions per slot
    INCLUDING the q_len newest tokens; defaults to a half-full ladder
    (the steady serving regime). Rows at 0 get all-scratch tables
    (masked/inactive slots)."""
    rng = rng if rng is not None else np.random.default_rng(0)
    n_pages = n_slots * pages_per_slot + 1
    q = jnp.asarray(rng.normal(size=(n_slots, q_len, h, d)), dtype)
    k_pages = jnp.asarray(rng.normal(size=(n_pages, page_size, h, d)), dtype)
    v_pages = jnp.asarray(rng.normal(size=(n_pages, page_size, h, d)), dtype)
    max_len = pages_per_slot * page_size
    if lengths is None:
        lengths = np.maximum(q_len, (np.arange(n_slots) + 1)
                             * max_len // (2 * n_slots)).astype(np.int32)
    lengths = np.asarray(lengths, np.int32)
    table = np.zeros((n_slots, pages_per_slot), np.int32)
    nxt = 1
    for i in range(n_slots):
        for j in range(-(-int(lengths[i]) // page_size)):
            table[i, j] = nxt
            nxt += 1
    return q, k_pages, v_pages, jnp.asarray(table), jnp.asarray(lengths)


__all__ = ["default_block_h", "has_pallas", "paged_attention", "paged_mode",
           "synthetic_paged_case", "use_kernel"]
