"""Int8 compute kernels — the OpenVINO-Int8 capability, TPU-native.

The reference's int8 path runs calibrated int8 inference inside OpenVINO
(`OpenVinoInferenceSupportive.scala:32-55`; "up to 2× speedup, 4× model-size
reduction, <0.1% accuracy drop" — docs/docs/wp-bigdl.md:192). On TPU the MXU
multiplies int8 operands natively at twice the bf16 rate: `lax.dot_general`
with int8 inputs and ``preferred_element_type=int32`` compiles to the int8
systolic-array path, no custom kernel needed.

Scheme (AQT-style dynamic quantization):
* weights: symmetric per-output-channel int8, packed once at
  ``InferenceModel.quantize_int8`` time ({"q": int8, "scale": f32[out]});
* activations: symmetric per-row (matmul) / per-pixel (conv) int8, quantized
  dynamically inside the compiled program;
* accumulate in int32, rescale with ``row_scale × channel_scale`` in f32.

Two execution tiers share this scheme:

* **fused** (:mod:`ops.int8_fused`) — pallas kernels that quantize the
  activation tile in VMEM and rescale on the f32 accumulator before
  writeback, so no int8/f32 intermediate ever round-trips HBM. This is the
  TPU dispatch path (the unfused HBM round-trips inverted the raw 1.53×
  matmul win into 0.72× end-to-end through serving).
* **unfused** (this module) — plain lax ops; XLA materializes the quantized
  activations, but every backend runs it. This is the interpreter/CPU
  fallback and the numerics oracle the fused kernels are tested against.

:func:`int8_matmul` / :func:`int8_conv2d` route between the tiers via
``int8_fused.fused_mode()`` (``ZOO_INT8_FUSED`` env; default: fused on TPU,
lax elsewhere) and fall back per-shape when a shape cannot tile.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from . import int8_fused


def quantize_weight(w: np.ndarray, axis: int = -1) -> Dict[str, Any]:
    """Symmetric per-channel int8 packing along ``axis`` (the output-channel
    axis: last for (in, out) matmul kernels and HWIO conv kernels)."""
    w = np.asarray(w, np.float32)
    axis = axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.maximum(amax, 1e-12) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {"q": q, "scale": scale.astype(np.float32)}


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "scale" in leaf


def dequantize(packed) -> jnp.ndarray:
    return packed["q"].astype(jnp.float32) * packed["scale"]


def _quant_activations(x: jnp.ndarray, axes=(-1,)):
    """Dynamic symmetric quantization: one abs-max scale per slice along
    ``axes`` (default: per-row over the contraction dim)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    xscale = jnp.maximum(amax, 1e-12) / 127.0
    xq = jnp.clip(jnp.round(xf / xscale), -127, 127).astype(jnp.int8)
    return xq, xscale


def int8_matmul_unfused(x: jnp.ndarray, packed: Dict[str, Any]) -> jnp.ndarray:
    """``x @ W`` with the MXU int8 path, quantize/rescale as separate lax
    ops (XLA materializes the int8 activations — see module docstring)."""
    xq, xscale = _quant_activations(x)
    acc = jax.lax.dot_general(
        xq, packed["q"],
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    # scale: (..., 1) row scales × (1, out)→(out,) channel scales
    ch = packed["scale"].reshape(-1)
    return acc.astype(jnp.float32) * xscale * ch


def int8_matmul(x: jnp.ndarray, packed: Dict[str, Any],
                out_dtype=None) -> jnp.ndarray:
    """``x @ W`` over a ``quantize_weight``-packed (in, out) kernel; returns
    ``x.shape[:-1] + (out,)`` in ``out_dtype`` (default f32).

    Routes to the fused pallas kernel (:func:`int8_fused.int8_matmul_fused`)
    when the mode/shape allow, else the unfused lax path."""
    mode = int8_fused.fused_mode()
    if mode != "off":
        y = int8_fused.int8_matmul_fused(
            x, packed, out_dtype=out_dtype, interpret=(mode == "interpret"))
        if y is not None:
            return y
    y = int8_matmul_unfused(x, packed)
    return y.astype(out_dtype) if out_dtype is not None else y


def int8_conv2d_unfused(x: jnp.ndarray, packed: Dict[str, Any], *, strides,
                        padding, dilation=(1, 1)) -> jnp.ndarray:
    """NHWC × HWIO conv on the int8 MXU path, **per-pixel** activation
    scales (one abs-max over channels per (n, h, w) pixel).

    A single ``lax.conv`` cannot rescale per-pixel post-hoc (each output
    pixel mixes window pixels with different scales), so the conv is
    decomposed into its KH·KW taps: per tap, a shifted/strided slice of the
    quantized input contracts with the tap's (Cin, Cout) int8 weight slice
    on the MXU, and the int32 partial is rescaled by that slice's own pixel
    scales before the f32 accumulate — identical math to the fused kernel
    (which folds the taps into its grid), and strictly finer granularity
    than the old per-image scheme that lost accuracy on high-dynamic-range
    inputs. XLA fuses the tap loop into one program under jit.
    """
    kh, kw, _cin, _cout = packed["q"].shape
    sh, sw = tuple(strides)
    dh, dw = tuple(dilation)
    xq, xscale = _quant_activations(x, axes=(3,))        # per-pixel scales
    if isinstance(padding, str):
        eff = ((kh - 1) * dh + 1, (kw - 1) * dw + 1)
        pads = jax.lax.padtype_to_pads(x.shape[1:3], eff, (sh, sw),
                                       padding.upper())
    else:
        pads = tuple(tuple(p) for p in padding)
    full = ((0, 0),) + tuple(pads) + ((0, 0),)
    # padded zeros contribute nothing regardless of scale; pad scales with 1
    # so the rescale multiply never sees a 0-scale
    xq = jnp.pad(xq, full)
    xscale = jnp.pad(xscale, full, constant_values=1.0)
    h, w = xq.shape[1:3]
    ho = (h - ((kh - 1) * dh + 1)) // sh + 1
    wo = (w - ((kw - 1) * dw + 1)) // sw + 1
    ch = packed["scale"].reshape(-1)
    acc = jnp.zeros(x.shape[:1] + (ho, wo) + ch.shape, jnp.float32)
    for i in range(kh):
        for j in range(kw):
            lo = (0, i * dh, j * dw, 0)
            hi = (x.shape[0], i * dh + (ho - 1) * sh + 1,
                  j * dw + (wo - 1) * sw + 1, xq.shape[3])
            x_tap = jax.lax.slice(xq, lo, hi, (1, sh, sw, 1))
            s_tap = jax.lax.slice(xscale, lo, hi[:3] + (1,), (1, sh, sw, 1))
            part = jax.lax.dot_general(
                x_tap, packed["q"][i, j],
                dimension_numbers=(((3,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            acc = acc + part.astype(jnp.float32) * s_tap
    return acc * ch


def int8_conv2d(x: jnp.ndarray, packed: Dict[str, Any], *, strides, padding,
                dilation=(1, 1), out_dtype=None) -> jnp.ndarray:
    """NHWC × HWIO conv on the int8 MXU path; per-output-channel weight
    scales × per-pixel activation scales.

    Routes to the fused pallas kernel (:func:`int8_fused.int8_conv2d_fused`,
    stride/dilation (1,1)) when the mode/shape allow, else the unfused
    tap-decomposed lax path — both compute the same per-pixel scheme."""
    mode = int8_fused.fused_mode()
    if mode != "off":
        y = int8_fused.int8_conv2d_fused(
            x, packed, strides=strides, padding=padding, dilation=dilation,
            out_dtype=out_dtype, interpret=(mode == "interpret"))
        if y is not None:
            return y
    y = int8_conv2d_unfused(x, packed, strides=strides, padding=padding,
                            dilation=dilation)
    return y.astype(out_dtype) if out_dtype is not None else y
