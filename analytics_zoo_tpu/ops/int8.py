"""Int8 compute kernels — the OpenVINO-Int8 capability, TPU-native.

The reference's int8 path runs calibrated int8 inference inside OpenVINO
(`OpenVinoInferenceSupportive.scala:32-55`; "up to 2× speedup, 4× model-size
reduction, <0.1% accuracy drop" — docs/docs/wp-bigdl.md:192). On TPU the MXU
multiplies int8 operands natively at twice the bf16 rate: `lax.dot_general`
with int8 inputs and ``preferred_element_type=int32`` compiles to the int8
systolic-array path, no custom kernel needed.

Scheme (AQT-style dynamic quantization):
* weights: symmetric per-output-channel int8, packed once at
  ``InferenceModel.quantize_int8`` time ({"q": int8, "scale": f32[out]});
* activations: symmetric per-row int8, quantized dynamically inside the
  compiled program (one abs-max per row — fused by XLA);
* accumulate in int32, rescale with ``row_scale × channel_scale`` in f32.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


def quantize_weight(w: np.ndarray, axis: int = -1) -> Dict[str, Any]:
    """Symmetric per-channel int8 packing along ``axis`` (the output-channel
    axis: last for (in, out) matmul kernels and HWIO conv kernels)."""
    w = np.asarray(w, np.float32)
    axis = axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.maximum(amax, 1e-12) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {"q": q, "scale": scale.astype(np.float32)}


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "scale" in leaf


def dequantize(packed) -> jnp.ndarray:
    return packed["q"].astype(jnp.float32) * packed["scale"]


def _quant_activations(x: jnp.ndarray):
    """Dynamic symmetric per-row quantization of the activations."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    xscale = jnp.maximum(amax, 1e-12) / 127.0
    xq = jnp.clip(jnp.round(xf / xscale), -127, 127).astype(jnp.int8)
    return xq, xscale


def int8_matmul(x: jnp.ndarray, packed: Dict[str, Any]) -> jnp.ndarray:
    """``x @ W`` with the MXU int8 path. ``packed`` is ``quantize_weight`` of a
    (in, out) kernel; returns f32 of shape ``x.shape[:-1] + (out,)``."""
    xq, xscale = _quant_activations(x)
    acc = jax.lax.dot_general(
        xq, packed["q"],
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    # scale: (..., 1) row scales × (1, out)→(out,) channel scales
    ch = packed["scale"].reshape(-1)
    return acc.astype(jnp.float32) * xscale * ch


def int8_conv2d(x: jnp.ndarray, packed: Dict[str, Any], *, strides, padding,
                dilation=(1, 1)) -> jnp.ndarray:
    """NHWC × HWIO conv on the int8 MXU path; per-output-channel rescale.

    Activation quantization is per-image (one abs-max over H,W,C) — per-row
    would change the scale across the window footprint.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(1, 2, 3), keepdims=True)
    xscale = jnp.maximum(amax, 1e-12) / 127.0
    xq = jnp.clip(jnp.round(xf / xscale), -127, 127).astype(jnp.int8)
    acc = jax.lax.conv_general_dilated(
        xq, packed["q"], window_strides=tuple(strides), padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.int32)
    ch = packed["scale"].reshape(-1)
    return acc.astype(jnp.float32) * xscale * ch
