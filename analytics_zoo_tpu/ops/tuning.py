"""Block-schedule autotuner for the pallas kernel tier.

The fused int8 kernels and the flash-attention kernels are all parameterized
by a tile schedule — (block_m, block_n, block_k) for the matmul, (block_q,
block_k) for attention.  The right schedule depends on shape AND device: the
fixed constants that earn MFU 0.53 at batch 4 leave the MXU idle at batch 16
(VMEM pressure), and the int8 tiles that win on a v5e are not the v6e ones.

This module sweeps a small candidate grid per (shape-bucket, dtype), scores
each candidate with a **timed probe** plus the **compiled memory analysis**
(structured ``compiled.memory_analysis()`` when the backend provides it,
else the text parser — both in ``analysis.memory`` since ISSUE 12), and
persists the winner in an on-disk JSON cache keyed by device kind, so every
later
process — ``InferenceModel.quantize_int8`` dispatch, ``flash_attention``
call sites, the MFU bench — traces with tuned blocks instead of constants.

Cache location: ``ZOO_TPU_TUNING_CACHE`` env, else
``~/.cache/analytics_zoo_tpu/tuning.json``.  Schema (see
docs/programming-guide/kernels.md)::

    {"version": 1,
     "devices": {"<device_kind>": {
        "int8_matmul": {"<Mbucket>x<N>x<K>/<dtype>":
            {"block_m": 256, "block_n": 256, "block_k": 512,
             "elapsed_ms": 0.41, "hbm": {...}, "swept": [...]}},
        "flash": {"<Tq>x<Tk>/<dtype>":
            {"block_q": 512, "block_k": 512, ...}}}}}

Lookups are in-memory after the first read; ``invalidate()`` drops the
memo (tests, or after an external process re-tuned).  Telemetry:
``zoo_kernel_tuning_sweeps_total`` and the cache hit/miss counters.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.memory import memory_fields  # noqa: F401  (re-export: the
# structured/text ingestion migrated to the analysis subsystem in ISSUE 12 —
# library code must not import from the bench script; existing callers and
# the tuning-cache schema keep using tuning.memory_fields)
from ..common import telemetry as _tm

_SWEEPS = _tm.counter("zoo_kernel_tuning_sweeps_total",
                      "Autotuner candidate sweeps executed (one per "
                      "(op, shape-bucket, dtype) tuned this process)",
                      labels=("op",))
_HITS = _tm.counter("zoo_kernel_tuning_cache_hits_total",
                    "Kernel-schedule lookups answered from the tuning cache",
                    labels=("op",))
_MISSES = _tm.counter("zoo_kernel_tuning_cache_misses_total",
                      "Kernel-schedule lookups that fell back to the fixed "
                      "default blocks (shape/device never tuned)",
                      labels=("op",))

_CACHE_VERSION = 1
_memo: Dict[str, Optional[dict]] = {}     # path -> parsed cache (None = bad)


def cache_path() -> str:
    return os.environ.get(
        "ZOO_TPU_TUNING_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "analytics_zoo_tpu",
                     "tuning.json"))


def device_kind() -> str:
    """Cache key: device kind of the default backend (e.g. ``TPU v5e``),
    ``cpu-interpret`` for interpreter-mode runs — schedules never leak
    across device generations."""
    import jax

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        return f"{dev.platform}-interpret"
    return str(getattr(dev, "device_kind", dev.platform))


def invalidate() -> None:
    """Drop the in-memory cache memo (tests; external re-tune)."""
    _memo.clear()


def _load(path: str) -> dict:
    cached = _memo.get(path)
    if cached is not None:
        return cached
    data: dict = {"version": _CACHE_VERSION, "devices": {}}
    try:
        with open(path) as f:
            raw = json.load(f)
        if isinstance(raw, dict) and raw.get("version") == _CACHE_VERSION:
            data = raw
    except (OSError, ValueError):
        pass
    _memo[path] = data
    return data


def _store(path: str, data: dict) -> None:
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)        # atomic: a killed sweep can't corrupt
    except OSError:
        pass                         # cache is an optimization, never a fault
    _memo[path] = data


def bucket(n: int) -> int:
    """Power-of-two shape bucket (same ladder the serving batcher pads to,
    so one tuned entry covers every batch the bucket admits)."""
    return 1 << (int(n) - 1).bit_length() if n > 1 else 1


def shape_key(*dims: int, dtype=None) -> str:
    key = "x".join(str(int(d)) for d in dims)
    return f"{key}/{np.dtype(dtype).name}" if dtype is not None else key


def lookup(op: str, key: str) -> Optional[dict]:
    """Tuned entry for (device kind, op, key), or None. Counts hit/miss."""
    entry = (_load(cache_path()).get("devices", {})
             .get(device_kind(), {}).get(op, {}).get(key))
    (_HITS if entry else _MISSES).labels(op=op).inc()
    return entry


def record(op: str, key: str, entry: dict) -> None:
    path = cache_path()
    # read-modify-write against the CURRENT file, not the process-lifetime
    # memo: another process may have persisted winners since our first read,
    # and rewriting from a stale snapshot would silently drop them
    _memo.pop(path, None)
    data = _load(path)
    data.setdefault("devices", {}).setdefault(
        device_kind(), {}).setdefault(op, {})[key] = entry
    _store(path, data)




def _time_probe(fn, *args, iters: int = 3, inner: int = 5) -> float:
    """Median wall time of ``inner`` chained dispatches (ms per call)."""
    import jax

    out = fn(*args)                          # compile + warm
    jax.block_until_ready(out)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(inner):
            out = fn(*args)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / inner * 1e3)
    return float(np.median(samples))


# ------------------------------------------------------------ int8 matmul op

MATMUL_OP = "int8_matmul"

#: Candidate tiles the sweep explores (clamped/shrunk per shape by
#: ``int8_fused.resolve_blocks``). Kept small: each candidate costs a compile.
MATMUL_CANDIDATES: Sequence[Tuple[int, int, int]] = (
    (128, 128, 512), (128, 256, 512), (256, 128, 512),
    (256, 256, 256), (256, 256, 512), (256, 512, 512),
    (512, 256, 512), (512, 512, 256),
)


def matmul_key(m: int, n: int, k: int, dtype) -> str:
    return shape_key(bucket(m), n, k, dtype=dtype)


def matmul_lookup(m: int, n: int, k: int,
                  dtype) -> Optional[Tuple[int, int, int]]:
    """Tuned (block_m, block_n, block_k) for an (M,K)×(K,N) fused int8
    matmul at this shape bucket, or None (callers keep the defaults)."""
    entry = lookup(MATMUL_OP, matmul_key(m, n, k, dtype))
    if not entry:
        return None
    try:
        return int(entry["block_m"]), int(entry["block_n"]), int(entry["block_k"])
    except (KeyError, TypeError, ValueError):
        return None


def tune_int8_matmul(m: int, n: int, k: int, dtype=np.float32, *,
                     candidates: Optional[Sequence[Tuple[int, int, int]]]
                     = None, interpret: Optional[bool] = None,
                     iters: int = 3) -> Optional[dict]:
    """Sweep the candidate tile grid for one (shape-bucket, dtype), score by
    timed probe + compiled memory analysis, persist and return the winner."""
    import jax
    import jax.numpy as jnp

    from . import int8_fused
    from .int8 import quantize_weight

    if not int8_fused.has_pallas():
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    mb = bucket(m)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(mb, k)), dtype)
    packed = quantize_weight(rng.normal(size=(k, n)).astype(np.float32))
    packed = {"q": jnp.asarray(packed["q"]),
              "scale": jnp.asarray(packed["scale"])}
    _SWEEPS.labels(op=MATMUL_OP).inc()
    swept: List[dict] = []
    seen = set()
    for cand in (candidates or MATMUL_CANDIDATES):
        blocks = int8_fused.resolve_blocks(mb, n, k, dtype, *cand,
                                           interpret=interpret)
        if blocks is None or blocks in seen:
            continue
        seen.add(blocks)
        bm, bn, bk = blocks

        def run(xx, pq=packed["q"], ps=packed["scale"], bm=bm, bn=bn, bk=bk):
            return int8_fused.int8_matmul_fused(
                xx, {"q": pq, "scale": ps}, block_m=bm, block_n=bn,
                block_k=bk, interpret=interpret)

        entry = {"block_m": bm, "block_n": bn, "block_k": bk}
        try:
            jitted = jax.jit(run)
            try:
                entry["hbm"] = memory_fields(jitted.lower(x).compile())
            except Exception:
                entry["hbm"] = {}
            entry["elapsed_ms"] = round(
                _time_probe(jitted, x, iters=iters), 4)
        except Exception as e:   # candidate doesn't compile/fit: skip it
            entry["error"] = str(e)[:200]
            swept.append(entry)
            continue
        swept.append(entry)
    timed = [e for e in swept if "elapsed_ms" in e]
    if not timed:
        return None
    best = dict(min(timed, key=lambda e: e["elapsed_ms"]))
    best["swept"] = swept
    record(MATMUL_OP, matmul_key(m, n, k, dtype), best)
    return best


# ------------------------------------------------------------------- flash op

FLASH_OP = "flash"

FLASH_CANDIDATES: Sequence[Tuple[int, int]] = (
    (128, 128), (256, 128), (256, 256), (512, 256), (512, 512),
)


def flash_key(t_q: int, t_k: int, dtype) -> str:
    return shape_key(t_q, t_k, dtype=dtype)


def flash_lookup(t_q: Optional[int], t_k: Optional[int],
                 dtype=np.dtype("bfloat16")) -> Optional[Tuple[int, int]]:
    """Tuned (block_q, block_k) for a (T_q, T_k) flash attention call, or
    None. Consulted by ``flash_attention.default_blocks`` after the env
    knobs and before the adaptive pow2 heuristic."""
    if not t_q or not t_k:
        return None
    entry = lookup(FLASH_OP, flash_key(t_q, t_k, dtype))
    if not entry:
        return None
    try:
        return int(entry["block_q"]), int(entry["block_k"])
    except (KeyError, TypeError, ValueError):
        return None


def tune_flash_blocks(t_q: int, t_k: int, *, batch: int = 1, heads: int = 8,
                      d: int = 128, dtype=np.dtype("bfloat16"),
                      causal: bool = True, with_backward: bool = True,
                      candidates: Optional[Sequence[Tuple[int, int]]] = None,
                      interpret: Optional[bool] = None,
                      iters: int = 3) -> Optional[dict]:
    """Sweep flash (block_q, block_k) tiles at one sequence shape (fwd+bwd —
    the training MFU regime), persist and return the winner."""
    import jax
    import jax.numpy as jnp

    from .flash_attention import _HAS_PALLAS, flash_attention

    if not _HAS_PALLAS:
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(0)

    def make(shape):
        return jnp.asarray(rng.normal(size=shape), dtype)

    q = make((batch, t_q, heads, d))
    k = make((batch, t_k, heads, d))
    v = make((batch, t_k, heads, d))
    _SWEEPS.labels(op=FLASH_OP).inc()
    swept: List[dict] = []
    for bq, bk in (candidates or FLASH_CANDIDATES):
        if t_q % bq or t_k % bk:
            continue
        if with_backward:
            def run(q, k, v, bq=bq, bk=bk):
                return jax.grad(lambda q_, k_, v_: flash_attention(
                    q_, k_, v_, causal, bq, bk, interpret)
                    .astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)
        else:
            def run(q, k, v, bq=bq, bk=bk):
                return flash_attention(q, k, v, causal, bq, bk, interpret)
        entry = {"block_q": bq, "block_k": bk}
        try:
            jitted = jax.jit(run)
            try:
                entry["hbm"] = memory_fields(jitted.lower(q, k, v).compile())
            except Exception:
                entry["hbm"] = {}
            entry["elapsed_ms"] = round(
                _time_probe(jitted, q, k, v, iters=iters), 4)
        except Exception as e:
            entry["error"] = str(e)[:200]
            swept.append(entry)
            continue
        swept.append(entry)
    timed = [e for e in swept if "elapsed_ms" in e]
    if not timed:
        return None
    best = dict(min(timed, key=lambda e: e["elapsed_ms"]))
    best["swept"] = swept
    best["with_backward"] = with_backward
    record(FLASH_OP, flash_key(t_q, t_k, dtype), best)
    return best


# ------------------------------------------------------------------ paged op

PAGED_OP = "paged"

#: Heads-per-program candidates for the fused paged-attention kernel
#: (filtered to divisors of the model's head count per sweep).
PAGED_CANDIDATES: Sequence[int] = (1, 2, 4, 8, 16)


def paged_key(q_len: int, pages_per_slot: int, page_size: int, h: int,
              d: int, dtype) -> str:
    return shape_key(q_len, pages_per_slot, page_size, h, d, dtype=dtype)


def paged_lookup(q_len: int, pages_per_slot: int, page_size: int, h: int,
                 d: int, dtype) -> Optional[int]:
    """Tuned ``block_h`` for a paged-attention call at this cache geometry,
    or None (callers keep the all-heads default). Consulted by
    ``paged_attention.default_block_h`` after the env knob."""
    entry = lookup(PAGED_OP, paged_key(q_len, pages_per_slot, page_size,
                                       h, d, dtype))
    if not entry:
        return None
    try:
        return int(entry["block_h"])
    except (KeyError, TypeError, ValueError):
        return None


def tune_paged_attention(q_len: int, pages_per_slot: int, page_size: int,
                         h: int, d: int, dtype=np.float32, *,
                         n_slots: int = 8,
                         candidates: Optional[Sequence[int]] = None,
                         interpret: Optional[bool] = None,
                         iters: int = 3) -> Optional[dict]:
    """Sweep ``block_h`` for the fused paged-attention kernel at one cache
    geometry (the decode/verify serving regime: B = n_slots, half-full
    slots), persist and return the winner — the decode twin of
    :func:`tune_flash_blocks`."""
    import jax

    from . import paged_attention as pa

    if not pa.has_pallas():
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q, k_pages, v_pages, table, lengths = pa.synthetic_paged_case(
        n_slots, pages_per_slot, page_size, h, d, q_len=q_len, dtype=dtype)
    _SWEEPS.labels(op=PAGED_OP).inc()
    swept: List[dict] = []
    for bh in (candidates or PAGED_CANDIDATES):
        if bh > h or h % bh:
            continue

        def run(qq, kk, vv, bh=bh):
            return pa.paged_attention(qq, kk, vv, table, lengths,
                                      page_size=page_size, block_h=bh,
                                      interpret=interpret)

        entry = {"block_h": bh}
        try:
            jitted = jax.jit(run)
            try:
                entry["hbm"] = memory_fields(
                    jitted.lower(q, k_pages, v_pages).compile())
            except Exception:
                entry["hbm"] = {}
            entry["elapsed_ms"] = round(
                _time_probe(jitted, q, k_pages, v_pages, iters=iters), 4)
        except Exception as e:   # candidate doesn't compile/fit: skip it
            entry["error"] = str(e)[:200]
            swept.append(entry)
            continue
        swept.append(entry)
    timed = [e for e in swept if "elapsed_ms" in e]
    if not timed:
        return None
    best = dict(min(timed, key=lambda e: e["elapsed_ms"]))
    best["swept"] = swept
    record(PAGED_OP, paged_key(q_len, pages_per_slot, page_size, h, d,
                               dtype), best)
    return best
