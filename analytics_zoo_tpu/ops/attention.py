"""Attention ops with selectable parallelism strategy.

The reference's attention is fixed-length single-node (TransformerLayer.scala,
BERT.scala — SURVEY.md §5.7: no ring attention, no sequence parallelism). Here
long-context is first-class: interchangeable strategies over the global mesh:

* ``full``    — plain batched attention; GSPMD shards it over dp/tp axes.
* ``ring``    — ring attention over the ``sp`` axis: K/V blocks rotate around the
                ring via ``lax.ppermute``. On TPU each ring step runs the pallas
                flash kernel (O(block) score memory); off TPU a plain-jnp
                online-softmax body runs. K/V transfers ride ICI neighbor links.
* ``zigzag``  — causal ring over the zigzag layout (device d holds the chunk
                pair (d, 2n−1−d)): the causal schedule is load-balanced — every
                device does ~2 half-blocks per step instead of the plain ring's
                tail-heavy triangle. Causal + TPU only; else falls to ``ring``.
* ``ulysses`` — DeepSpeed-Ulysses-style all-to-all: resharding from sequence-split
                to head-split, local (flash on TPU) attention over the full
                sequence, then the inverse all-to-all.

All strategies compute bitwise-comparable results (up to float reassociation) and
are differentiable (pure jnp/lax — JAX autodiff through collectives).

Shapes: q, k, v are (B, T, H, D) per-device LOCAL blocks inside shard_map, or
global arrays for ``full``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..common.compat import axis_size, shard_map

NEG_INF = -1e30


def full_attention(q, k, v, *, causal: bool = False, q_offset=0, k_offset=0):
    """Reference attention: softmax(q k^T / sqrt(d)) v. (B, T, H, D) layout."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _ring_body(q, k_blk, v_blk, o, m, l, *, scale, causal, q_pos, k_pos):
    """One ring step: fold k_blk/v_blk into the online-softmax accumulator."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    blk_max = jnp.max(scores, axis=-1)                       # (B,H,Tq)
    m_new = jnp.maximum(m, blk_max)
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])                   # (B,H,Tq,Tk)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv.astype(jnp.float32)
    return o_new, m_new, l_new


def _ring_attention_jnp(q, k, v, *, axis_name: str = "sp", causal: bool = False):
    """Plain-jnp ring body (O(T_local²) score blocks) — fallback when the
    pallas kernel is unavailable or the local sequence does not tile."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    q32 = q
    o = jnp.zeros((b, t_q, h, d), jnp.float32)
    m = jnp.full((b, h, t_q), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, t_q), jnp.float32)
    q_pos = idx * t_q + jnp.arange(t_q)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        src = (idx - i) % n                     # which global block we now hold
        k_pos = src * k_blk.shape[1] + jnp.arange(k_blk.shape[1])
        o, m, l = _ring_body(q32, k_blk, v_blk, o, m, l, scale=scale,
                             causal=causal, q_pos=q_pos, k_pos=k_pos)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = jax.lax.scan(step, (o, m, l, k, v), jnp.arange(n))
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


# --------------------------------------------------------------- flash-in-ring
def _block_cases(src, idx, causal, diag_fn, past_fn, future_fn, operand):
    """Dispatch one ring step on the visiting block's causal relation to the
    local Q block. ``src`` is traced (depends on axis_index), so the three
    cases are runtime ``lax.cond`` branches: src == idx → diagonal (causal
    mask), src < idx → strictly past (dense), src > idx → strictly future
    (fully masked, skipped)."""
    if not causal:
        return past_fn(operand)
    return jax.lax.cond(
        src == idx, diag_fn,
        lambda op: jax.lax.cond(src < idx, past_fn, future_fn, op),
        operand)


def _merge_blocks(o, lse, o_blk, lse_blk):
    """Fold one normalized block result into the running (o, lse) accumulator:
    U = o·e^lse is the unnormalized numerator, so the merged output is a
    stable convex combination weighted by e^(lse−lse_new). NEG_INF is finite,
    so empty blocks merge to weight 0 without NaNs."""
    m = jnp.maximum(lse, lse_blk)
    w_old = jnp.exp(lse - m)                        # (B, H, Tq)
    w_new = jnp.exp(lse_blk - m)
    lse_new = m + jnp.log(w_old + w_new)
    tr = lambda w: w.transpose(0, 2, 1)[..., None]  # -> (B, Tq, H, 1)
    denom = tr(w_old + w_new)
    o_new = (o * tr(w_old) + o_blk.astype(jnp.float32) * tr(w_new)) / denom
    return o_new, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, causal, block_q, block_k):
    """Ring attention whose per-step body is the pallas flash kernel —
    O(block_q·block_k) score memory inside each ring step instead of the jnp
    body's O(T_local²) (VERDICT r3 #3: "flash-within-ring is the composition
    that makes long-context real")."""
    out, _ = _ring_flash_fwd_res(q, k, v, axis_name, causal, block_q, block_k)
    return out


def _ring_flash_fwd_res(q, k, v, axis_name, causal, block_q, block_k):
    from .flash_attention import _flash_fwd, _interpret_default

    interpret = _interpret_default()
    n = axis_size(axis_name)
    # non-causal rings never branch on block position — every visiting block
    # is dense. Emitting axis_index anyway leaves an (unused) PartitionId in
    # the shard_map body, which XLA's SPMD partitioner rejects outright
    # ("meaning is ambiguous"); only materialize it when causal needs it.
    idx = jax.lax.axis_index(axis_name) if causal else None
    b, t_q, h, d = q.shape
    o0 = jnp.zeros((b, t_q, h, d), jnp.float32)
    lse0 = jnp.full((b, h, t_q), NEG_INF, jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def flash(causal_flag):
        def run(op):
            q_, k_, v_ = op
            return _flash_fwd(q_, k_, v_, causal=causal_flag, block_q=block_q,
                              block_k=block_k, interpret=interpret)
        return run

    def future(op):
        return (jnp.zeros((b, t_q, h, d), q.dtype),
                jnp.full((b, h, t_q), NEG_INF, jnp.float32))

    def step(carry, i):
        o, lse, k_blk, v_blk = carry
        src = (idx - i) % n if causal else None
        o_blk, lse_blk = _block_cases(src, idx, causal, flash(True),
                                      flash(False), future, (q, k_blk, v_blk))
        o, lse = _merge_blocks(o, lse, o_blk, lse_blk)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, lse, k_blk, v_blk), None

    (o, lse, _, _), _ = jax.lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, block_q, block_k):
    return _ring_flash_fwd_res(q, k, v, axis_name, causal, block_q, block_k)


def _ring_flash_vjp_bwd(axis_name, causal, block_q, block_k, res, g):
    """Second ring pass: (k, v, dk, dv) rotate together; each device folds the
    visiting block's gradients through the tiled flash backward kernels using
    the saved GLOBAL lse (P = exp(S − lse) is exact for every block), so the
    backward is O(block) memory too. After n rotations every bundle is back on
    its home device with dk/dv fully accumulated; dq accumulates locally."""
    from .flash_attention import _flash_bwd, _interpret_default

    q, k, v, out, lse = res
    interpret = _interpret_default()
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name) if causal else None  # see fwd note
    perm = [(j, (j + 1) % n) for j in range(n)]

    def bwd(causal_flag):
        def run(op):
            k_blk, v_blk = op
            return _flash_bwd(q, k_blk, v_blk, out, lse, g, causal=causal_flag,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret)
        return run

    def future(op):
        k_blk, v_blk = op
        return (jnp.zeros_like(q), jnp.zeros_like(k_blk),
                jnp.zeros_like(v_blk))

    def step(carry, i):
        dq, k_blk, v_blk, dk, dv = carry
        src = (idx - i) % n if causal else None
        dq_c, dk_c, dv_c = _block_cases(src, idx, causal, bwd(True),
                                        bwd(False), future, (k_blk, v_blk))
        dq = dq + dq_c.astype(jnp.float32)
        dk = dk + dk_c.astype(jnp.float32)
        dv = dv + dv_c.astype(jnp.float32)
        roll = lambda x: jax.lax.ppermute(x, axis_name, perm)
        return (dq, roll(k_blk), roll(v_blk), roll(dk), roll(dv)), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


# ----------------------------------------------------------- zigzag ring
def zigzag_permutation(t: int, n: int):
    """Sequence-axis permutation for load-balanced CAUSAL ring attention.

    Contiguous chunking starves the early devices: device d has only d+1
    non-future blocks of n, so the wall-clock is set by the last device while
    the first sits idle (~2× waste at large n). Zigzag gives device d the
    chunk PAIR (d, 2n−1−d) of 2n half-chunks — causal work per device becomes
    (d+1) + (2n−1−d − (n−1)) … = 2n+1 half-pairs, EQUAL for every d. Returns
    the permutation such that ``x[:, perm]`` sharded over ``n`` devices puts
    that pair on device d; invert with ``np.argsort(perm)``.
    """
    import numpy as np

    if t % (2 * n):
        raise ValueError(f"zigzag needs seq len divisible by 2*sp ({2 * n}); "
                         f"got {t}")
    c = t // (2 * n)
    order = []
    for d in range(n):
        order += [d, 2 * n - 1 - d]
    return np.concatenate([np.arange(c) + ch * c for ch in order])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _zigzag_ring_flash(q, k, v, axis_name, block_q, block_k):
    """Causal ring attention over the zigzag layout; called INSIDE shard_map.

    The local sequence is [lo | hi] = chunks (idx, 2n−1−idx). Of the four
    (q-half × visiting-k-half) pairs, two are STATIC: q_lo×k_hi is always
    future (skipped at trace time) and q_hi×k_lo always strictly past (dense
    flash, no cond); only the two same-half pairs need runtime 3-way
    dispatch. Per-step work is therefore ~2 half-blocks on every device —
    the balanced schedule the plain causal ring lacks."""
    out, _ = _zigzag_fwd_res(q, k, v, axis_name, block_q, block_k)
    return out


def _zigzag_split(x, axis=1):
    c = x.shape[axis] // 2
    lo = jax.lax.slice_in_dim(x, 0, c, axis=axis)
    hi = jax.lax.slice_in_dim(x, c, 2 * c, axis=axis)
    return lo, hi


def _zigzag_fwd_res(q, k, v, axis_name, block_q, block_k):
    from .flash_attention import _flash_fwd, _interpret_default

    interpret = _interpret_default()
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    c = t_loc // 2
    bq, bk = min(block_q, c), min(block_k, c)
    q_lo, q_hi = _zigzag_split(q)
    k_lo, k_hi = _zigzag_split(k)
    v_lo, v_hi = _zigzag_split(v)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def fwd(causal_flag):
        def run(op):
            qh, kh, vh = op
            return _flash_fwd(qh, kh, vh, causal=causal_flag, block_q=bq,
                              block_k=bk, interpret=interpret)
        return run

    def future(op):
        return (jnp.zeros((b, c, h, d), q.dtype),
                jnp.full((b, h, c), NEG_INF, jnp.float32))

    def step(carry, i):
        o_lo, lse_lo, o_hi, lse_hi, kl, kh, vl, vh = carry
        src = (idx - i) % n
        # q_hi × k_lo: hi chunk (2n−1−idx) is ALWAYS past every lo chunk
        o_blk, lse_blk = fwd(False)((q_hi, kl, vl))
        o_hi, lse_hi = _merge_blocks(o_hi, lse_hi, o_blk, lse_blk)
        # q_lo × k_lo: past iff src < idx on lo chunk ids
        o_blk, lse_blk = _block_cases(src, idx, True, fwd(True), fwd(False),
                                      future, (q_lo, kl, vl))
        o_lo, lse_lo = _merge_blocks(o_lo, lse_lo, o_blk, lse_blk)
        # q_hi × k_hi: hi ids invert the order — past iff src > idx
        o_blk, lse_blk = _block_cases(idx, src, True, fwd(True), fwd(False),
                                      future, (q_hi, kh, vh))
        o_hi, lse_hi = _merge_blocks(o_hi, lse_hi, o_blk, lse_blk)
        roll = lambda x: jax.lax.ppermute(x, axis_name, perm)
        return (o_lo, lse_lo, o_hi, lse_hi,
                roll(kl), roll(kh), roll(vl), roll(vh)), None

    z_o = jnp.zeros((b, c, h, d), jnp.float32)
    z_l = jnp.full((b, h, c), NEG_INF, jnp.float32)
    (o_lo, lse_lo, o_hi, lse_hi, *_), _ = jax.lax.scan(
        step, (z_o, z_l, z_o, z_l, k_lo, k_hi, v_lo, v_hi), jnp.arange(n))
    out = jnp.concatenate([o_lo, o_hi], axis=1).astype(q.dtype)
    lse = jnp.concatenate([lse_lo, lse_hi], axis=2)
    return out, (q, k, v, out, lse)


def _zigzag_vjp_fwd(q, k, v, axis_name, block_q, block_k):
    return _zigzag_fwd_res(q, k, v, axis_name, block_q, block_k)


def _zigzag_vjp_bwd(axis_name, block_q, block_k, res, g):
    """Backward ring pass with the same 4-pair structure: (k, v, dk, dv)
    half-bundles rotate together and return home fully accumulated after n
    steps; dq halves accumulate locally. Every pair recomputes P from the
    saved global lse via the tiled flash backward kernels."""
    from .flash_attention import _flash_bwd, _interpret_default

    q, k, v, out, lse = res
    interpret = _interpret_default()
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    c = t_loc // 2
    bq, bk = min(block_q, c), min(block_k, c)
    q_lo, q_hi = _zigzag_split(q)
    k_lo, k_hi = _zigzag_split(k)
    v_lo, v_hi = _zigzag_split(v)
    o_lo, o_hi = _zigzag_split(out)
    g_lo, g_hi = _zigzag_split(g)
    lse_lo, lse_hi = _zigzag_split(lse, axis=2)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def bwd(qh, oh, lseh, gh, causal_flag):
        def run(op):
            kh, vh = op
            return _flash_bwd(qh, kh, vh, oh, lseh, gh, causal=causal_flag,
                              block_q=bq, block_k=bk, interpret=interpret)
        return run

    def future(op):
        kh, vh = op
        return (jnp.zeros((b, c, h, d), q.dtype), jnp.zeros_like(kh),
                jnp.zeros_like(vh))

    def step(carry, i):
        dq_lo, dq_hi, kl, kh, vl, vh, dkl, dkh, dvl, dvh = carry
        src = (idx - i) % n
        # q_hi × k_lo: always past (dense)
        dqc, dkc, dvc = bwd(q_hi, o_hi, lse_hi, g_hi, False)((kl, vl))
        dq_hi = dq_hi + dqc.astype(jnp.float32)
        dkl = dkl + dkc.astype(jnp.float32)
        dvl = dvl + dvc.astype(jnp.float32)
        # q_lo × k_lo
        dqc, dkc, dvc = _block_cases(
            src, idx, True, bwd(q_lo, o_lo, lse_lo, g_lo, True),
            bwd(q_lo, o_lo, lse_lo, g_lo, False), future, (kl, vl))
        dq_lo = dq_lo + dqc.astype(jnp.float32)
        dkl = dkl + dkc.astype(jnp.float32)
        dvl = dvl + dvc.astype(jnp.float32)
        # q_hi × k_hi (inverted order)
        dqc, dkc, dvc = _block_cases(
            idx, src, True, bwd(q_hi, o_hi, lse_hi, g_hi, True),
            bwd(q_hi, o_hi, lse_hi, g_hi, False), future, (kh, vh))
        dq_hi = dq_hi + dqc.astype(jnp.float32)
        dkh = dkh + dkc.astype(jnp.float32)
        dvh = dvh + dvc.astype(jnp.float32)
        roll = lambda x: jax.lax.ppermute(x, axis_name, perm)
        return (dq_lo, dq_hi, roll(kl), roll(kh), roll(vl), roll(vh),
                roll(dkl), roll(dkh), roll(dvl), roll(dvh)), None

    z = lambda: jnp.zeros((b, c, h, d), jnp.float32)
    (dq_lo, dq_hi, _, _, _, _, dkl, dkh, dvl, dvh), _ = jax.lax.scan(
        step, (z(), z(), k_lo, k_hi, v_lo, v_hi, z(), z(), z(), z()),
        jnp.arange(n))
    cat = lambda a, b_, dt: jnp.concatenate([a, b_], axis=1).astype(dt)
    return (cat(dq_lo, dq_hi, q.dtype), cat(dkl, dkh, k.dtype),
            cat(dvl, dvh, v.dtype))


_zigzag_ring_flash.defvjp(_zigzag_vjp_fwd, _zigzag_vjp_bwd)


def _zigzag_ok(t: int, sp: int) -> bool:
    """Whether the zigzag layout applies: global T divides into 2·sp chunks
    AND each half-chunk tiles by the (env-default) flash blocks — otherwise
    the caller should stay on the plain ring (which clamps/falls back)."""
    from .flash_attention import default_blocks

    if t % (2 * sp):
        return False
    c = t // (2 * sp)
    env_q, env_k = default_blocks(c, c)
    return c % min(env_q, c) == 0 and c % min(env_k, c) == 0


def zigzag_ring_attention_local(q, k, v, *, axis_name: str = "sp",
                                causal: bool = True,
                                block_q: Optional[int] = None,
                                block_k: Optional[int] = None):
    """Load-balanced causal ring attention; called INSIDE shard_map over the
    ZIGZAG layout (``zigzag_permutation``). Causal only — without masking the
    plain ring is already balanced."""
    from .flash_attention import _HAS_PALLAS, default_blocks

    if not causal:
        return ring_attention_local(q, k, v, axis_name=axis_name, causal=False,
                                    block_q=block_q, block_k=block_k)
    if q.shape[1] % 2:
        raise ValueError("zigzag local block needs an even sequence length")
    if not _HAS_PALLAS:
        raise ValueError("zigzag ring needs pallas (use strategy='ring' "
                         "for the jnp fallback)")
    c = q.shape[1] // 2
    env_q, env_k = default_blocks(c, c)
    b_q = min(env_q if block_q is None else block_q, c)
    b_k = min(env_k if block_k is None else block_k, c)
    if c % b_q or c % b_k:
        raise ValueError(f"zigzag half-chunk {c} must tile by blocks "
                         f"({b_q}/{b_k})")
    return _zigzag_ring_flash(q, k, v, axis_name, b_q, b_k)


def ring_attention_local(q, k, v, *, axis_name: str = "sp", causal: bool = False,
                         use_flash: Optional[bool] = None,
                         block_q: Optional[int] = None,
                         block_k: Optional[int] = None):
    """Ring attention over ``axis_name``; called INSIDE shard_map.

    q/k/v: local blocks (B, T_local, H, D); global seq is sharded over the ring.
    The per-step body is the pallas flash kernel whenever pallas is available
    and the local sequence tiles evenly (``use_flash=None`` auto-detects);
    otherwise the plain-jnp online-softmax body runs. Tile sizes default to
    ``default_blocks()`` (env-tunable, like every flash call site).
    """
    from .flash_attention import _HAS_PALLAS, default_blocks

    env_q, env_k = default_blocks(q.shape[1], k.shape[1])
    b_q = min(env_q if block_q is None else block_q, q.shape[1])
    b_k = min(env_k if block_k is None else block_k, k.shape[1])
    tiles_ok = q.shape[1] % b_q == 0 and k.shape[1] % b_k == 0
    if use_flash is None:
        # auto only on real TPU: elsewhere the kernel runs in interpret mode
        # (correct but slow) — forcing use_flash=True still works for tests
        use_flash = (_HAS_PALLAS and tiles_ok
                     and jax.default_backend() == "tpu")
    if use_flash and not (_HAS_PALLAS and tiles_ok):
        raise ValueError(
            f"use_flash=True needs pallas and evenly-tiling local sequence "
            f"(T_q={q.shape[1]}, T_k={k.shape[1]}, blocks {b_q}/{b_k})")
    if not use_flash:
        return _ring_attention_jnp(q, k, v, axis_name=axis_name, causal=causal)
    return _ring_flash(q, k, v, axis_name, causal, b_q, b_k)


def ulysses_attention_local(q, k, v, *, axis_name: str = "sp",
                            causal: bool = False):
    """Ulysses all-to-all attention; called INSIDE shard_map.

    Reshard (B, T/n, H, D) -> (B, T, H/n, D) with all_to_all, run full local
    attention over the complete sequence, reshard back. Head count must divide
    the ``sp`` axis size.
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    def a2a(x, split, concat):
        return jax.lax.all_to_all(x, axis_name, split_axis=split,
                                  concat_axis=concat, tiled=True)

    # seq-sharded -> head-sharded (gather full sequence, scatter heads)
    q_h = a2a(q, 2, 1)
    k_h = a2a(k, 2, 1)
    v_h = a2a(v, 2, 1)
    if jax.default_backend() == "tpu":
        # blockwise kernel over the gathered sequence: O(block²) score memory
        # per core instead of full_attention's O(T²) (falls back internally
        # when pallas is unavailable or the sequence doesn't tile)
        from .flash_attention import flash_attention

        o = flash_attention(q_h, k_h, v_h, causal)
    else:  # interpret-mode pallas is slow; off-TPU uses the fused XLA path
        o = full_attention(q_h, k_h, v_h, causal=causal)
    return a2a(o, 1, 2)


def prefer_flash_single_device(t: int) -> bool:
    """Auto-dispatch rule shared by the layer (mesh-less) and
    :func:`sharded_attention` (sp==1) paths, so both resolve identically:
    on TPU the pallas kernel beats XLA full attention from 4k up, matches
    it at 2k at the model level (LONGCTX_BENCH.json, MFU_SWEEP.json), and
    is the only option once the (H, T, T) score tensor would OOM.

    Query length 1 — the KV-cache decode step — is excluded UNCONDITIONALLY
    (not just by the threshold): a single query row has nothing to tile, so
    the flash grid/VMEM machinery is pure overhead over one dot+softmax;
    plain attention is the fast path no matter how the threshold is tuned."""
    if t <= 1:
        return False
    return jax.default_backend() == "tpu" and t >= 2048


def sharded_attention(q, k, v, mesh, *, strategy: str = "auto",
                      causal: bool = False, seq_axis: str = "sp",
                      batch_axes=("dp", "fsdp"), head_axis: str = "tp"):
    """Dispatch attention under the global mesh (called inside jit).

    With ``sp > 1`` wraps the chosen sequence-parallel kernel in a shard_map whose
    specs shard batch over dp/fsdp, sequence over sp, heads over tp — so tensor and
    sequence parallelism compose.
    """
    if strategy not in ("auto", "full", "flash", "ring", "zigzag", "ulysses"):
        raise ValueError(f"unknown attention strategy {strategy!r}; "
                         "known: auto, full, flash, ring, zigzag, ulysses")
    sp = mesh.shape[seq_axis]
    if strategy == "auto":
        if sp > 1:
            # causal: the zigzag layout halves the causal ring's idle time
            # when the shape supports it (divisibility + flash tiling); the
            # zigzag branch additionally falls back to ring off TPU
            strategy = ("zigzag" if causal and _zigzag_ok(q.shape[1], sp)
                        else "ring")
        else:
            strategy = ("flash" if prefer_flash_single_device(q.shape[1])
                        else "full")
    if strategy == "flash":
        if sp > 1:
            raise ValueError(
                "strategy='flash' is a single-device kernel; on a sequence-"
                "parallel mesh (sp>1) use 'ring' (blockwise over the sp ring) "
                "or 'ulysses'")
        from .flash_attention import flash_attention

        # batch/head parallelism is embarrassingly parallel for attention:
        # shard_map keeps each device's kernel on its OWN batch/head shard
        # (without it GSPMD would all-gather q/k/v and replicate the work).
        # shard_map needs exact divisibility; shapes that don't split fall
        # back to the unwrapped kernel (GSPMD handles them, possibly with
        # gathers — correct, just not maximally parallel).
        batch_div = 1
        for a in batch_axes:
            batch_div *= mesh.shape[a]
        if q.shape[0] % batch_div or q.shape[2] % mesh.shape[head_axis]:
            return flash_attention(q, k, v, causal)
        spec = P(batch_axes, None, head_axis, None)
        wrapped = shard_map(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return wrapped(q, k, v)
    if strategy == "full" or sp == 1:
        return full_attention(q, k, v, causal=causal)

    spec = P(batch_axes, seq_axis, head_axis, None)
    if strategy == "zigzag":
        import os

        if not causal:
            strategy = "ring"         # balanced already; zigzag buys nothing
        elif not _zigzag_ok(q.shape[1], sp):
            strategy = "ring"         # documented fallback: shape unsuitable
        elif (jax.default_backend() != "tpu"
              and os.environ.get("ZOO_FORCE_ZIGZAG") != "1"):
            # interpret-mode pallas off TPU is orders slower than the jnp
            # ring body; tests force the kernel with ZOO_FORCE_ZIGZAG=1
            strategy = "ring"
        else:
            import numpy as np

            perm = zigzag_permutation(q.shape[1], sp)
            inv = np.argsort(perm)
            wrapped = shard_map(
                functools.partial(zigzag_ring_attention_local,
                                  axis_name=seq_axis, causal=True),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False)
            # constant-index gathers; GSPMD lowers them to ICI permutes
            o = wrapped(q[:, perm], k[:, perm], v[:, perm])
            return o[:, inv]
    fn = {"ring": ring_attention_local,
          "ulysses": ulysses_attention_local}[strategy]
    wrapped = shard_map(
        functools.partial(fn, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return wrapped(q, k, v)
