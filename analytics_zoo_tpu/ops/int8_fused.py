"""Fused-quantization pallas kernels — int8 matmul/conv with in-VMEM
activation quantization (ROADMAP "Pallas kernel tier").

Why this exists: the lax path in :mod:`ops.int8` is numerically right but
structurally wrong for serving — XLA materializes the quantized activations
(``round``/``clamp``/``convert`` → an int8 array the size of the input) and
the f32 rescale as separate HBM round-trips around each ``dot_general``.  On
a raw matmul int8 still wins (1.53×), but through the serving dispatch path
those extra HBM passes inverted the win to 0.72× vs bf16.  Here the whole
pipeline lives inside one kernel per layer:

* the activation tile is quantized **in VMEM** (per-row abs-max over the
  K-tile → int8 — finer granularity than the unfused per-full-row scheme, so
  accuracy can only improve),
* the MXU int8 dot runs per (M,N,K) tile with an int32 accumulator,
* the per-row × per-output-channel rescale is applied on the f32 VMEM
  accumulator, and only the final activation-dtype output block is written
  back — no int8 or dequantized-f32 intermediate ever touches HBM.

The conv variant folds the KH×KW taps into the grid: each program owns one
(batch, output-row) pair and accumulates ``window @ W[kh,kw]`` per tap with
per-output-pixel activation scales (one abs-max over channels per pixel —
the granularity the unfused path in :mod:`ops.int8` now matches).

Block sizes come from :mod:`ops.tuning` (on-disk autotuner cache keyed by
device kind) with ``ZOO_INT8_BLOCK_M/N/K`` env overrides; shapes that do not
tile fall back to the lax path (see :func:`ops.int8.int8_matmul`, the
router).  On non-TPU backends the kernels run in interpreter mode for tests;
production CPU inference keeps the lax path (an interpreted kernel is not a
speedup).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas import kept optional: CPU-only deployments fall back to lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    _HAS_PALLAS = False

from ..common.compat import tpu_compiler_params

#: Fixed pre-autotuner schedule (the constants the tuner sweeps around).
DEFAULT_BLOCK_M = 256
DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_K = 512

# int8 VMEM tiling floor is (32, 128); the M dim only feeds the MXU rows so
# 8 (the f32 sublane) is enough for the padded-M path. Interpreter mode has
# no hardware tiling constraint but keeps a floor of 8 on N/K so the
# tileable-vs-fallback decision CPU tests exercise mirrors the TPU one
# (scaled down), instead of degenerating to 1-wide tiles.
_MIN_M, _MIN_N, _MIN_K = 8, 128, 128
_MIN_INTERPRET = 8


def has_pallas() -> bool:
    return _HAS_PALLAS


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def fused_mode() -> str:
    """Routing decision for the int8 entry points: ``'compiled'`` (TPU),
    ``'interpret'`` (forced kernels on CPU — tests/structural gates), or
    ``'off'`` (lax path).

    ``ZOO_INT8_FUSED``: ``0``/``off`` disables, ``1``/``on`` enables (kernels
    interpret on non-TPU backends), ``interpret`` forces interpreter mode.
    Default: compiled on TPU, off elsewhere — an interpreted kernel is
    correctness-equal but orders of magnitude slower than the lax fallback.
    """
    if not _HAS_PALLAS:
        return "off"
    env = os.environ.get("ZOO_INT8_FUSED", "").strip().lower()
    if env in ("0", "off", "false"):
        return "off"
    if env == "interpret":
        return "interpret"
    if env in ("1", "on", "true"):
        return "interpret" if _interpret_default() else "compiled"
    return "off" if _interpret_default() else "compiled"


def _pow2_floor(v: int) -> int:
    return 1 << (int(v).bit_length() - 1)


def _pow2_ceil(v: int) -> int:
    return 1 << (int(v) - 1).bit_length() if v > 1 else 1


def _shrink_to_divisor(dim: int, block: int, floor: int) -> Optional[int]:
    """Largest power-of-two ≤ ``block`` that divides ``dim`` and is ≥
    ``floor`` — None when no such tile exists (caller falls back to lax)."""
    b = _pow2_floor(block)
    while b >= floor:
        if dim % b == 0:
            return b
        b //= 2
    return None


def resolve_blocks(m: int, n: int, k: int, dtype,
                   block_m: Optional[int] = None,
                   block_n: Optional[int] = None,
                   block_k: Optional[int] = None,
                   interpret: bool = False) -> Optional[Tuple[int, int, int]]:
    """Resolve the (block_m, block_n, block_k) schedule for an (M,K)×(K,N)
    fused matmul: explicit args win, then ``ZOO_INT8_BLOCK_M/N/K`` env, then
    the tuning cache (per shape-bucket × dtype × device kind), then the fixed
    defaults; every choice is shrunk to a power-of-two divisor of its dim.
    Returns None when N or K cannot tile (M is padded by the caller)."""
    if block_m is None or block_n is None or block_k is None:
        env = tuple(os.environ.get(f"ZOO_INT8_BLOCK_{ax}")
                    for ax in ("M", "N", "K"))
        tuned = None
        if not any(env):
            from . import tuning

            tuned = tuning.matmul_lookup(m, n, k, dtype)
        block_m = block_m or (int(env[0]) if env[0] else None) or \
            (tuned and tuned[0]) or DEFAULT_BLOCK_M
        block_n = block_n or (int(env[1]) if env[1] else None) or \
            (tuned and tuned[1]) or DEFAULT_BLOCK_N
        block_k = block_k or (int(env[2]) if env[2] else None) or \
            (tuned and tuned[2]) or DEFAULT_BLOCK_K
    # M need not divide: the caller zero-pads the rows up to a block multiple
    # (ragged shape-bucket edges); clamp near M so a tiny batch doesn't pay a
    # full 256-row tile of padding compute
    bm = max(min(_pow2_floor(block_m), _pow2_ceil(max(m, 1))),
             1 if interpret else _MIN_M)
    bn = _shrink_to_divisor(n, min(block_n, n),
                            _MIN_INTERPRET if interpret else _MIN_N)
    bk = _shrink_to_divisor(k, min(block_k, k),
                            _MIN_INTERPRET if interpret else _MIN_K)
    if bn is None or bk is None:
        return None
    return bm, bn, bk


# --------------------------------------------------------------- fused matmul


def _int8_matmul_kernel(x_ref, wq_ref, ws_ref, o_ref, acc_scr):
    """One (block_m, block_n) output tile; grid dim 2 folds the K tiles.

    Quantize the activation K-tile in VMEM (per-row abs-max), int8 MXU dot,
    rescale the int32 partial by the per-row scale into the f32 accumulator;
    the per-channel weight scale lands once on writeback."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(jnp.float32)                      # (bm, bk)
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) * (1.0 / 127.0)        # (bm, 1)
    xq = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    part = jax.lax.dot_general(xq, wq_ref[...], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)
    acc_scr[:] += part.astype(jnp.float32) * scale

    @pl.when(ki == nk - 1)
    def _finish():
        o_ref[...] = (acc_scr[:] * ws_ref[...]).astype(o_ref.dtype)


def _fused_matmul_2d(x2, wq, ws_row, out_dtype, bm: int, bn: int, bk: int,
                     interpret: bool):
    m, k = x2.shape
    n = wq.shape[1]
    return pl.pallas_call(
        _int8_matmul_kernel,
        grid=(m // bm, n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((bk, bn), lambda mi, ni, ki: (ki, ni)),
            pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        # the (mi, ni) dims each own a disjoint output block; only the K fold
        # must stay sequential (it revisits the accumulator)
        compiler_params=None if interpret else tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x2, wq, ws_row)


def int8_matmul_fused(x: jnp.ndarray, packed: Dict[str, Any], *,
                      block_m: Optional[int] = None,
                      block_n: Optional[int] = None,
                      block_k: Optional[int] = None,
                      out_dtype=None,
                      interpret: Optional[bool] = None
                      ) -> Optional[jnp.ndarray]:
    """``x @ W`` on the int8 MXU path with quantize+rescale fused into the
    kernel. ``packed`` is ``ops.int8.quantize_weight`` of an (in, out)
    kernel. Returns ``x.shape[:-1] + (out,)`` in ``out_dtype`` (default f32,
    matching the unfused path), or **None** when the shape cannot tile — the
    caller (the :func:`ops.int8.int8_matmul` router) falls back to lax."""
    if not _HAS_PALLAS:
        return None
    interpret = _interpret_default() if interpret is None else interpret
    wq = packed["q"]
    k, n = wq.shape
    lead = x.shape[:-1]
    m = int(np.prod(lead)) if lead else 1
    out_dtype = jnp.float32 if out_dtype is None else out_dtype
    if m == 0:
        return jnp.zeros(lead + (n,), out_dtype)
    blocks = resolve_blocks(m, n, k, x.dtype, block_m, block_n, block_k,
                            interpret=interpret)
    if blocks is None:
        return None
    bm, bn, bk = blocks
    x2 = x.reshape(m, k)
    pad = (-m) % bm
    if pad:     # ragged M (shape-bucket edges): zero rows quantize to zeros
        x2 = jnp.concatenate(
            [x2, jnp.zeros((pad, k), x2.dtype)], axis=0)
    ws_row = packed["scale"].reshape(1, n).astype(jnp.float32)
    y = _fused_matmul_2d(x2, wq, ws_row, out_dtype, bm, bn, bk, interpret)
    if pad:
        y = y[:m]
    return y.reshape(lead + (n,))


# ----------------------------------------------------------------- fused conv


def _int8_conv_kernel(x_ref, wq_ref, ws_ref, o_ref, acc_scr, *,
                      kw_total: int, wo: int):
    """One (batch, output-row) pair; grid dim 2 folds the KH·KW taps.

    Tap t = kh·KW + kw reads input row ``ho + kh`` (via the x BlockSpec index
    map) and its stride-1 window ``[kw : kw+Wo]``; each output pixel's window
    row is quantized with its own channel-abs-max scale (per-pixel
    granularity), dotted against the tap's (Cin, Cout) int8 slice on the MXU,
    and accumulated in f32 VMEM."""
    t = pl.program_id(2)
    nt = pl.num_programs(2)

    @pl.when(t == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kw = jax.lax.rem(t, kw_total)
    win = x_ref[0, 0, pl.ds(kw, wo), :].astype(jnp.float32)  # (Wo, Cin)
    amax = jnp.max(jnp.abs(win), axis=1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) * (1.0 / 127.0)        # (Wo, 1)
    xq = jnp.clip(jnp.round(win / scale), -127, 127).astype(jnp.int8)
    part = jax.lax.dot_general(xq, wq_ref[0, 0], (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)
    acc_scr[:] += part.astype(jnp.float32) * scale

    @pl.when(t == nt - 1)
    def _finish():
        o_ref[0, 0] = (acc_scr[:] * ws_ref[...]).astype(o_ref.dtype)


def int8_conv2d_fused(x: jnp.ndarray, packed: Dict[str, Any], *,
                      strides=(1, 1), padding="VALID", dilation=(1, 1),
                      out_dtype=None,
                      interpret: Optional[bool] = None
                      ) -> Optional[jnp.ndarray]:
    """NHWC × HWIO int8 conv with per-pixel activation quantization fused
    into the kernel. Supports stride (1, 1) / dilation (1, 1) (the serving
    conv shapes); anything else returns None and the router falls back to
    the lax tap-decomposition in :mod:`ops.int8` — same per-pixel math."""
    if not _HAS_PALLAS:
        return None
    if tuple(strides) != (1, 1) or tuple(dilation) != (1, 1):
        return None
    interpret = _interpret_default() if interpret is None else interpret
    wq = packed["q"]
    kh, kw, cin, cout = wq.shape
    out_dtype = jnp.float32 if out_dtype is None else out_dtype
    if isinstance(padding, str) and padding.upper() == "SAME":
        pads = jax.lax.padtype_to_pads(x.shape[1:3], (kh, kw), (1, 1),
                                       "SAME")
        x = jnp.pad(x, ((0, 0),) + tuple(pads) + ((0, 0),))
    elif not isinstance(padding, str):
        x = jnp.pad(x, ((0, 0),) + tuple(tuple(p) for p in padding)
                    + ((0, 0),))
    b, h, w, _ = x.shape
    ho, wo = h - kh + 1, w - kw + 1
    if b == 0 or ho <= 0 or wo <= 0:
        return None
    ws_row = packed["scale"].reshape(1, cout).astype(jnp.float32)
    kernel = functools.partial(_int8_conv_kernel, kw_total=kw, wo=wo)
    y = pl.pallas_call(
        kernel,
        grid=(b, ho, kh * kw),
        in_specs=[
            # one full input row per program; the tap index selects which
            # (block-size-1 ⇒ index == element offset along H)
            pl.BlockSpec((1, 1, w, cin),
                         lambda bi, hi, t: (bi, hi + t // kw, 0, 0)),
            pl.BlockSpec((1, 1, cin, cout),
                         lambda bi, hi, t: (t // kw, t % kw, 0, 0)),
            pl.BlockSpec((1, cout), lambda bi, hi, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, wo, cout),
                               lambda bi, hi, t: (bi, hi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, ho, wo, cout), out_dtype),
        scratch_shapes=[pltpu.VMEM((wo, cout), jnp.float32)],
        compiler_params=None if interpret else tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x, wq, ws_row)
    return y
