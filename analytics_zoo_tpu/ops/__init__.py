"""TPU compute ops: attention strategies (full/ring/zigzag/Ulysses), pallas
kernels (flash attention, fused-quantization int8), block-schedule tuning."""

from .attention import (full_attention, ring_attention_local, sharded_attention,
                        ulysses_attention_local, zigzag_permutation,
                        zigzag_ring_attention_local)
from .int8 import (int8_conv2d, int8_matmul, is_quantized, quantize_weight)
from .int8_fused import (fused_mode, int8_conv2d_fused, int8_matmul_fused)

__all__ = ["full_attention", "ring_attention_local", "sharded_attention",
           "ulysses_attention_local", "zigzag_permutation",
           "zigzag_ring_attention_local",
           "int8_matmul", "int8_conv2d", "int8_matmul_fused",
           "int8_conv2d_fused", "fused_mode", "is_quantized",
           "quantize_weight"]
