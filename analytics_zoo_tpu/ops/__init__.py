"""TPU compute ops: attention strategies (full/ring/zigzag/Ulysses), pallas kernels."""

from .attention import (full_attention, ring_attention_local, sharded_attention,
                        ulysses_attention_local, zigzag_permutation,
                        zigzag_ring_attention_local)

__all__ = ["full_attention", "ring_attention_local", "sharded_attention",
           "ulysses_attention_local", "zigzag_permutation",
           "zigzag_ring_attention_local"]
