"""Speculative multi-token decode: self-drafting k-gram proposals + the
batched accept/reject verify rule (ISSUE 14).

The PR-8 decode loop advances ONE token per step per stream; every step pays
a full model dispatch for a single sampled token. Speculative decoding
amortizes that dispatch: a cheap draft proposer guesses the next
``k - 1`` tokens, the target model scores all ``k`` positions (the certain
last-sampled token + the drafts) in ONE fixed-shape verify step — query
length k against the same paged cache, the q_len>1 mode of the fused
paged-attention kernel — and the accept/reject rule advances a *variable*
number of tokens per slot per step.

**Draft proposer: self-drafting k-gram lookup** (:func:`propose_kgram`), the
zero-parameter flavor of the "small draft model" design point: the proposal
for a stream is the continuation that followed the most recent earlier
occurrence of its current suffix n-gram (prompt-lookup decoding). No second
model to train, version, or hot-swap in lockstep — the "draft model" is the
stream's own history — and it exploits exactly the structure real LM traffic
has (quoting, code, templated text, repetition). Deterministic given the
history, so preempt/park/resume replays identically.

**Accept rule** (:func:`verify_draft_tokens`): for each position j the
target's token x_j is sampled with the SAME per-(seed, ordinal) key
discipline as :func:`~analytics_zoo_tpu.ops.kv_cache.sample_tokens` — the
identical categorical draw the non-speculative loop would have made at that
ordinal given the same prefix. Draft d_j is accepted iff x_j == d_j; the
first mismatching x_j is itself the emitted correction, and a fully
accepted run emits the bonus token x_{k-1}. For a point-mass draft
distribution this IS the standard speculative-sampling accept/reject rule
(accept probability π(d), rejection residual π restricted to ≠d), with a
much stronger practical property: the emitted stream is **bit-identical to
the non-speculative stream at every temperature**, not just greedy — same
seeds, same ordinals, same conditional prefixes ⇒ same draws, by induction.
Speculation changes only how many dispatches the tokens cost.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache import sample_tokens


@dataclasses.dataclass(frozen=True)
class SpecDecodeConfig:
    """Static speculative-decode schedule (part of the compiled verify
    executable's identity: ONE executable per (k, slot-count)).

    ``k``: tokens scored per verify step = 1 certain + (k-1) drafted;
    k=1 degenerates to the plain single-token decode step. ``max_ngram``:
    longest suffix the k-gram proposer backs off from."""

    k: int = 4
    max_ngram: int = 3

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")
        if self.max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {self.max_ngram}")


def propose_kgram(history: Sequence[int], n_draft: int,
                  max_ngram: int = 3) -> List[int]:
    """Draft ``n_draft`` tokens by suffix-matching the stream's own history.

    Finds the most recent EARLIER occurrence of the trailing ``n``-gram
    (n = max_ngram down to 1) and copies the tokens that followed it;
    repeats the last token to pad when the match runs out, and as the
    no-match fallback (repetition is the cheapest structure greedy decode
    exhibits). Host-side, O(|history|) numpy — drafting must cost nothing
    next to a model dispatch."""
    hist = np.asarray(history, np.int32).reshape(-1)
    n_hist = hist.size
    if n_hist == 0:
        return [0] * n_draft
    for n in range(min(max_ngram, n_hist - 1), 0, -1):
        suffix = hist[n_hist - n:]
        starts = np.flatnonzero(hist[: n_hist - n] == suffix[0])
        for s in starts[::-1]:
            if n == 1 or np.array_equal(hist[s:s + n], suffix):
                cont = hist[s + n: s + n + n_draft]
                if cont.size:
                    out = cont.tolist()
                    while len(out) < n_draft:
                        out.append(int(hist[-1]))
                    return out[:n_draft]
    return [int(hist[-1])] * n_draft


def verify_draft_tokens(logits: jax.Array, draft_ids: jax.Array,
                        seeds: jax.Array, token_idx: jax.Array,
                        temperature: jax.Array, *, top_k: int = 0):
    """Batched accept/reject over one verify step's logits.

    ``logits``: (B, k, V) — position j's distribution is conditioned on the
    certain token + drafts d_1..d_j (valid whenever all earlier drafts were
    accepted, which is the only case it is read). ``draft_ids``: (B, k-1);
    ``seeds``/``token_idx``/``temperature``: (B,) — ``token_idx`` is the
    ordinal of the FIRST token this step emits; position j samples under
    ordinal ``token_idx + j``, the exact key the plain loop would use.

    Returns ``(accepted, tokens, draft_probs)``: ``accepted`` (B,) int32 in
    [0, k-1] = leading drafts confirmed; ``tokens`` (B, k) — the target's
    own samples, of which ``tokens[:, :accepted+1]`` are the emitted tokens
    (confirmed drafts + the correction/bonus); ``draft_probs`` (B, k-1) f32
    = π_j(d_j), each draft's acceptance probability under the target (the
    ``zoo_gen_spec_accept_prob`` observability signal)."""
    b, k, v = logits.shape
    flat = logits.reshape(b * k, v)
    ordinals = (token_idx.astype(jnp.uint32)[:, None]
                + jnp.arange(k, dtype=jnp.uint32)[None]).reshape(-1)
    tokens, probs = sample_tokens(
        flat, jnp.repeat(seeds.astype(jnp.uint32), k), ordinals,
        jnp.repeat(temperature, k), top_k=top_k, return_probs=True)
    tokens = tokens.reshape(b, k)
    if k == 1:
        return (jnp.zeros((b,), jnp.int32), tokens,
                jnp.zeros((b, 0), jnp.float32))
    probs = probs.reshape(b, k, v)
    draft_ids = jnp.asarray(draft_ids, jnp.int32)
    match = (tokens[:, : k - 1] == draft_ids).astype(jnp.int32)
    accepted = jnp.sum(jnp.cumprod(match, axis=1), axis=1).astype(jnp.int32)
    draft_probs = jnp.take_along_axis(
        probs[:, : k - 1], draft_ids[..., None], axis=2)[..., 0]
    return accepted, tokens, draft_probs


__all__ = ["SpecDecodeConfig", "propose_kgram", "verify_draft_tokens"]
