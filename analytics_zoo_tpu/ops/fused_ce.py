"""Fused LM-head softmax cross-entropy — O(chunk×V) logits memory.

The reference bounds sequence models by single-node memory (SURVEY §5.7);
its largest classifier heads materialize full (N, V) score matrices. For a
TPU LM at vocab 32k, f32 logits are 1 GB per 8k tokens — at batch 32 ×
seq 2048 that is 8 GB of HBM, which is what forces large batches into
rematerialization (MFU_SWEEP.json: batches ≥16 drop to ~0.35 MFU under
remat). This op is the LM-head analog of flash attention: never hold the
full logits.

Mechanism (``jax.custom_vjp``, like ops/flash_attention.py):

* forward: ``lax.scan`` over token chunks — each step computes the chunk's
  logits ``z = h_c @ W`` (in the operands' promoted dtype: bf16 operands hit
  the MXU bf16 path with f32 accumulation, f32 operands stay full
  precision), reduces them to ``logsumexp`` + the label logit, and drops
  them; only (N,) reductions survive.
* backward: recompute each chunk's logits, form ``softmax − onehot`` scaled
  by the incoming cotangent, and accumulate ``dh_c = dz @ Wᵀ`` and
  ``dW += h_cᵀ @ dz`` — the recompute costs one extra ``N·H·V`` matmul
  (+25% of head FLOPs) in exchange for never materializing (N, V).

Fidelity (tests/test_fused_ce.py, vs the direct lse-form loss): with f32
operands, value and grads match to ~1e-5. With bf16 operands the VALUE
still matches to ~2e-5 (reductions are f32 either way) but ``dW`` is only
bf16-close (rtol ~1e-2): it accumulates through bf16 multiplies in a
different order than the direct path's einsum-VJP.

When to use: this is a MEMORY tool, not a speed tool. Measured on a v5e at
vocab 32k / hidden 1024: batch 16 trains WITHOUT rematerialization through
this path (the direct loss OOMs), but where the direct path fits it is
~6% faster (171 vs 181 ms/step at batch 8) because the backward's logits
recompute costs more than the saved HBM traffic at this scale. Reach for
it when the (N, V) logits (or the remat they force) are the binding
constraint — very large vocabs, long sequences, or big per-chip batches.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _chunk_logits(h_c, kernel):
    """``h_c @ kernel`` in the operands' promoted dtype with f32 accumulation
    — the same discipline as the model's direct head matmul (low-precision
    operands use the MXU fast path; f32 operands stay full precision)."""
    dt = jnp.result_type(h_c.dtype, kernel.dtype)
    return jax.lax.dot_general(
        h_c.astype(dt), kernel.astype(dt),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _prepare(h, labels, chunk):
    """Flatten to a token axis, pad to a chunk multiple, reshape for scan.

    Shared by forward and backward so both ALWAYS agree on the chunking —
    a divergence here would be a silent wrong-gradient bug. Returns
    ``(h3, l3, valid3, n)``: (n_chunks, chunk, H) activations,
    (n_chunks, chunk) labels, validity mask, and the true token count."""
    H = h.shape[-1]
    hf, lf = h.reshape(-1, H), labels.reshape(-1)
    n = hf.shape[0]
    if n == 0:
        raise ValueError(
            "fused_softmax_xent: zero tokens (h has an empty leading shape); "
            "the mean over n=0 tokens is undefined")
    chunk = min(chunk, n)
    pad = (-n) % chunk
    if pad:
        hf = jnp.concatenate([hf, jnp.zeros((pad, H), hf.dtype)])
        lf = jnp.concatenate([lf, jnp.zeros((pad,), lf.dtype)])
    n_chunks = hf.shape[0] // chunk
    h3 = hf.reshape(n_chunks, chunk, H)
    l3 = lf.reshape(n_chunks, chunk).astype(jnp.int32)
    valid3 = (jnp.arange(hf.shape[0]) < n).reshape(n_chunks, chunk)
    return h3, l3, valid3, n


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_softmax_xent(h, kernel, labels, chunk: int = 4096):
    """Mean softmax cross-entropy of ``h @ kernel`` against int ``labels``.

    ``h``: (..., H) activations (any leading shape), ``kernel``: (H, V),
    ``labels``: int array matching ``h``'s leading shape. ``chunk`` is the
    token-chunk size (static); peak extra memory is ``chunk × V`` f32.
    """
    loss, _ = _vjp_fwd(h, kernel, labels, chunk)
    return loss


def _vjp_fwd(h, kernel, labels, chunk):
    h3, l3, valid3, n = _prepare(h, labels, chunk)

    def step(acc, xs):
        h_c, l_c, v_c = xs
        z = _chunk_logits(h_c, kernel)                       # (chunk, V) f32
        lse = jax.nn.logsumexp(z, axis=-1)                   # (chunk,)
        picked = jnp.take_along_axis(z, l_c[:, None], axis=-1)[:, 0]
        return acc + jnp.sum(jnp.where(v_c, lse - picked, 0.0)), None

    total, _ = jax.lax.scan(step, jnp.float32(0), (h3, l3, valid3))
    return total / n, (h, kernel, labels)


def _vjp_bwd(chunk, res, g):
    h, kernel, labels = res
    h3, l3, valid3, n = _prepare(h, labels, chunk)
    scale = (g / n).astype(jnp.float32)

    def step(dW, xs):
        h_c, l_c, v_c = xs
        z = _chunk_logits(h_c, kernel)                       # recompute
        p = jax.nn.softmax(z, axis=-1)
        dz = p - jax.nn.one_hot(l_c, z.shape[-1], dtype=jnp.float32)
        dz = jnp.where(v_c[:, None], dz, 0.0) * scale        # (chunk, V)
        dt = jnp.result_type(h_c.dtype, kernel.dtype)
        dh_c = jax.lax.dot_general(                          # dz @ Wᵀ
            dz.astype(dt), kernel.astype(dt),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        dW = dW + jax.lax.dot_general(                       # h_cᵀ @ dz
            h_c.astype(dt), dz.astype(dt),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dW, dh_c

    dW, dh3 = jax.lax.scan(
        step, jnp.zeros(kernel.shape, jnp.float32), (h3, l3, valid3))
    dh = dh3.reshape(-1, h.shape[-1])[:n].reshape(h.shape)
    # Integer primals take a float0 symbolic-zero cotangent per JAX convention
    # (a zeros_like int array only works while nothing extracts this grad).
    dlabels = np.zeros(np.shape(labels), dtype=jax.dtypes.float0)
    return (dh.astype(h.dtype), dW.astype(kernel.dtype), dlabels)


fused_softmax_xent.defvjp(_vjp_fwd, _vjp_bwd)
