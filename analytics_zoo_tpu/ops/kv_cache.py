"""Paged KV cache for autoregressive decode serving.

The serving stack's one-shot predict path recomputes the whole sequence per
request; an autoregressive decode loop doing that would pay O(T²) attention
per EMITTED token. This module is the TPU-native fix — the decode-side state
store behind ``TransformerLM.prefill()``/``decode_step()`` and the continuous
batcher (:mod:`analytics_zoo_tpu.serving.generation`):

* **Pages, not ragged buffers.** K/V live in a preallocated pool of
  fixed-size pages, ``(n_layers, n_pages, page_size, n_heads, head_dim)``.
  A sequence *slot* owns an int32 page-table row mapping its logical
  positions to pool pages; pages are handed out by the host-side
  :class:`PagePool` as sequences grow and returned when they retire, so HBM
  is sized for the *working set* (active tokens), not
  ``n_slots × max_seq_len`` worst case.
* **One decode executable.** Every device op here has shapes fixed by the
  cache config — ``(n_slots, pages_per_slot)`` tables, ``(n_slots,)``
  lengths — and masks to each row's true length instead of reshaping, the
  same pow2-bucket discipline the serving engine uses for batch sizes. The
  whole multi-slot decode step compiles ONCE; admission/retirement never
  changes a traced shape (the ``decode-shape-stability`` graph-lint rule
  asserts exactly this).
* **Page 0 is scratch.** The pool never hands out page 0; inactive slots
  and not-yet-allocated table entries point at it, so masked lanes scatter
  harmlessly into scratch instead of needing a traced branch.

Parity: the reference's Cluster Serving has no decode path at all (one-shot
Flink inference, PAPERS.md "BigDL 2.0" streams *requests*, not tokens);
paged attention is the standard modern serving answer rebuilt here on
jnp gather/scatter so it runs on any backend and stays one jaxpr.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common.locks import traced_lock

NEG_INF = -1e30

#: Page id every unallocated / masked table entry points at. The pool never
#: allocates it, so garbage writes from inactive lanes land in scratch.
SCRATCH_PAGE = 0


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static geometry of one paged cache (fixes every traced shape)."""

    n_layers: int
    n_heads: int
    head_dim: int
    n_slots: int                       # concurrent decode sequences
    page_size: int = 16                # tokens per page
    pages_per_slot: int = 16           # max_seq_len = page_size * pages_per_slot
    n_pages: Optional[int] = None      # pool size incl. scratch (None = full)
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.page_size < 1 or self.pages_per_slot < 1:
            raise ValueError("page_size and pages_per_slot must be >= 1")
        if self.n_pages is not None and self.n_pages < 2:
            raise ValueError("n_pages must leave room for scratch + 1 page")

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.pages_per_slot

    @property
    def total_pages(self) -> int:
        # +1: page 0 is reserved scratch and backs no sequence
        if self.n_pages is not None:
            return self.n_pages
        return self.n_slots * self.pages_per_slot + 1


def init_cache(cfg: KVCacheConfig) -> Dict[str, jax.Array]:
    """Preallocate the K/V page pools (zeros; contents only ever read through
    a length mask, so stale pages are invisible)."""
    shape = (cfg.n_layers, cfg.total_pages, cfg.page_size, cfg.n_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


class PagePool:
    """Host-side free-list allocator over the cache's page pool.

    Thread-safe; page 0 (scratch) is never handed out. ``alloc`` raises
    :class:`OutOfPages` when the pool is dry — the batcher turns that into a
    truncated stream rather than a deadlock.
    """

    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        # taken under ContinuousBatcher._lock by the decode loop's page-grow
        # path and acquires nothing itself
        # zoo-lock: leaf
        self._lock = traced_lock("PagePool._lock")
        self._free: List[int] = list(range(cfg.total_pages - 1, 0, -1))
        self._capacity = len(self._free)

    @property
    def capacity(self) -> int:
        return self._capacity

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self, n: int = 1) -> List[int]:
        with self._lock:
            if n > len(self._free):
                raise OutOfPages(
                    f"requested {n} pages, {len(self._free)} free "
                    f"(capacity {self._capacity})")
            out = [self._free.pop() for _ in range(n)]
        return out

    def release(self, pages: Sequence[int]) -> None:
        with self._lock:
            for p in pages:
                if p == SCRATCH_PAGE:
                    continue
                if p in self._free:
                    raise ValueError(f"double free of page {p}")
                self._free.append(int(p))


class OutOfPages(RuntimeError):
    """The page pool cannot satisfy an allocation (working set too big)."""


# ---------------------------------------------------------------------------
# device ops — all shapes fixed by KVCacheConfig; traced once
# ---------------------------------------------------------------------------

def paged_write(pages: jax.Array, table: jax.Array, pos: jax.Array,
                new: jax.Array, *, page_size: int) -> jax.Array:
    """Write one token's K or V per slot.

    ``pages``: (P, page_size, H, D) — ONE layer's pool.
    ``table``: (B, pages_per_slot) int32; ``pos``: (B,) int32 (the position
    being written, i.e. the slot's current length); ``new``: (B, H, D).
    Masked/inactive slots must carry table rows full of ``SCRATCH_PAGE``.
    """
    page_idx = pos // page_size
    offset = pos % page_size
    page_ids = jnp.take_along_axis(table, page_idx[:, None], axis=1)[:, 0]
    return pages.at[page_ids, offset].set(new.astype(pages.dtype))


def paged_read(pages: jax.Array, table: jax.Array) -> jax.Array:
    """Gather a slot-major contiguous view of one layer's cache.

    ``pages``: (P, page_size, H, D); ``table``: (B, pages_per_slot) →
    (B, pages_per_slot * page_size, H, D). Fixed output shape — reads beyond
    a slot's true length surface scratch/stale values that the attention
    mask removes.
    """
    b, pps = table.shape
    gathered = pages[table]                      # (B, PPS, page, H, D)
    return gathered.reshape(b, pps * pages.shape[1], *pages.shape[2:])


def prefill_write(pages: jax.Array, table: jax.Array, kv: jax.Array,
                  *, page_size: int) -> jax.Array:
    """Scatter a whole prefill's K or V into the pool.

    ``kv``: (B, T_bucket, H, D) with T_bucket divisible by ``page_size``;
    table entries past the allocated prefix are ``SCRATCH_PAGE``, so bucket
    padding scatters into scratch.
    """
    b, t, h, d = kv.shape
    if t % page_size:
        raise ValueError(f"prefill bucket {t} must divide page_size "
                         f"{page_size}")
    n_pages = t // page_size
    tiles = kv.reshape(b, n_pages, page_size, h, d).astype(pages.dtype)
    return pages.at[table[:, :n_pages]].set(tiles)


def paged_write_multi(pages: jax.Array, table: jax.Array, pos: jax.Array,
                      new: jax.Array, *, page_size: int) -> jax.Array:
    """Write ``T`` consecutive tokens' K or V per slot (the speculative
    verify step's batched twin of :func:`paged_write`).

    ``pages``: (P, page_size, H, D); ``table``: (B, pages_per_slot) int32;
    ``pos``: (B,) int32 — the FIRST position written per slot; ``new``:
    (B, T, H, D) — tokens land at positions ``pos .. pos+T-1``. The caller
    guarantees ``pos + T <= pages_per_slot * page_size`` (the batcher
    retires a slot before its tail can spill past the table). Masked slots
    carry scratch-only table rows, so their writes land in scratch.
    """
    t = new.shape[1]
    positions = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None]  # (B,T)
    page_idx = positions // page_size
    offsets = positions % page_size
    page_ids = jnp.take_along_axis(table, page_idx, axis=1)          # (B,T)
    return pages.at[page_ids, offsets].set(new.astype(pages.dtype))


def decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     lengths: jax.Array) -> jax.Array:
    """Single-query attention against a cached prefix, masked to each row's
    true length.

    ``q``: (B, H, D); ``k``/``v``: (B, T_max, H, D); ``lengths``: (B,) —
    number of VALID cache positions (the new token's K/V already written, so
    the query attends to itself). Plain dot attention on purpose: at query
    length 1 flash tiling is pure overhead (see
    ``ops.attention.prefer_flash_single_device``); softmax statistics in f32.
    """
    d = q.shape[-1]
    scores = jnp.einsum("bhd,bthd->bht", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(d).astype(np.float32)
    t = k.shape[1]
    mask = jnp.arange(t, dtype=jnp.int32)[None, :] < lengths[:, None]  # (B,T)
    scores = jnp.where(mask[:, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bht,bthd->bhd", probs.astype(v.dtype), v)


def decode_attention_multi(q: jax.Array, k: jax.Array, v: jax.Array,
                           lengths: jax.Array) -> jax.Array:
    """Multi-query decode attention: ``T`` new tokens per slot against the
    cached prefix — the reference/fallback path for the speculative verify
    step (the fused twin is :func:`~analytics_zoo_tpu.ops.paged_attention.
    paged_attention` at q_len>1).

    ``q``: (B, T, H, D); ``k``/``v``: (B, T_max, H, D); ``lengths``: (B,) —
    VALID cache positions *including* the T new tokens (their K/V already
    written). Query ``i`` attends to positions ``<= lengths - T + i``:
    causal among the new tokens, full prefix before them. At T=1 this is
    exactly :func:`decode_attention` (bound = lengths - 1).
    """
    t_new = q.shape[1]
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bthd->bhqt", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(d).astype(np.float32)
    t = k.shape[1]
    kv_pos = jnp.arange(t, dtype=jnp.int32)[None, None, None, :]
    q_idx = jnp.arange(t_new, dtype=jnp.int32)[None, None, :, None]
    bound = lengths[:, None, None, None] - t_new + q_idx
    scores = jnp.where(kv_pos <= bound, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqt,bthd->bqhd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# sampling — per-request keys so continuous-batch scheduling never changes a
# stream's tokens (determinism gate in tests/test_generation.py)
# ---------------------------------------------------------------------------

def sample_tokens(logits: jax.Array, seeds: jax.Array, token_idx: jax.Array,
                  temperature: jax.Array, *, top_k: int = 0,
                  return_probs: bool = False):
    """Sample one token per row under an explicit per-request PRNG key.

    ``logits``: (B, V) — any float dtype, upcast to f32 for the softmax.
    ``seeds``: (B,) uint32/int — per-REQUEST seed; ``token_idx``: (B,) —
    the row's generated-token ordinal. The key is
    ``fold_in(PRNGKey(seed), token_idx)``: token i of request r samples
    identically no matter which slot or decode step it lands in, which is
    what makes continuous admit/retire scheduling reproducible.
    ``temperature``: (B,) f32; rows at <= 0 take argmax (greedy).
    ``top_k`` (static): 0 = full distribution, else restrict to the k
    highest-logit tokens.

    ``return_probs`` (static): additionally return the (B, V) f32
    post-temperature/top_k distribution each row sampled from — the
    per-token probabilities the speculative accept/reject rule consumes
    (:mod:`analytics_zoo_tpu.ops.speculative`). The token path is
    UNCHANGED either way (existing streams stay bit-identical; greedy rows'
    probs are the temperature-floored softmax, ≈ one-hot on the argmax).
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(temperature.astype(jnp.float32), 1e-6)[:, None]
    scaled = logits / temp
    if top_k:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled >= kth, scaled, NEG_INF)

    def one(row, seed, idx):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), idx)
        return jax.random.categorical(key, row)

    sampled = jax.vmap(one)(scaled, seeds.astype(jnp.uint32),
                            token_idx.astype(jnp.uint32)).astype(jnp.int32)
    tokens = jnp.where(temperature <= 0, greedy, sampled)
    if not return_probs:
        return tokens
    return tokens, jax.nn.softmax(scaled, axis=-1)


__all__ = [
    "KVCacheConfig", "OutOfPages", "PagePool", "SCRATCH_PAGE",
    "decode_attention", "decode_attention_multi", "init_cache", "paged_read",
    "paged_write", "paged_write_multi", "prefill_write", "sample_tokens",
]
