"""Paged KV cache for autoregressive decode serving.

The serving stack's one-shot predict path recomputes the whole sequence per
request; an autoregressive decode loop doing that would pay O(T²) attention
per EMITTED token. This module is the TPU-native fix — the decode-side state
store behind ``TransformerLM.prefill()``/``decode_step()`` and the continuous
batcher (:mod:`analytics_zoo_tpu.serving.generation`):

* **Pages, not ragged buffers.** K/V live in a preallocated pool of
  fixed-size pages, ``(n_layers, n_pages, page_size, n_heads, head_dim)``.
  A sequence *slot* owns an int32 page-table row mapping its logical
  positions to pool pages; pages are handed out by the host-side
  :class:`PagePool` as sequences grow and returned when they retire, so HBM
  is sized for the *working set* (active tokens), not
  ``n_slots × max_seq_len`` worst case.
* **One decode executable.** Every device op here has shapes fixed by the
  cache config — ``(n_slots, pages_per_slot)`` tables, ``(n_slots,)``
  lengths — and masks to each row's true length instead of reshaping, the
  same pow2-bucket discipline the serving engine uses for batch sizes. The
  whole multi-slot decode step compiles ONCE; admission/retirement never
  changes a traced shape (the ``decode-shape-stability`` graph-lint rule
  asserts exactly this).
* **Page 0 is scratch.** The pool never hands out page 0; inactive slots
  and not-yet-allocated table entries point at it, so masked lanes scatter
  harmlessly into scratch instead of needing a traced branch.

Parity: the reference's Cluster Serving has no decode path at all (one-shot
Flink inference, PAPERS.md "BigDL 2.0" streams *requests*, not tokens);
paged attention is the standard modern serving answer rebuilt here on
jnp gather/scatter so it runs on any backend and stays one jaxpr.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common.locks import traced_lock

NEG_INF = -1e30

#: Page id every unallocated / masked table entry points at. The pool never
#: allocates it, so garbage writes from inactive lanes land in scratch.
SCRATCH_PAGE = 0


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static geometry of one paged cache (fixes every traced shape)."""

    n_layers: int
    n_heads: int
    head_dim: int
    n_slots: int                       # concurrent decode sequences
    page_size: int = 16                # tokens per page
    pages_per_slot: int = 16           # max_seq_len = page_size * pages_per_slot
    n_pages: Optional[int] = None      # pool size incl. scratch (None = full)
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.page_size < 1 or self.pages_per_slot < 1:
            raise ValueError("page_size and pages_per_slot must be >= 1")
        if self.n_pages is not None and self.n_pages < 2:
            raise ValueError("n_pages must leave room for scratch + 1 page")

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.pages_per_slot

    @property
    def total_pages(self) -> int:
        # +1: page 0 is reserved scratch and backs no sequence
        if self.n_pages is not None:
            return self.n_pages
        return self.n_slots * self.pages_per_slot + 1


def init_cache(cfg: KVCacheConfig) -> Dict[str, jax.Array]:
    """Preallocate the K/V page pools (zeros; contents only ever read through
    a length mask, so stale pages are invisible)."""
    shape = (cfg.n_layers, cfg.total_pages, cfg.page_size, cfg.n_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


class PagePool:
    """Host-side REFCOUNTED free-list allocator over the cache's page pool.

    Thread-safe; page 0 (scratch) is never handed out. ``alloc`` hands out
    pages at refcount 1 and raises :class:`OutOfPages` when the pool is dry —
    the batcher turns that into a truncated stream rather than a deadlock.

    Refcounts are what make shared-prefix serving safe: a page a completed
    prefill published into the :class:`PrefixCache` can back MANY streams'
    page tables at once (each holder took :meth:`incref`), and ``release``
    only reclaims it when the LAST holder lets go. Double-free and leak
    accounting survive the upgrade: releasing a page nobody holds still
    raises, and every page is at all times exactly one of *free* or *held*
    (``free_count() + held_count() == capacity`` — the conservation law the
    refcount property test drives).
    """

    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        # taken under ContinuousBatcher._lock by the decode loop's page-grow
        # path and under PrefixCache._lock by publish/evict; acquires
        # nothing itself
        # zoo-lock: leaf
        self._lock = traced_lock("PagePool._lock")
        self._free: List[int] = list(range(cfg.total_pages - 1, 0, -1))
        # page id -> refcount; absent = free. alloc() starts a page at 1.
        self._refs: Dict[int, int] = {}
        self._capacity = len(self._free)

    @property
    def capacity(self) -> int:
        return self._capacity

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def held_count(self) -> int:
        """Distinct pages currently allocated (any refcount)."""
        with self._lock:
            return len(self._refs)

    def shared_count(self) -> int:
        """Pages with refcount >= 2 — prefix pages mapped into more than
        one holder (streams and/or the prefix cache)."""
        with self._lock:
            return sum(1 for r in self._refs.values() if r >= 2)

    def ref_count(self, page: int) -> int:
        """Current refcount of ``page`` (0 = free/scratch)."""
        with self._lock:
            return self._refs.get(int(page), 0)

    def alloc(self, n: int = 1) -> List[int]:
        with self._lock:
            if n > len(self._free):
                raise OutOfPages(
                    f"requested {n} pages, {len(self._free)} free "
                    f"(capacity {self._capacity})")
            out = [self._free.pop() for _ in range(n)]
            for p in out:
                self._refs[p] = 1
        return out

    def incref(self, pages: Sequence[int]) -> None:
        """Add one reference per page — mapping an already-allocated page
        into another holder's table (prefix sharing). Increffing a free
        page is a use-after-free and raises."""
        with self._lock:
            for p in pages:
                p = int(p)
                if p == SCRATCH_PAGE:
                    continue
                if p not in self._refs:
                    raise ValueError(
                        f"incref of unallocated page {p} (use-after-free)")
                self._refs[p] += 1

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; a page returns to the free list
        when its LAST reference is dropped. Releasing a free page raises
        (double free)."""
        with self._lock:
            for p in pages:
                p = int(p)
                if p == SCRATCH_PAGE:
                    continue
                r = self._refs.get(p)
                if r is None:
                    raise ValueError(f"double free of page {p}")
                if r <= 1:
                    del self._refs[p]
                    self._free.append(p)
                else:
                    self._refs[p] = r - 1

    def check_conservation(self) -> None:
        """Assert the pool invariant: every non-scratch page is exactly one
        of free or held, and the two partitions sum to capacity."""
        with self._lock:
            free = set(self._free)
            held = set(self._refs)
            if free & held:
                raise AssertionError(
                    f"pages both free and held: {sorted(free & held)}")
            if len(self._free) != len(free):
                raise AssertionError("duplicate pages on the free list")
            if len(free) + len(held) != self._capacity:
                raise AssertionError(
                    f"page conservation violated: {len(free)} free + "
                    f"{len(held)} held != capacity {self._capacity}")


class OutOfPages(RuntimeError):
    """The page pool cannot satisfy an allocation (working set too big)."""


# ---------------------------------------------------------------------------
# content-addressed prefix cache — host-side index over published KV pages
# ---------------------------------------------------------------------------

def prefix_block_key(parent: Optional[str], tokens: np.ndarray) -> str:
    """Chain hash of one page-aligned prefix block: H(parent key, tokens).

    Keying each block by its parent's key makes a block's identity the
    identity of the WHOLE prefix through it, so lookup is a longest-prefix
    walk (block i only matches if blocks 0..i-1 matched) and two prompts
    sharing a block's tokens but not its prefix never collide."""
    h = hashlib.blake2b(digest_size=16)
    if parent is not None:
        h.update(parent.encode("ascii"))
    h.update(b"|")
    h.update(np.ascontiguousarray(tokens, dtype=np.int32).tobytes())
    return h.hexdigest()


class _PrefixEntry:
    """One published block: the pages backing ``block_tokens`` tokens of
    some prompt prefix, plus the chain bookkeeping."""

    __slots__ = ("key", "parent", "pages", "n_tokens", "last_used",
                 "active", "children")

    def __init__(self, key: str, parent: Optional[str], pages: List[int],
                 n_tokens: int, last_used: int):
        self.key = key
        self.parent = parent
        self.pages = pages          # page ids this entry holds one ref each
        self.n_tokens = n_tokens    # cumulative prefix tokens through here
        self.last_used = last_used  # logical clock, bumped per hit
        self.active = 0             # streams currently matched through here
        self.children: set = set()  # keys chained directly off this block


class PrefixMatch:
    """Result of a :meth:`PrefixCache.lookup` hit. The caller OWNS one
    pool reference per page in ``pages`` (taken by lookup) and must either
    install them in a stream's table or release them."""

    __slots__ = ("keys", "pages", "n_tokens")

    def __init__(self, keys: List[str], pages: List[int], n_tokens: int):
        self.keys = keys
        self.pages = pages
        self.n_tokens = n_tokens


class PrefixCache:
    """Content-addressed index of published prefix KV pages.

    Completed prefills :meth:`publish` their full page-aligned blocks under
    a rolling chain hash; new prefills :meth:`lookup` their prompt and get
    the longest cached prefix mapped back as shared pages (refcount bump,
    zero compute, zero new HBM). The cache holds its OWN pool reference on
    every published page, so entries survive their publisher retiring;
    eviction (:meth:`evict_to_budget` / :meth:`reclaim_pages`) is LRU over
    entries no live stream is matched through, leaf blocks first (an
    interior block is unreachable-from-root only after its children go).

    Thread-safe. All mutation is all-or-nothing under one lock — a chaos
    kill between a stream's prefill and its publish can never leave a torn
    (half-inserted) chain. The K/V *contents* of published pages are
    weight-dependent, so a hot-swap must call :meth:`invalidate`.
    """

    def __init__(self, pool: PagePool, *, block_tokens: int, page_size: int,
                 max_pages: int):
        if block_tokens < 1 or block_tokens % page_size:
            raise ValueError(
                f"prefix_block_tokens must be a positive multiple of "
                f"page_size {page_size}, got {block_tokens}")
        if max_pages < 1:
            raise ValueError(f"prefix cache budget must be >= 1 page, "
                             f"got {max_pages}")
        self.pool = pool
        self.block_tokens = int(block_tokens)
        self.page_size = int(page_size)
        self.max_pages = int(max_pages)
        # taken under ContinuousBatcher._lock (retire path) and takes
        # PagePool._lock (a leaf) for incref/release
        # zoo-lock: guards(_entries, _held_pages, _clock)
        self._lock = traced_lock("PrefixCache._lock")
        self._entries: Dict[str, _PrefixEntry] = {}
        self._held_pages = 0
        self._clock = 0
        # plain counters — the serving layer mirrors these into telemetry
        self.hits = 0
        self.misses = 0
        self.evicted_pages = 0
        self.evict_sweeps = 0

    # ------------------------------------------------------------ read side

    def _pages_per_block(self) -> int:
        return self.block_tokens // self.page_size

    def lookup(self, tokens: np.ndarray) -> Optional[PrefixMatch]:
        """Longest-prefix match of ``tokens`` against the published chains.

        On a hit, takes one pool reference per matched page FOR THE CALLER
        (atomic with the walk, so a concurrent eviction can never reclaim a
        matched page first) and marks each matched entry stream-active
        until :meth:`release_stream`. Returns ``None`` on a miss."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = int(tokens.size)
        bt = self.block_tokens
        with self._lock:
            keys: List[str] = []
            pages: List[int] = []
            matched = 0
            parent: Optional[str] = None
            while matched + bt <= n:
                key = prefix_block_key(parent, tokens[matched:matched + bt])
                entry = self._entries.get(key)
                if entry is None:
                    break
                keys.append(key)
                pages.extend(entry.pages)
                matched += bt
                parent = key
            if not keys:
                self.misses += 1
                return None
            self._clock += 1
            for k in keys:
                e = self._entries[k]
                e.last_used = self._clock
                e.active += 1
            self.pool.incref(pages)      # the caller's references
            self.hits += 1
            return PrefixMatch(keys, list(pages), matched)

    def release_stream(self, keys: Sequence[str]) -> None:
        """Drop a stream's active marks (retire/cancel/failed prefill).
        Tolerates keys already gone — an intervening :meth:`invalidate`
        cleared the index but the stream's own page refs were its safety."""
        with self._lock:
            for k in keys:
                e = self._entries.get(k)
                if e is not None and e.active > 0:
                    e.active -= 1

    # ----------------------------------------------------------- write side

    def publish(self, tokens: np.ndarray, n_tokens: int,
                pages: Sequence[int]) -> int:
        """Publish a completed prefill's FULL blocks into the index.

        ``tokens``: the prompt; ``n_tokens``: how many of them are prefilled
        (decode writes start at ``n_tokens``, so only blocks wholly below it
        are frozen and publishable); ``pages``: the stream's page ids in
        table order. The cache takes its own reference on every newly
        published page. Blocks already present are skipped (first publisher
        wins — identical content by construction). Insertion of the whole
        chain happens under one lock hold: all-or-nothing, never torn.
        Returns the number of blocks newly published."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bt = self.block_tokens
        ppb = self._pages_per_block()
        n_full = int(n_tokens) // bt
        if n_full < 1:
            return 0
        with self._lock:
            parent: Optional[str] = None
            fresh: List[Tuple[str, Optional[str], List[int], int]] = []
            for b in range(n_full):
                key = prefix_block_key(parent, tokens[b * bt:(b + 1) * bt])
                if key not in self._entries:
                    blk = [int(p) for p in pages[b * ppb:(b + 1) * ppb]]
                    fresh.append((key, parent, blk, (b + 1) * bt))
                parent = key
            if not fresh:
                return 0
            self._clock += 1
            for key, par, blk, ntok in fresh:
                self.pool.incref(blk)   # the cache's own references
                self._entries[key] = _PrefixEntry(key, par, blk, ntok,
                                                  self._clock)
                self._held_pages += len(blk)
                if par is not None:
                    self._entries[par].children.add(key)
        return len(fresh)

    # ------------------------------------------------------------- eviction

    def _remove_locked(self, entry: _PrefixEntry) -> None:
        del self._entries[entry.key]
        self._held_pages -= len(entry.pages)
        if entry.parent is not None:
            par = self._entries.get(entry.parent)
            if par is not None:
                par.children.discard(entry.key)
        self.pool.release(entry.pages)

    def _evict_locked(self, done) -> Tuple[int, int]:
        """LRU-evict leaf entries with no active streams until ``done()``
        or no candidates remain. Caller holds the lock."""
        n_entries = n_pages = 0
        while not done():
            cands = [e for e in self._entries.values()
                     if not e.children and e.active == 0]
            if not cands:
                break
            victim = min(cands, key=lambda e: e.last_used)
            self._remove_locked(victim)
            n_entries += 1
            n_pages += len(victim.pages)
        return n_entries, n_pages

    def evict_to_budget(self) -> Dict[str, int]:
        """Shrink cache-held pages to ``max_pages`` (LRU, leaf-first).
        Returns sweep stats (zeros when already under budget)."""
        with self._lock:
            if self._held_pages <= self.max_pages:
                return {"entries": 0, "pages": 0, "held_pages":
                        self._held_pages}
            n_entries, n_pages = self._evict_locked(
                lambda: self._held_pages <= self.max_pages)
            self.evict_sweeps += 1
            self.evicted_pages += n_pages
            return {"entries": n_entries, "pages": n_pages,
                    "held_pages": self._held_pages}

    def reclaim_pages(self, need_free: int) -> int:
        """Pool-pressure valve: evict (LRU, leaf-first) until the POOL has
        ``need_free`` free pages or nothing evictable remains. Returns
        pages released — cache-held-but-unreferenced HBM is reclaimable
        memory, not occupancy."""
        with self._lock:
            n_entries, n_pages = self._evict_locked(
                lambda: self.pool.free_count() >= need_free)
            if n_pages:
                self.evict_sweeps += 1
                self.evicted_pages += n_pages
            return n_pages

    def invalidate(self) -> int:
        """Drop EVERY entry and the cache's page references — the hot-swap
        hook (published K/V was computed under the old weights). Streams
        matched through dropped entries are unaffected: they hold their own
        page references and never re-read the index. Returns pages
        released."""
        with self._lock:
            released = 0
            for e in self._entries.values():
                self.pool.release(e.pages)
                released += len(e.pages)
            self._entries.clear()
            self._held_pages = 0
            return released

    # ---------------------------------------------------------- diagnostics

    def held_pages(self) -> int:
        with self._lock:
            return self._held_pages

    def reclaimable_pages(self) -> int:
        """Cache-held pages whose ONLY reference is the cache's (refcount
        1, entry not stream-active): what an eviction sweep would actually
        hand back to the free list right now."""
        with self._lock:
            return sum(1 for e in self._entries.values() if e.active == 0
                       for p in e.pages if self.pool.ref_count(p) == 1)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            entries = len(self._entries)
            held = self._held_pages
            active = sum(1 for e in self._entries.values() if e.active)
        total = self.hits + self.misses
        return {
            "entries": entries,
            "held_pages": held,
            "budget_pages": self.max_pages,
            "block_tokens": self.block_tokens,
            "stream_active_entries": active,
            "reclaimable_pages": self.reclaimable_pages(),
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
            "evicted_pages": self.evicted_pages,
            "evict_sweeps": self.evict_sweeps,
        }


# ---------------------------------------------------------------------------
# device ops — all shapes fixed by KVCacheConfig; traced once
# ---------------------------------------------------------------------------

def copy_page(cache: Dict[str, jax.Array], src, dst) -> Dict[str, jax.Array]:
    """Copy one page's K and V across every layer, ``src`` -> ``dst`` — the
    copy-on-write op for the one partially-shared boundary page of a
    full-prompt prefix hit. ``src``/``dst`` are traced int32 scalars, so
    every (src, dst) pair rides ONE compiled executable; jit with the cache
    donated and the copy is an in-place page-sized update, not a second
    pool."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    return {name: pages.at[:, dst].set(pages[:, src])
            for name, pages in cache.items()}

def paged_write(pages: jax.Array, table: jax.Array, pos: jax.Array,
                new: jax.Array, *, page_size: int) -> jax.Array:
    """Write one token's K or V per slot.

    ``pages``: (P, page_size, H, D) — ONE layer's pool.
    ``table``: (B, pages_per_slot) int32; ``pos``: (B,) int32 (the position
    being written, i.e. the slot's current length); ``new``: (B, H, D).
    Masked/inactive slots must carry table rows full of ``SCRATCH_PAGE``.
    """
    page_idx = pos // page_size
    offset = pos % page_size
    page_ids = jnp.take_along_axis(table, page_idx[:, None], axis=1)[:, 0]
    return pages.at[page_ids, offset].set(new.astype(pages.dtype))


def paged_read(pages: jax.Array, table: jax.Array) -> jax.Array:
    """Gather a slot-major contiguous view of one layer's cache.

    ``pages``: (P, page_size, H, D); ``table``: (B, pages_per_slot) →
    (B, pages_per_slot * page_size, H, D). Fixed output shape — reads beyond
    a slot's true length surface scratch/stale values that the attention
    mask removes.
    """
    b, pps = table.shape
    gathered = pages[table]                      # (B, PPS, page, H, D)
    return gathered.reshape(b, pps * pages.shape[1], *pages.shape[2:])


def prefill_write(pages: jax.Array, table: jax.Array, kv: jax.Array,
                  *, page_size: int) -> jax.Array:
    """Scatter a whole prefill's K or V into the pool.

    ``kv``: (B, T_bucket, H, D) with T_bucket divisible by ``page_size``;
    table entries past the allocated prefix are ``SCRATCH_PAGE``, so bucket
    padding scatters into scratch.
    """
    b, t, h, d = kv.shape
    if t % page_size:
        raise ValueError(f"prefill bucket {t} must divide page_size "
                         f"{page_size}")
    n_pages = t // page_size
    tiles = kv.reshape(b, n_pages, page_size, h, d).astype(pages.dtype)
    return pages.at[table[:, :n_pages]].set(tiles)


def paged_write_multi(pages: jax.Array, table: jax.Array, pos: jax.Array,
                      new: jax.Array, *, page_size: int) -> jax.Array:
    """Write ``T`` consecutive tokens' K or V per slot (the speculative
    verify step's batched twin of :func:`paged_write`).

    ``pages``: (P, page_size, H, D); ``table``: (B, pages_per_slot) int32;
    ``pos``: (B,) int32 — the FIRST position written per slot; ``new``:
    (B, T, H, D) — tokens land at positions ``pos .. pos+T-1``. The caller
    guarantees ``pos + T <= pages_per_slot * page_size`` (the batcher
    retires a slot before its tail can spill past the table). Masked slots
    carry scratch-only table rows, so their writes land in scratch.
    """
    t = new.shape[1]
    positions = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None]  # (B,T)
    page_idx = positions // page_size
    offsets = positions % page_size
    page_ids = jnp.take_along_axis(table, page_idx, axis=1)          # (B,T)
    return pages.at[page_ids, offsets].set(new.astype(pages.dtype))


def decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     lengths: jax.Array) -> jax.Array:
    """Single-query attention against a cached prefix, masked to each row's
    true length.

    ``q``: (B, H, D); ``k``/``v``: (B, T_max, H, D); ``lengths``: (B,) —
    number of VALID cache positions (the new token's K/V already written, so
    the query attends to itself). Plain dot attention on purpose: at query
    length 1 flash tiling is pure overhead (see
    ``ops.attention.prefer_flash_single_device``); softmax statistics in f32.
    """
    d = q.shape[-1]
    scores = jnp.einsum("bhd,bthd->bht", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(d).astype(np.float32)
    t = k.shape[1]
    mask = jnp.arange(t, dtype=jnp.int32)[None, :] < lengths[:, None]  # (B,T)
    scores = jnp.where(mask[:, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bht,bthd->bhd", probs.astype(v.dtype), v)


def decode_attention_multi(q: jax.Array, k: jax.Array, v: jax.Array,
                           lengths: jax.Array) -> jax.Array:
    """Multi-query decode attention: ``T`` new tokens per slot against the
    cached prefix — the reference/fallback path for the speculative verify
    step (the fused twin is :func:`~analytics_zoo_tpu.ops.paged_attention.
    paged_attention` at q_len>1).

    ``q``: (B, T, H, D); ``k``/``v``: (B, T_max, H, D); ``lengths``: (B,) —
    VALID cache positions *including* the T new tokens (their K/V already
    written). Query ``i`` attends to positions ``<= lengths - T + i``:
    causal among the new tokens, full prefix before them. At T=1 this is
    exactly :func:`decode_attention` (bound = lengths - 1).
    """
    t_new = q.shape[1]
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bthd->bhqt", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(d).astype(np.float32)
    t = k.shape[1]
    kv_pos = jnp.arange(t, dtype=jnp.int32)[None, None, None, :]
    q_idx = jnp.arange(t_new, dtype=jnp.int32)[None, None, :, None]
    bound = lengths[:, None, None, None] - t_new + q_idx
    scores = jnp.where(kv_pos <= bound, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqt,bthd->bqhd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# sampling — per-request keys so continuous-batch scheduling never changes a
# stream's tokens (determinism gate in tests/test_generation.py)
# ---------------------------------------------------------------------------

def sample_tokens(logits: jax.Array, seeds: jax.Array, token_idx: jax.Array,
                  temperature: jax.Array, *, top_k: int = 0,
                  return_probs: bool = False):
    """Sample one token per row under an explicit per-request PRNG key.

    ``logits``: (B, V) — any float dtype, upcast to f32 for the softmax.
    ``seeds``: (B,) uint32/int — per-REQUEST seed; ``token_idx``: (B,) —
    the row's generated-token ordinal. The key is
    ``fold_in(PRNGKey(seed), token_idx)``: token i of request r samples
    identically no matter which slot or decode step it lands in, which is
    what makes continuous admit/retire scheduling reproducible.
    ``temperature``: (B,) f32; rows at <= 0 take argmax (greedy).
    ``top_k`` (static): 0 = full distribution, else restrict to the k
    highest-logit tokens.

    ``return_probs`` (static): additionally return the (B, V) f32
    post-temperature/top_k distribution each row sampled from — the
    per-token probabilities the speculative accept/reject rule consumes
    (:mod:`analytics_zoo_tpu.ops.speculative`). The token path is
    UNCHANGED either way (existing streams stay bit-identical; greedy rows'
    probs are the temperature-floored softmax, ≈ one-hot on the argmax).
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(temperature.astype(jnp.float32), 1e-6)[:, None]
    scaled = logits / temp
    if top_k:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled >= kth, scaled, NEG_INF)

    def one(row, seed, idx):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), idx)
        return jax.random.categorical(key, row)

    sampled = jax.vmap(one)(scaled, seeds.astype(jnp.uint32),
                            token_idx.astype(jnp.uint32)).astype(jnp.int32)
    tokens = jnp.where(temperature <= 0, greedy, sampled)
    if not return_probs:
        return tokens
    return tokens, jax.nn.softmax(scaled, axis=-1)


__all__ = [
    "KVCacheConfig", "OutOfPages", "PagePool", "PrefixCache", "PrefixMatch",
    "SCRATCH_PAGE", "copy_page", "decode_attention",
    "decode_attention_multi", "init_cache", "paged_read", "paged_write",
    "paged_write_multi", "prefill_write", "prefix_block_key",
    "sample_tokens",
]
