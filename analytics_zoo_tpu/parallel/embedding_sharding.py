"""Mesh-row-sharded embedding tables with a model-parallel gather.

The north-star NCF recommender caps out where its user/item tables fit one
chip's HBM. The reference solved the capacity wall host-side with its PMem
feature layer; the TPU-native answer is the DLRM/BigDL-2.0 recipe (PAPERS.md
"BigDL 2.0"): shard the table by ROWS over a mesh axis and make the lookup a
model-parallel exchange instead of a local gather —

    all-gather(ids)  →  owner-shard partial gather  →  reduce-scatter(rows)

Each shard holds ``rows/n`` contiguous table rows. The (batch-sharded) lookup
ids are all-gathered so every shard sees the full batch, each shard gathers
the rows it OWNS (zeros elsewhere), and one tiled ``psum_scatter`` both sums
the partials (each id is owned by exactly one shard, so the "sum" is an exact
select — no float reassociation) and hands every shard its batch slice back.
Exactly one small int collective in, one row-sized collective out.

The backward pass is the transpose by construction: the row-grad
reduce-scatter transposes to an all-gather, the masked owner-gather
transposes to a scatter-add into the LOCAL shard only — so sparse-touched
rows update shard-locally and the dense replicated ``(vocab, embed)``
gradient never exists on any device. This composes with the ZeRO-1 gspmd
machinery unchanged: the table's base spec ``P(axis, None)`` already carries
the axis, so :func:`~.update_sharding.shard_spec_over_axis` leaves it alone
and the optimizer state lands congruently sharded (1/n rows of Adam moments
per device).

Serving-side, the capacity wall is solved by the host hot-row cache instead
(:mod:`analytics_zoo_tpu.serving.rowcache`) — unmarked model instances fall
back to a plain ``jnp.take`` and never need a mesh.
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = [
    "TableSharding", "owned_row_range", "pad_rows", "row_shard_spec",
    "shard_embedding_tables", "sharded_gather", "sharded_table_layers",
]


class TableSharding(NamedTuple):
    """How a marked embedding layer's table is laid out: mesh + the row axis
    (``shard_batch`` selects the training exchange — batch-sharded ids,
    all-gather in / reduce-scatter out — vs the replicated-batch serving
    exchange, masked gather + psum)."""

    mesh: Any
    axis: str = "dp"
    shard_batch: bool = True


def pad_rows(rows: int, n_shards: int) -> int:
    """Smallest row count >= ``rows`` divisible by ``n_shards`` (vocab
    padding: the +1-row id convention rarely divides a mesh axis)."""
    return ((int(rows) + n_shards - 1) // n_shards) * n_shards


def owned_row_range(rows: int, n_shards: int, shard: int) -> Tuple[int, int]:
    """Global ``[lo, hi)`` row range owned by ``shard`` under contiguous
    row sharding — the layout the gather, the row-delta publisher and the
    hot-row cache all key on."""
    per = rows // n_shards
    return shard * per, (shard + 1) * per


def row_shard_spec(shape, mesh, axis: str = "dp") -> P:
    """``P(axis, None)`` when the table's rows divide the axis, else
    replicated — the base param spec for a row-sharded table."""
    n = mesh.shape.get(axis, 1)
    if len(shape) == 2 and n > 1 and shape[0] % n == 0:
        return P(axis, None)
    return P(*([None] * len(shape)))


def sharded_gather(table, ids, mesh, axis: str = "dp", *,
                   shard_batch: bool = True):
    """Model-parallel row lookup: ``table`` is ``(rows, W)`` sharded
    ``P(axis, None)``, ``ids`` is any integer shape; returns
    ``ids.shape + (W,)`` rows.

    ``shard_batch=True`` (training): ids are laid ``P(axis)`` — the exchange
    is all-gather(ids) → owner partial gather → tiled reduce-scatter(rows),
    and the result stays batch-sharded. ``shard_batch=False`` (replicated
    batch, e.g. eval on a training mesh): every shard gathers its owned rows
    for the full batch and one ``psum`` rebuilds replicated rows.

    Falls back to a plain ``jnp.take`` when the axis is trivial or the rows
    don't divide (pad with :func:`pad_rows` first). Out-of-range ids return
    ZERO rows (no shard owns them) — unlike ``jnp.take``'s clamp — so padded
    vocab tails read as explicit zeros.
    """
    from ..common.compat import shard_map

    n = mesh.shape.get(axis, 1) if mesh is not None else 1
    ids = jnp.asarray(ids, jnp.int32)
    out_shape = tuple(ids.shape) + (table.shape[1],)
    flat = ids.reshape(-1)
    if n <= 1 or table.shape[0] % n != 0:
        return jnp.take(table, flat, axis=0).reshape(out_shape)
    rows_per = table.shape[0] // n
    use_batch = shard_batch and flat.shape[0] % n == 0

    def owned_partial(local_table, all_ids):
        loc = all_ids - jax.lax.axis_index(axis) * rows_per
        ok = (loc >= 0) & (loc < rows_per)
        part = jnp.take(local_table, jnp.where(ok, loc, 0), axis=0)
        return jnp.where(ok[:, None], part,
                         jnp.zeros((), local_table.dtype))

    if use_batch:
        def block(local_table, local_ids):
            all_ids = jax.lax.all_gather(local_ids, axis, tiled=True)
            part = owned_partial(local_table, all_ids)
            return jax.lax.psum_scatter(part, axis, scatter_dimension=0,
                                        tiled=True)

        out = shard_map(block, mesh=mesh,
                        in_specs=(P(axis, None), P(axis)),
                        out_specs=P(axis, None), check_vma=False)(table, flat)
    else:
        def block(local_table, all_ids):
            return jax.lax.psum(owned_partial(local_table, all_ids), axis)

        out = shard_map(block, mesh=mesh, in_specs=(P(axis, None), P()),
                        out_specs=P(), check_vma=False)(table, flat)
    return out.reshape(out_shape)


def sharded_table_layers(model) -> List[Any]:
    """Embedding-bearing layers of ``model`` (recursing through containers)
    whose tables CAN shard — i.e. expose a 2-D ``embeddings`` param."""
    from ..nn.layers.embedding import Embedding, FusedPairEmbedding

    out, stack, seen = [], [model], set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        for layer in getattr(node, "layers", []) or []:
            if isinstance(layer, (Embedding, FusedPairEmbedding)):
                out.append(layer)
            elif getattr(layer, "layers", None):
                stack.append(layer)
    return out


def shard_embedding_tables(model, mesh, *, axis: str = "dp",
                           min_rows: int = 0,
                           shard_batch: bool = True) -> Callable:
    """Mark every divisible embedding table in ``model`` for the sharded
    gather and return the matching ``(path, leaf) -> PartitionSpec``
    param-sharding rule for the :class:`~...engine.Estimator`.

    Marking is per LAYER INSTANCE: the training model gathers through the
    mesh while a separately-constructed serving copy of the same
    architecture stays on the plain single-device ``jnp.take`` path. Tables
    whose rows don't divide the axis (pad the vocab with :func:`pad_rows`)
    or fall under ``min_rows`` stay replicated — a tiny table is not worth
    a collective round.

    The returned rule shards ONLY ``embeddings`` leaves the walk marked;
    everything else replicates, and the ZeRO-1 update-sharding rule
    (:func:`~.update_sharding.make_update_sharding`) then extends the dense
    leaves with the usual dp shard while leaving the already-axis-bearing
    tables untouched.
    """
    n = mesh.shape.get(axis, 1)

    def eligible(rows: int) -> bool:
        return n > 1 and rows % n == 0 and rows >= min_rows

    marked_shapes = set()
    for layer in sharded_table_layers(model):
        rows = (layer.user_count + layer.item_count
                if hasattr(layer, "user_count") else layer.input_dim)
        if eligible(int(rows)):
            layer.table_sharding = TableSharding(mesh, axis, shard_batch)
            marked_shapes.add(int(rows))

    def rule(path, leaf) -> P:
        shape = tuple(getattr(leaf, "shape", ()))
        keys = [str(getattr(p, "key", getattr(p, "name", p))) for p in path]
        if (len(shape) == 2 and keys and keys[-1] == "embeddings"
                and shape[0] in marked_shapes and eligible(shape[0])):
            return P(axis, None)
        return P(*([None] * len(shape)))

    return rule
