"""Pipeline parallelism — GPipe-style microbatching over the ``pp`` mesh axis
(SURVEY.md §2.2: "stage mesh axis + jax.lax collective permute microbatching").

Design (TPU-idiomatic, no per-stage Python processes):
* stage parameters are STACKED on a leading axis and sharded over ``pp`` so each
  device holds exactly its stage's weights;
* inside ``shard_map`` every device runs the same program: at step t it applies
  its stage to the activation it holds, then ``ppermute``s the result to the
  next stage. After ``n_micro + n_stages - 1`` steps every microbatch has
  flowed through every stage (the classic pipeline schedule, bubble =
  (n_stages-1)/(n_micro+n_stages-1));
* the loop is a ``lax.scan`` → one compiled program, differentiable (JAX
  autodiff through ``ppermute`` gives the reverse schedule for backward).

The stage function must be shape-preserving (hidden size constant across
stages) — the standard transformer-block pipeline regime.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..common.compat import axis_size, shard_map


def stack_stage_params(params_list):
    """[per-stage pytree] → one pytree with a leading stage axis (to shard
    over ``pp``)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def _pipeline_local(stage_params, x_micro, *, stage_fn, axis_name: str):
    """Runs INSIDE shard_map. ``stage_params``: this device's stage params
    (leading stage axis already consumed by sharding → shape (1, ...) per leaf);
    ``x_micro``: (n_micro, micro_B, ...) — full microbatch stream, present on
    stage 0 (other stages receive via the ring).
    """
    n_stages = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    my_params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    n_micro = x_micro.shape[0]
    total = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    micro_shape = x_micro.shape[1:]
    carry_in = jnp.zeros(micro_shape, x_micro.dtype)   # activation in flight
    outputs = jnp.zeros((n_micro,) + micro_shape, x_micro.dtype)

    def step(state, t):
        carry, outputs = state
        # stage 0 injects microbatch t (while it exists); others use the ring input
        inject = jnp.where(t < n_micro, jnp.minimum(t, n_micro - 1), 0)
        x_in = jnp.where(idx == 0,
                         jax.lax.dynamic_index_in_dim(x_micro, inject, 0,
                                                      keepdims=False),
                         carry)
        y = stage_fn(my_params, x_in)
        # last stage records finished microbatch (micro t arrives at stage s at
        # step t + s; on the last stage: out_t = t - (n_stages - 1))
        out_t = t - (n_stages - 1)
        record = jnp.logical_and(idx == n_stages - 1, out_t >= 0)
        outputs = jax.lax.cond(
            record,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_t, 0), 0),
            lambda o: o, outputs)
        carry = jax.lax.ppermute(y, axis_name, perm)
        return (carry, outputs), None

    (carry, outputs), _ = jax.lax.scan(step, (carry_in, outputs),
                                       jnp.arange(total))
    # outputs live on the last stage; broadcast so every shard returns them
    # (psum over the one-hot owner is a broadcast on the pp ring)
    owner = (idx == n_stages - 1).astype(outputs.dtype)
    outputs = jax.lax.psum(outputs * owner, axis_name)
    return outputs


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params, x: jnp.ndarray, mesh, *,
                   n_microbatches: int, axis_name: str = "pp"):
    """Apply ``n_stages`` copies of ``stage_fn`` as a pipeline.

    Args:
        stage_fn: ``(stage_params, activation) -> activation`` (shape-preserving).
        stacked_params: pytree with leading stage axis == mesh.shape[axis_name].
        x: global batch (B, ...); B must divide by n_microbatches.
        mesh: the global mesh (other axes replicated here; compose via vmap/dp
              sharding of the batch upstream).
    Returns the final-stage activations, shape (B, ...).
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by {n_microbatches} microbatches")
    x_micro = x.reshape((n_microbatches, b // n_microbatches) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis_name), stacked_params)
    fn = shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, P()),     # params stage-sharded, stream replicated
        out_specs=P(),
        check_vma=False)
    out = fn(stacked_params, x_micro)
    return out.reshape((b,) + x.shape[1:])
