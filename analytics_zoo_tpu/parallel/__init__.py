"""Parallelism strategies: mesh-axis sharding rules + sequence-parallel attention.

Axes (SURVEY.md §2.2 — all first-class here, vs. data-parallel-only reference):
dp (data), fsdp (ZeRO param/optstate), tp (tensor), sp (sequence/ring attention),
pp (pipeline), ep (expert).
"""

from ..common.context import build_mesh
from ..ops.attention import (full_attention, ring_attention_local,
                             sharded_attention, ulysses_attention_local)
from .sharding import TP_RULES, make_param_sharding, replicated
from .pipeline import pipeline_apply, stack_stage_params

__all__ = [
    "pipeline_apply", "stack_stage_params",
    "TP_RULES", "build_mesh", "full_attention", "make_param_sharding",
    "replicated", "ring_attention_local", "sharded_attention",
    "ulysses_attention_local",
]
