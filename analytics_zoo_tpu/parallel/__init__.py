"""Parallelism strategies: mesh-axis sharding rules + sequence-parallel attention.

Axes (SURVEY.md §2.2 — all first-class here, vs. data-parallel-only reference):
dp (data), fsdp (ZeRO param/optstate), tp (tensor), sp (sequence/ring attention),
pp (pipeline), ep (expert).
"""

from ..common.context import build_mesh
from ..ops.attention import (full_attention, ring_attention_local,
                             sharded_attention, ulysses_attention_local)
from .sharding import TP_RULES, make_param_sharding, replicated
from .pipeline import pipeline_apply, stack_stage_params
from .embedding_sharding import (TableSharding, owned_row_range, pad_rows,
                                 row_shard_spec, shard_embedding_tables,
                                 sharded_gather, sharded_table_layers)
from .update_sharding import (collective_counts, flat_exchange, flat_meta,
                              make_comm_probe, make_update_sharding,
                              shard_spec_over_axis, with_master_weights)

__all__ = [
    "pipeline_apply", "stack_stage_params",
    "TP_RULES", "TableSharding", "build_mesh", "collective_counts",
    "flat_exchange", "flat_meta", "full_attention", "make_comm_probe",
    "make_param_sharding", "make_update_sharding", "owned_row_range",
    "pad_rows", "replicated", "ring_attention_local", "row_shard_spec",
    "shard_embedding_tables", "shard_spec_over_axis", "sharded_attention",
    "sharded_gather", "sharded_table_layers", "ulysses_attention_local",
    "with_master_weights",
]
