"""Parallelism strategies: mesh-axis sharding rules + sequence-parallel attention.

Axes (SURVEY.md §2.2 — all first-class here, vs. data-parallel-only reference):
dp (data), fsdp (ZeRO param/optstate), tp (tensor), sp (sequence/ring attention),
pp (pipeline), ep (expert).
"""

from ..common.context import build_mesh
from ..ops.attention import (full_attention, ring_attention_local,
                             sharded_attention, ulysses_attention_local)
from .sharding import TP_RULES, make_param_sharding, replicated
from .pipeline import pipeline_apply, stack_stage_params
from .update_sharding import (collective_counts, flat_exchange, flat_meta,
                              make_comm_probe, make_update_sharding,
                              shard_spec_over_axis, with_master_weights)

__all__ = [
    "pipeline_apply", "stack_stage_params",
    "TP_RULES", "build_mesh", "collective_counts", "flat_exchange",
    "flat_meta", "full_attention", "make_comm_probe", "make_param_sharding",
    "make_update_sharding", "replicated", "ring_attention_local",
    "shard_spec_over_axis", "sharded_attention", "ulysses_attention_local",
    "with_master_weights",
]
