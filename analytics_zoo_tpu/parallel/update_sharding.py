"""Weight-update sharding (ZeRO-1) over the ``dp`` mesh axis.

This is the last core subsystem of the reference rebuilt TPU-native: BigDL's
``AllReduceParameter`` (Topology.scala:1129-1131, 1578-1597) slices the flat
parameter vector across nodes, reduces each gradient slice to its owner, runs
the optimizer update for that slice only, and broadcasts updated slices back.
On a pure data-parallel mesh the equivalent exchange is

    reduce-scatter(grads) → shard-local optimizer update → all-gather(params)

("Automatic Cross-Replica Sharding of Weight Update in Data-Parallel Training",
Xu et al. 2020): per-step gradient communication stays one collective round,
and optimizer state (plus the f32 master weights of the mixed-precision path)
shrinks to ``1/dp`` per device.

Two implementations, selected by the training engine:

* **flat** (pure-dp mesh) — the BigDL layout, literally: the gradient pytree is
  flattened to one padded f32 vector inside ``shard_map``; ``psum_scatter``
  hands each replica its slice, the optimizer updates that slice against a
  flat (sharded) optimizer state, and one tiled ``all_gather`` rebuilds the
  replicated params. The collective count per *global* step is structural —
  gradient accumulation scans microbatches over device-local grads, so K
  microbatches still cost exactly one reduce-scatter + one all-gather.
* **gspmd** (meshes that also shard params over ``fsdp``/``tp``) —
  :func:`make_update_sharding` extends the per-leaf
  :func:`~analytics_zoo_tpu.parallel.sharding.make_param_sharding` specs with a
  ``dp`` axis on the largest divisible dim; optimizer state is *placed* with
  those specs and the step constrains grads to them, letting the SPMD
  partitioner place the reduce-scatter/all-gather pair (the Xu et al.
  mechanism). Composes with the existing fsdp/tp rules; collective placement
  inside an accumulation scan is XLA's choice on this path.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ..common.compat import axis_size

__all__ = [
    "FlatParamMeta", "FlatUpdateState", "MasterWeightsState",
    "collective_counts", "flat_exchange", "flat_meta", "flatten_tree",
    "make_comm_probe", "make_update_sharding", "shard_spec_over_axis",
    "unflatten_tree", "with_master_weights",
]


# --------------------------------------------------------- gspmd per-leaf specs
def shard_spec_over_axis(spec: P, shape: Sequence[int], mesh,
                         axis: str = "dp") -> P:
    """Extend ``spec`` with ``axis`` on the largest divisible dim.

    Used to derive the optimizer-state/gradient-shard placement from a param's
    base (fsdp/tp) spec: prefers an unsharded dim; otherwise appends ``axis``
    to an existing dim's axis tuple when the combined product still divides;
    leaves the spec unchanged (replicated update for that leaf) when nothing
    divides — small biases/scalars are not worth a collective.

    For 2-D leaves the *row* dim (dim 0) wins ties: embedding tables are
    ``(vocab, embed)`` and row sharding is what the sharded-gather path and
    row-delta publishing key on, so an oblong table with ``embed`` larger
    than the per-shard vocab slice must still shard by rows, not columns.
    Dims of other ranks keep the largest-first order (best bytes/shard).
    """
    size = mesh.shape.get(axis, 1)
    shape = tuple(shape)
    if size <= 1 or not shape:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    entries = entries[: len(shape)]
    used = set()
    for e in entries:
        for a in (e if isinstance(e, tuple) else (e,)):
            if a is not None:
                used.add(a)
    if axis in used:
        return P(*entries)

    def axprod(e) -> int:
        p = 1
        for a in (e if isinstance(e, tuple) else ((e,) if e else ())):
            p *= mesh.shape[a]
        return p

    if len(shape) == 2:
        # (vocab, embed) tables: rows first, regardless of which dim is larger
        order = [0, 1]
    else:
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if entries[i] is None and shape[i] % size == 0:
            entries[i] = axis
            return P(*entries)
    for i in order:
        cur = axprod(entries[i])
        if entries[i] is not None and shape[i] % (cur * size) == 0:
            e = entries[i] if isinstance(entries[i], tuple) else (entries[i],)
            entries[i] = e + (axis,)
            return P(*entries)
    return P(*entries)


def make_update_sharding(mesh, base_rule: Optional[Callable] = None,
                         axis: str = "dp") -> Callable:
    """``(path, leaf) -> PartitionSpec`` for optimizer-state placement: the
    param's base spec (fsdp/tp rules, or replicated) plus ``axis`` on the
    largest divisible dim. Congruent with the grad shards the step's
    ``with_sharding_constraint`` produces."""

    def rule(path, leaf) -> P:
        shape = tuple(getattr(leaf, "shape", ()))
        base = base_rule(path, leaf) if base_rule is not None else P()
        return shard_spec_over_axis(base, shape, mesh, axis)

    return rule


# ------------------------------------------------------------- flat exchange
class FlatParamMeta(NamedTuple):
    """Static flattening layout of a param pytree (BigDL AllReduceParameter's
    flat-vector view): leaf order/shapes/dtypes + dp-padded total length."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    dtypes: Tuple[Any, ...]
    n: int
    npad: int
    n_shards: int

    @property
    def shard_size(self) -> int:
        return self.npad // self.n_shards


class FlatUpdateState(NamedTuple):
    """Optimizer state of the flat exchange: the inner transformation's state
    over the flat (npad,) vector — dp-sharded — plus the f32 master-weight
    shard of the mixed-precision path (``None`` when params are already f32,
    in which case the master shard is re-sliced from the replicated params
    each step instead of stored)."""

    inner_state: Any
    master: Any


class MasterWeightsState(NamedTuple):
    """State of :func:`with_master_weights` (gspmd/replicated mixed-precision
    path): inner optimizer state + the f32 master copy of the params."""

    inner_state: Any
    master: Any


def flat_meta(params, n_shards: int) -> FlatParamMeta:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(tuple(l.shape) for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    dtypes = tuple(jnp.asarray(l).dtype for l in leaves)
    n = int(sum(sizes))
    npad = ((n + n_shards - 1) // n_shards) * n_shards
    return FlatParamMeta(treedef, shapes, sizes, dtypes, n, npad, n_shards)


def flatten_tree(tree, meta: FlatParamMeta, dtype=jnp.float32):
    """Pytree → one (npad,) vector in ``dtype`` (zero-padded tail)."""
    leaves = jax.tree_util.tree_leaves(tree)
    vec = jnp.concatenate([jnp.ravel(l).astype(dtype) for l in leaves])
    if meta.npad > meta.n:
        vec = jnp.pad(vec, (0, meta.npad - meta.n))
    return vec


def unflatten_tree(vec, meta: FlatParamMeta):
    """(npad,) vector → pytree with the meta's original shapes/dtypes."""
    out, off = [], 0
    for shape, size, dt in zip(meta.shapes, meta.sizes, meta.dtypes):
        out.append(jax.lax.slice_in_dim(vec, off, off + size)
                   .reshape(shape).astype(dt))
        off += size
    return jax.tree_util.tree_unflatten(meta.treedef, out)


def flat_opt_init(tx: optax.GradientTransformation, params,
                  meta: FlatParamMeta, keep_master: bool) -> FlatUpdateState:
    """Global-view init (arrays are full (npad,) vectors; the engine places
    them dp-sharded). ``params`` may be any float dtype — masters are f32."""
    flat32 = flatten_tree(params, meta, jnp.float32)
    return FlatUpdateState(tx.init(flat32), flat32 if keep_master else None)


def flat_exchange(params, grads, opt_state: FlatUpdateState,
                  meta: FlatParamMeta, tx: optax.GradientTransformation, *,
                  axis: str = "dp",
                  clip_norm: Optional[float] = None,
                  clip_value: Optional[tuple] = None):
    """One weight-update exchange; runs INSIDE ``shard_map`` (manual over
    ``axis``). ``grads`` are this replica's local-mean grads.

    Returns ``(new_params, new_opt_state, grad_norm)``; ``grad_norm`` is the
    f32 global (pre-clip) gradient L2 norm. Exactly one grad-sized collective
    round per call: ``psum_scatter`` in, tiled ``all_gather`` out (the norm
    rides a scalar psum).
    """
    n = axis_size(axis)
    shard = meta.shard_size
    idx = jax.lax.axis_index(axis)

    gflat = flatten_tree(grads, meta, jnp.float32)
    # mean over replicas: local grads are means over the local micro/batch
    gshard = jax.lax.psum_scatter(gflat, axis, scatter_dimension=0,
                                  tiled=True) / n
    gnorm = jnp.sqrt(jax.lax.psum(jnp.sum(gshard * gshard), axis))
    if clip_norm is not None:
        # f32 global-norm clipping computed across the scattered shards —
        # optax.clip_by_global_norm would only see one shard here
        gshard = gshard * jnp.minimum(1.0, clip_norm / (gnorm + 1e-12))
    if clip_value is not None:
        lo, hi = clip_value
        gshard = jnp.clip(gshard, lo, hi)

    if opt_state.master is not None:
        master = opt_state.master        # persistent f32 shard (bf16 params)
    else:                                # f32 params: re-slice, store nothing
        pflat = flatten_tree(params, meta, jnp.float32)
        master = jax.lax.dynamic_slice_in_dim(pflat, idx * shard, shard)

    updates, inner2 = tx.update(gshard, opt_state.inner_state, master)
    master2 = optax.apply_updates(master, updates)

    # all-gather in the MODEL dtype: under bf16 params the param broadcast
    # costs half the bytes of the f32 masters
    gather_dt = meta.dtypes[0] if len(set(meta.dtypes)) == 1 else jnp.float32
    new_flat = jax.lax.all_gather(master2.astype(gather_dt), axis, axis=0,
                                  tiled=True)
    new_params = unflatten_tree(new_flat, meta)
    new_opt = FlatUpdateState(inner2,
                              master2 if opt_state.master is not None else None)
    return new_params, new_opt, gnorm


# ------------------------------------------------- master weights (gspmd path)
def with_master_weights(tx: optax.GradientTransformation
                        ) -> optax.GradientTransformation:
    """Wrap ``tx`` so f32 master weights live in (and only in) the optimizer
    state: ``update`` expects f32 grads, runs ``tx`` against the masters, and
    returns the NEW low-precision params as the "updates" (the engine installs
    them directly instead of ``optax.apply_updates``)."""

    def init(params):
        master = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32)
            if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else p,
            params)
        return MasterWeightsState(tx.init(master), master)

    def update(grads, state, params=None):
        g32 = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        updates, inner2 = tx.update(g32, state.inner_state, state.master)
        master2 = optax.apply_updates(state.master, updates)
        if params is not None:
            new_params = jax.tree_util.tree_map(
                lambda m, p: m.astype(jnp.asarray(p).dtype), master2, params)
        else:
            new_params = master2
        return new_params, MasterWeightsState(inner2, master2)

    return optax.GradientTransformation(init, update)


# ------------------------------------------------------------------ comm probe
# probe ceiling: 16M f32 elements = 64 MiB. Above this the probe measures a
# capped vector instead of the full param count — a telemetry probe must not
# hold (and all-gather) gigabytes next to a training state that ZeRO-1 just
# shrank to fit
PROBE_MAX_ELEMS = 16 * 1024 * 1024


def make_comm_probe(mesh, n_elems: int, axis: str = "dp",
                    sharded: bool = False):
    """Jitted one-round grad-exchange probe over an ``n_elems`` f32 vector:
    ``psum`` (replicated exchange) or ``psum_scatter`` + tiled ``all_gather``
    (sharded exchange). The engine times a call at each log point to feed
    ``zoo_train_comm_seconds`` — a measured collective round of the real
    exchange size on the real mesh, off the jitted hot path. ``n_elems`` is
    capped at :data:`PROBE_MAX_ELEMS` (64 MiB of f32) so the cached probe
    vector can never crowd out training memory on billion-param models.

    Returns ``(fn, vec)``; call ``jax.block_until_ready(fn(vec))`` and time
    it. The returned fn is pre-warmed (compiled) so the first observation is
    not a compile.
    """
    from ..common.compat import shard_map

    n = mesh.shape.get(axis, 1)
    n_elems = min(max(1, n_elems), PROBE_MAX_ELEMS)
    npad = ((n_elems + n - 1) // n) * n

    def body(v):
        if sharded:
            s = jax.lax.psum_scatter(v, axis, scatter_dimension=0, tiled=True)
            return jax.lax.all_gather(s, axis, axis=0, tiled=True)
        return jax.lax.psum(v, axis)

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                           check_vma=False))
    vec = jnp.ones((npad,), jnp.float32)
    jax.block_until_ready(fn(vec))      # pre-warm: compile outside the timing
    return fn, vec


# --------------------------------------------------------------- HLO forensics
# The HLO collective counter moved onto the shared static-analysis rule
# engine (analysis/rules/collectives.py) where it backs the
# "collective-budget-hlo" rule; re-exported here so existing callers (the
# bench, tests) keep their import path.
from ..analysis.rules.collectives import collective_counts  # noqa: E402
