"""Parameter-sharding rules: tensor parallelism + ZeRO-style fsdp sharding.

This replaces the reference's gradient-exchange layout — BigDL's
``AllReduceParameter`` slices the flat parameter vector across nodes and lets each
"slice owner" run the optimizer update (Topology.scala:1129-1131, 1578-1597;
docs/docs/wp-bigdl.md §parameter-manager). The TPU-native equivalent is sharding
the param/optimizer pytree over mesh axes and letting GSPMD place the collectives:

* ``tp`` rules — 2D matmul sharding for transformer/dense weights (megatron
  layout): QKV/up projections shard the OUTPUT dim, out/down projections shard the
  INPUT dim, embeddings shard rows.
* ``fsdp`` rule — shard the largest divisible axis of every remaining ≥2D param
  over ``fsdp`` (ZeRO-3-ish; optimizer state inherits the same sharding because it
  is pytree-congruent with params). This IS the "slice owner updates" capability,
  minus the driver round-trips.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

# (path-substring, spec) — first match wins. Specs use logical axis names; the
# builder swaps in None for any axis the dim doesn't divide.
TP_RULES: Tuple[Tuple[str, P], ...] = (
    ("qkv_kernel", P("fsdp", "tp")),
    ("mlp_up_kernel", P("fsdp", "tp")),
    ("out_kernel", P("tp", "fsdp")),
    ("mlp_down_kernel", P("tp", "fsdp")),
    ("token_embeddings", P("tp", None)),
    ("embeddings", P("tp", None)),
    ("logits_kernel", P("fsdp", "tp")),
)


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def _fits(size: int, axis, mesh) -> bool:
    if axis is None:
        return True
    ax_size = 1
    for a in (axis if isinstance(axis, tuple) else (axis,)):
        ax_size *= mesh.shape[a]
    return size % ax_size == 0


def _sanitize(spec: P, shape, mesh, path: Optional[str] = None) -> P:
    """Adapt ``spec`` to ``shape``: a single mesh axis that does not divide a
    dim falls back to replicated on that dim (documented, tested behavior for
    e.g. odd vocab sizes), but a *tuple* of axes whose combined size
    over-divides a dim is a layout error in the rule itself — raise with the
    param path so the author can fix the rule rather than silently training
    replicated."""
    axes = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for dim, axis in zip(shape, axes[: len(shape)]):
        if isinstance(axis, tuple) and not _fits(dim, axis, mesh):
            sizes = {a: mesh.shape[a] for a in axis}
            raise ValueError(
                f"param {path or '<unknown>'}: dim of size {dim} cannot be "
                f"sharded over combined mesh axes {axis} (sizes {sizes}, "
                f"product {int(np.prod(list(sizes.values())))}) — the "
                f"combined axes must divide the dim; fix the sharding rule "
                f"or the mesh layout")
        out.append(axis if _fits(dim, axis, mesh) else None)
    return P(*out)


def make_param_sharding(mesh, rules: Sequence[Tuple[str, P]] = TP_RULES,
                        fsdp_default: bool = True) -> Callable:
    """Build a ``(path, leaf) -> PartitionSpec`` fn for Estimator(param_sharding=...).

    Matching order: explicit tp rules by path substring, then (optionally) fsdp
    sharding of the largest divisible axis, else replicated.
    """
    fsdp_size = mesh.shape.get("fsdp", 1)

    def rule(path, leaf) -> P:
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape:
            return P()
        pstr = _path_str(path)
        for needle, spec in rules:
            if needle in pstr:
                return _sanitize(spec, shape, mesh, path=pstr)
        if fsdp_default and fsdp_size > 1 and len(shape) >= 1:
            # shard the largest divisible axis over fsdp
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size:
                    axes = [None] * len(shape)
                    axes[i] = "fsdp"
                    return P(*axes)
        return P()

    return rule


def replicated(mesh) -> Callable:
    return lambda path, leaf: P()
