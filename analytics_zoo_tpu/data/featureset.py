"""FeatureSet — the training-data abstraction with cache tiers + epoch slicing.

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/feature/
FeatureSet.scala (AbstractFeatureSet :53-103, CachedDistributedFeatureSet :230,
DiskFeatureSet :546, memory-type dispatch :652-676). The reference caches RDDs in
DRAM / Optane PMEM / disk with epoch slicing; here the tiers are:

* ``DRAM``            — host RAM ndarrays (default)
* ``DISK_AND_DRAM(n)``— ``np.memmap``-backed arrays sliced into ``n`` epoch slices,
                        only one slice resident per sub-epoch (DiskFeatureSet parity)
* ``PMEM``            — alias of DISK_AND_DRAM(1) over a memmap on a pmem/NVMe mount
                        (PersistentMemoryAllocator capability, java/.../pmem/)

Multi-host sharding: each process owns ``data[process_index::process_count]``
(replaces Spark partition placement). Batches are GLOBAL — the loader yields each
host's shard of every global batch; the training engine lays them onto the ``dp``
mesh axis with ``jax.make_array_from_process_local_data``.

Deterministic shuffle: per-epoch permutation from ``seed + epoch`` so every host
computes the same global permutation without communication.
"""

from __future__ import annotations

import math
import os
import tempfile
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..common import telemetry as _tm

ArrayTree = Any  # nested tuple/dict/list of np.ndarray, all with equal leading dim

# input-pipeline visibility: how long each host-side batch takes to
# materialize (gather/slice/decode) — the producer-side complement of the
# Estimator's per-step DataWait, which only sees time the STEP loop blocked
_DATA_BATCHES = _tm.counter("zoo_data_batches_total",
                            "Host batches produced by FeatureSet iterators")
_DATA_GATHER = _tm.histogram("zoo_data_batch_gather_seconds",
                             "Host time to materialize one batch "
                             "(gather/slice, memmap reads, AND per-record "
                             "decode for byte-record tiers)")
_DATA_DECODE = _tm.histogram("zoo_data_decode_seconds",
                             "Per-batch record-decode time "
                             "(BytesFeatureSet.decoder over the gathered "
                             "records; subset of zoo_data_batch_gather_seconds)")


class MemoryType:
    DRAM = "DRAM"
    PMEM = "PMEM"
    # the reference's DIRECT tier = off-JVM-heap byte buffers (GC pressure
    # relief); numpy arrays are already heap-external so it IS the DRAM tier
    DIRECT = "DIRECT"

    @staticmethod
    def DISK_AND_DRAM(num_slice: int) -> str:
        return f"DISK_AND_DRAM_{num_slice}"


def _tree_map(fn, tree):
    if isinstance(tree, dict):
        return {k: _tree_map(fn, v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_map(fn, v) for v in tree)
    return fn(tree)


def _tree_leaves(tree):
    if isinstance(tree, dict):
        out = []
        for v in tree.values():
            out.extend(_tree_leaves(v))
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for v in tree:
            out.extend(_tree_leaves(v))
        return out
    return [tree]


class FeatureSet:
    """An immutable, shardable dataset of array trees."""

    def __init__(self, data: ArrayTree, memory_type: str = MemoryType.DRAM,
                 cache_dir: Optional[str] = None, process_index: int = 0,
                 process_count: int = 1, seed: int = 0,
                 host_shard: bool = False):
        self.memory_type = memory_type
        self.process_index = process_index
        self.process_count = process_count
        self.seed = seed
        self.host_shard = host_shard
        leaves = _tree_leaves(data)
        if not leaves:
            raise ValueError("empty FeatureSet")
        n = leaves[0].shape[0]
        for l in leaves:
            if l.shape[0] != n:
                raise ValueError("all arrays must share the leading dimension")
        self._n_total = n
        self._mm_count = 0
        if memory_type.startswith("DISK_AND_DRAM") or memory_type == MemoryType.PMEM:
            self.num_slices = (int(memory_type.rsplit("_", 1)[1])
                               if memory_type.startswith("DISK_AND_DRAM") else 1)
            self._cache_dir = cache_dir or tempfile.mkdtemp(prefix="zoo_featureset_")
            self.data = _tree_map(self._to_memmap, data)
        else:
            self.num_slices = 1
            self.data = data

    # -------------------------------------------------------------- constructors
    @classmethod
    def from_numpy(cls, x, y=None, **kw) -> "FeatureSet":
        """Build from feature array(s) + optional label array(s)
        (FeatureSet.rdd(...) parity)."""
        data = (x,) if y is None else (x, y)
        return cls(data, **kw)

    @classmethod
    def from_xshards(cls, shards, **kw) -> "FeatureSet":
        from .xshards import XShards

        assert isinstance(shards, XShards)
        return cls(shards.collect_tree(), **kw)

    @classmethod
    def from_host_shard(cls, data: ArrayTree, process_index: Optional[int] = None,
                        process_count: Optional[int] = None,
                        **kw) -> "FeatureSet":
        """Multi-host sharded ingest: ``data`` is THIS host's slice only (e.g.
        from ``XShards.host_split`` over per-host files) — no host ever
        materializes the global dataset. ``batches`` then yields the local
        ``batch/process_count`` rows per global step; shards should be
        balanced (±1 batch) so hosts stay in lockstep. Defaults ranks from
        ``jax.distributed`` (process_index/process_count)."""
        if process_index is None or process_count is None:
            import jax

            process_index = jax.process_index() if process_index is None \
                else process_index
            process_count = jax.process_count() if process_count is None \
                else process_count
        return cls(data, process_index=process_index,
                   process_count=process_count, host_shard=True, **kw)

    @classmethod
    def from_tf_dataset(cls, dataset, max_elements: Optional[int] = None,
                        **kw) -> "FeatureSet":
        """Materialize a ``tf.data.Dataset`` into a FeatureSet (TFDataset
        family parity — tf_dataset.py:116 ``from_tf_data``; the tf.data graph
        runs host-side once, then batches feed the device like any other tier).

        Elements may be tensors, (x, y) tuples, or dicts of tensors; dataset
        must be UNBATCHED (per-example elements). ``max_elements`` caps
        materialization for infinite/huge datasets.
        """
        import itertools

        it = dataset.as_numpy_iterator()
        if max_elements is not None:
            it = itertools.islice(it, max_elements)  # no extra fetch past cap
        rows = list(it)
        if not rows:
            raise ValueError("tf.data dataset yielded no elements")
        first = rows[0]
        if isinstance(first, dict):
            tree = {k: np.stack([r[k] for r in rows]) for k in first}
        elif isinstance(first, (tuple, list)):
            tree = tuple(np.stack([r[i] for r in rows])
                         for i in range(len(first)))
        else:
            tree = np.stack(rows)
        return cls(tree, **kw)

    @classmethod
    def from_generator(cls, generator, max_elements: Optional[int] = None,
                       **kw) -> "FeatureSet":
        """Materialize a python generator/iterable of per-example elements
        (the TFDataset py-func variants — TFFeatureDataset/TFTextDataset,
        tf_dataset.py:661-1131 — where user python code produces examples).
        Elements may be arrays, (x, y) tuples, or dicts of arrays."""
        import itertools

        it = iter(generator() if callable(generator) else generator)
        if max_elements is not None:
            it = itertools.islice(it, max_elements)
        rows = list(it)
        if not rows:
            raise ValueError("generator yielded no elements")
        first = rows[0]
        if isinstance(first, dict):
            tree = {k: np.stack([np.asarray(r[k]) for r in rows])
                    for k in first}
        elif isinstance(first, (tuple, list)):
            tree = tuple(np.stack([np.asarray(r[i]) for r in rows])
                         for i in range(len(first)))
        else:
            tree = np.stack([np.asarray(r) for r in rows])
        return cls(tree, **kw)

    @classmethod
    def from_bytes(cls, records: Sequence[bytes], decoder: Callable,
                   **kw) -> "BytesFeatureSet":
        """Raw byte-record stream with decode-at-batch-time (``TFBytesDataset``
        parity, tf_dataset.py:661 — the reference feeds undecoded records to a
        TF decode graph per batch). ``decoder(record: bytes)`` returns the
        per-example array tree; only the records of the current batch are ever
        decoded, so memory stays at raw-record size (e.g. JPEG bytes, not
        pixel tensors)."""
        return BytesFeatureSet(records, decoder, **kw)

    @classmethod
    def from_tfrecord(cls, paths, feature_cols: Optional[Sequence[str]] = None,
                      label_cols: Optional[Sequence[str]] = None,
                      max_records: Optional[int] = None, **kw) -> "FeatureSet":
        """tf.Example TFRecord file(s) → FeatureSet (TFDataset TFRecord-variant
        parity, tf_dataset.py:661-1131; decoded by the built-in codec, no
        tensorflow). Without ``feature_cols`` the tree is a dict of all
        features; with them, a ((features...), (labels...)) pair tree."""
        from .tfrecord import read_tfrecord_examples

        table = read_tfrecord_examples(paths, max_records=max_records)

        def label(c):
            arr = table[c]
            # single-value label features squeeze to (N,) for sparse losses;
            # features keep their (N, F) axis (same contract as from_dataframe)
            return arr[:, 0] if (arr.ndim == 2 and arr.shape[1] == 1) else arr

        if feature_cols is None:
            return cls(table, **kw)
        feats = tuple(table[c] for c in feature_cols)
        x = feats[0] if len(feats) == 1 else feats
        if not label_cols:
            return cls((x,), **kw)
        labels = tuple(label(c) for c in label_cols)
        y = labels[0] if len(labels) == 1 else labels
        return cls((x, y), **kw)

    @classmethod
    def from_dataframe(cls, df, feature_cols: Sequence[str],
                       label_cols: Optional[Sequence[str]] = None,
                       **kw) -> "FeatureSet":
        """pandas DataFrame → FeatureSet (DataFrameDataset parity,
        tf_dataset.py DataFrameDataset / nnframes' df ingestion): feature
        columns stack into one (N, F) float array (object/array cells stack
        row-wise), labels likewise."""

        def gather(cols, squeeze: bool):
            arrays = []
            for c in cols:
                col = df[c].to_numpy()
                if col.dtype == object:   # cells hold arrays/lists
                    col = np.stack([np.asarray(v) for v in col])
                arrays.append(col if col.ndim > 1 else col[:, None])
            out = arrays[0] if len(arrays) == 1 else np.concatenate(
                [a.astype(np.result_type(*[x.dtype for x in arrays]))
                 for a in arrays], axis=1)
            if squeeze and out.ndim == 2 and out.shape[1] == 1:
                return out[:, 0]
            return out

        # features keep (N, F) even for F=1 (models expect a feature axis);
        # a single label column squeezes to (N,) for sparse losses/metrics
        x = gather(feature_cols, squeeze=False)
        if not label_cols:
            return cls((x,), **kw)
        return cls((x, gather(label_cols, squeeze=True)), **kw)

    # ----------------------------------------------------------------- internals
    def _to_memmap(self, arr: np.ndarray) -> np.ndarray:
        path = os.path.join(self._cache_dir, f"arr_{self._mm_count}.npy")
        self._mm_count += 1
        mm = np.lib.format.open_memmap(path, mode="w+", dtype=arr.dtype, shape=arr.shape)
        mm[:] = arr
        mm.flush()
        return np.lib.format.open_memmap(path, mode="r")

    # ------------------------------------------------------------------- API
    def size(self) -> int:
        """Global sample count (AbstractFeatureSet.size parity)."""
        return self._n_total

    def __len__(self) -> int:
        return self._n_total

    def shuffle_indices(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng(self.seed + epoch * 1_000_003)
        return rng.permutation(self._n_total)

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        if self.host_shard:
            if batch_size % self.process_count:
                raise ValueError(
                    f"global batch {batch_size} not divisible by "
                    f"{self.process_count} hosts")
            # _n_total is LOCAL rows here; balanced shards keep hosts in lockstep
            local_bs = batch_size // self.process_count
            if drop_remainder:
                return self._n_total // local_bs
            return math.ceil(self._n_total / local_bs)
        if drop_remainder:
            return self._n_total // batch_size
        return math.ceil(self._n_total / batch_size)

    def batches(self, batch_size: int, *, epoch: int = 0, shuffle: bool = True,
                drop_remainder: bool = True) -> Iterator[ArrayTree]:
        """Yield this host's shard of every global batch.

        ``batch_size`` is GLOBAL and must divide by ``process_count`` (the
        reference requires batch % total_cores == 0 — tf_dataset.py:144).
        Each batch's host-side materialization time lands in the shared
        registry (``zoo_data_batch_gather_seconds``).
        """
        inner = self._iter_batches(batch_size, epoch=epoch, shuffle=shuffle,
                                   drop_remainder=drop_remainder)
        while True:
            t0 = time.perf_counter()
            try:
                b = next(inner)
            except StopIteration:
                return
            _DATA_GATHER.observe(time.perf_counter() - t0)
            _DATA_BATCHES.inc()
            yield b

    def _iter_batches(self, batch_size: int, *, epoch: int = 0,
                      shuffle: bool = True,
                      drop_remainder: bool = True) -> Iterator[ArrayTree]:
        if batch_size % self.process_count:
            raise ValueError(
                f"global batch {batch_size} not divisible by {self.process_count} hosts")
        if not shuffle and self.process_count == 1:
            # sequential single-host epoch: every batch is a CONTIGUOUS row
            # range, so yield slice VIEWS instead of paying a full fancy-index
            # gather per batch (the serving/eval input path reads each row
            # exactly once — a copy would only burn DRAM bandwidth). Consumers
            # treat batches as read-only (they are device_put/stacked next).
            for b in range(self.num_batches(batch_size, drop_remainder)):
                lo = b * batch_size
                hi = min(lo + batch_size, self._n_total)
                yield _tree_map(lambda a: a[lo:hi], self.data)
            return
        if self.host_shard:
            # data is already THIS host's shard (FeatureSet.from_host_shard):
            # every host walks its local permutation in lockstep, yielding
            # batch_size/process_count rows per global step
            local_bs = batch_size // self.process_count
            idx = self.shuffle_indices(epoch) if shuffle \
                else np.arange(self._n_total)
            for b in range(self.num_batches(batch_size, drop_remainder)):
                sel = idx[b * local_bs:(b + 1) * local_bs]
                if len(sel) == 0:
                    continue
                yield _tree_map(lambda a: self._gather(a, sel), self.data)
            return
        idx = self.shuffle_indices(epoch) if shuffle else np.arange(self._n_total)
        nb = self.num_batches(batch_size, drop_remainder)
        for b in range(nb):
            # Strided host assignment: for a partial trailing batch every host
            # still yields (sizes differ by at most 1), so multi-host loops stay
            # in lockstep instead of some hosts skipping the final batch.
            sel = idx[b * batch_size:(b + 1) * batch_size][
                self.process_index::self.process_count]
            if len(sel) == 0:
                continue
            yield _tree_map(lambda a: self._gather(a, sel), self.data)

    @staticmethod
    def _gather(a: np.ndarray, sel: np.ndarray) -> np.ndarray:
        """Row gather for one batch. Memmap tiers read in SORTED index order
        (page-cache friendly) then restore batch order; in-DRAM contiguous
        arrays route through the native threaded gather (zoo_native.cpp
        gather_rows — saturates DRAM bandwidth instead of numpy's
        single-threaded memcpy)."""
        if isinstance(a, np.memmap):
            order = np.argsort(sel, kind="stable")
            inv = np.empty_like(order)
            inv[order] = np.arange(len(order))
            return np.ascontiguousarray(a[sel[order]][inv])
        from ..native import gather_rows, native_available

        # native path only for contiguous non-object arrays: gather_rows would
        # otherwise copy the WHOLE source once per batch — and for object
        # dtypes it would memcpy PyObject pointers without increfs
        # (use-after-free once the batch is collected)
        if (native_available() and a.nbytes >= (1 << 20)
                and a.flags["C_CONTIGUOUS"] and not a.dtype.hasobject):
            return gather_rows(a, sel)
        return np.ascontiguousarray(a[sel])

    def row_slice(self, indices) -> ArrayTree:
        """Random-access row read: the rows at ``indices`` (any order, repeats
        allowed), in batch order, as plain in-DRAM arrays.

        On the memmap tiers (``DISK_AND_DRAM``/``PMEM``) this reads ONLY the
        requested rows — sorted-index order against the memmap, page-cache
        friendly — instead of materializing an epoch slice and copying whole
        row ranges. This is the miss path of the serving hot-row cache
        (:mod:`analytics_zoo_tpu.serving.rowcache`): a cache fill touches the
        bytes of the missed rows and nothing else. Bit-identical to gathering
        from the same data held in DRAM.
        """
        sel = np.asarray(indices)
        if sel.ndim != 1:
            raise ValueError(f"row_slice wants a 1-D index array, got "
                             f"shape {sel.shape}")
        if not np.issubdtype(sel.dtype, np.integer):
            raise ValueError(f"row_slice wants integer indices, got {sel.dtype}")
        if sel.size and (sel.min() < 0 or sel.max() >= self._n_total):
            raise IndexError(
                f"row_slice indices out of range [0, {self._n_total}): "
                f"min={sel.min()} max={sel.max()}")
        t0 = time.perf_counter()
        out = _tree_map(lambda a: self._gather(a, sel), self.data)
        _DATA_GATHER.observe(time.perf_counter() - t0)
        return out

    def slices(self, num_slices: Optional[int] = None) -> List["FeatureSet"]:
        """Epoch slicing: split into sub-epoch FeatureSets (DiskFeatureSet's
        DISK_AND_DRAM numSlice semantics, FeatureSet.scala:546)."""
        k = num_slices or self.num_slices
        out = []
        per = math.ceil(self._n_total / k)
        for i in range(k):
            sl = slice(i * per, min((i + 1) * per, self._n_total))
            out.append(FeatureSet(
                _tree_map(lambda a: np.asarray(a[sl]), self.data),
                process_index=self.process_index, process_count=self.process_count,
                seed=self.seed + 17 * (i + 1), host_shard=self.host_shard))
        return out

    def transform(self, fn) -> "FeatureSet":
        """Apply a preprocessing fn over the whole tree (ImageSet/TextSet transform
        chain parity — applied eagerly host-side).

        The cache tier SURVIVES: a transformed ``DISK_AND_DRAM``/``PMEM`` set
        re-memmaps the transformed tree into a fresh subdirectory of the
        original cache dir (same mount), instead of silently coming back as
        a plain DRAM set.
        """
        kw = {}
        if (self.memory_type.startswith("DISK_AND_DRAM")
                or self.memory_type == MemoryType.PMEM):
            kw = dict(memory_type=self.memory_type,
                      cache_dir=tempfile.mkdtemp(prefix="transform_",
                                                 dir=self._cache_dir))
        return FeatureSet(fn(self.data), process_index=self.process_index,
                          process_count=self.process_count, seed=self.seed,
                          host_shard=self.host_shard, **kw)


def device_prefetch(batch_iter: Iterator[ArrayTree], sharding=None, depth: int = 2):
    """Legacy alias — absorbed into :mod:`analytics_zoo_tpu.data.pipeline`
    (the PrefetchLoader runs the ``device_put`` on a producer THREAD instead
    of buffering futures on the consumer)."""
    from .pipeline import device_prefetch as _impl

    return _impl(batch_iter, sharding=sharding, depth=depth)


class BytesFeatureSet(FeatureSet):
    """Raw byte records + a per-record decoder, decoded at batch time only
    (``TFBytesDataset`` capability — tf_dataset.py:661). The stored tier is an
    object ndarray of ``bytes``; every FeatureSet facility (deterministic
    shuffle, multi-host strided sharding, epoch slicing of the RAW records)
    applies unchanged, and ``batches`` decodes just the gathered records."""

    def __init__(self, records: Sequence[bytes], decoder: Callable,
                 decode_workers: Optional[int] = None, **kw):
        arr = np.empty(len(records), dtype=object)
        arr[:] = list(records)
        kw.pop("memory_type", None)   # raw-object tier is DRAM by definition
        super().__init__((arr,), **kw)
        self.decoder = decoder
        # per-record decode parallelism: None = auto (min(8, cpu) or the
        # ZOO_TPU_DECODE_WORKERS override), 0/1 = in-line. Decoders are
        # numpy/PIL-heavy and release the GIL, so the shared zoo-decode pool
        # overlaps records of one batch while keeping output order exact.
        # CONTRACT: under auto, `decoder` must be thread-safe (a pure
        # per-record function — the jpeg/np.frombuffer shape). A decoder
        # that mutates shared state (scratch buffers, a shared tokenizer)
        # must pass decode_workers=0 to keep the old serial behavior.
        self.decode_workers = decode_workers

    def _iter_batches(self, batch_size: int, *, epoch: int = 0,
                      shuffle: bool = True,
                      drop_remainder: bool = True) -> Iterator[ArrayTree]:
        # decode INSIDE the parent's timing wrapper: batches() wraps this
        # iterator, so per-record decode lands in zoo_data_batch_gather_seconds
        # (and, itemized, in zoo_data_decode_seconds) instead of vanishing
        # from the DataWait story
        from .pipeline import decode_map

        for (raw,) in super()._iter_batches(batch_size, epoch=epoch,
                                            shuffle=shuffle,
                                            drop_remainder=drop_remainder):
            t0 = time.perf_counter()
            rows = decode_map(self.decoder, raw, self.decode_workers)
            first = rows[0]
            if isinstance(first, dict):
                out = {k: np.stack([r[k] for r in rows]) for k in first}
            elif isinstance(first, (tuple, list)):
                out = tuple(np.stack([r[i] for r in rows])
                            for i in range(len(first)))
            else:
                out = (np.stack(rows),)
            _DATA_DECODE.observe(time.perf_counter() - t0)
            yield out

    def slices(self, num_slices: Optional[int] = None) -> List["FeatureSet"]:
        """Sub-epoch slices of the RAW records — each slice keeps the decoder
        (a plain-FeatureSet slice would yield undecoded object arrays)."""
        k = num_slices or self.num_slices
        per = math.ceil(self._n_total / k)
        out = []
        for i in range(k):
            sl = slice(i * per, min((i + 1) * per, self._n_total))
            out.append(BytesFeatureSet(
                list(self.data[0][sl]), self.decoder,
                decode_workers=self.decode_workers,
                process_index=self.process_index,
                process_count=self.process_count,
                seed=self.seed + 17 * (i + 1), host_shard=self.host_shard))
        return out

    def transform(self, fn) -> "FeatureSet":
        """Transform the raw record array; the decoder rides along."""
        (arr,) = fn(self.data)
        return BytesFeatureSet(list(arr), self.decoder,
                               decode_workers=self.decode_workers,
                               process_index=self.process_index,
                               process_count=self.process_count, seed=self.seed,
                               host_shard=self.host_shard)
