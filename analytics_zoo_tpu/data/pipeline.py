"""Asynchronous input pipeline: background producers feeding a bounded queue.

The streaming train/eval/predict paths used to run the whole host side of a
step — row gather, ``BytesFeatureSet`` decode, and the host→HBM
``jax.device_put`` — inline on the consumer thread, between device steps
(``Estimator._run_epoch``'s old one-batch-lookahead generator).  That put the
host on the critical path: the per-step DataWaitMs the telemetry layer
reports *is* that inline work.  The reference system kept data next to
compute via Spark partition locality; the TPU-native equivalent is this
module — a producer thread that overlaps gather → decode → ``device_put``
with the device step, the overlap the TF input pipeline made canonical.

Components:

* :class:`PrefetchLoader` — the async loader.  One producer thread walks the
  underlying ``FeatureSet.batches`` iterator IN ORDER (so the batch stream is
  byte-identical to the synchronous path for a given ``(seed, epoch)``),
  applies an optional ``put_fn`` (the Estimator passes its batch-sharded
  ``device_put``), and feeds a bounded queue of ``depth`` batches.  ``depth=0``
  degrades to fully synchronous in-line production (the bench's control arm).
  Worker exceptions propagate to the consumer; ``close()`` (or the context
  manager / generator teardown) shuts the producer down promptly even when it
  is blocked on a full queue.
* :func:`decode_map` — ordered map over a process-wide pool of daemon
  ``zoo-decode-*`` threads; ``BytesFeatureSet`` routes per-record decode
  through it (numpy/PIL-heavy decoders release the GIL, so records of one
  batch decode in parallel while order stays deterministic).
* :func:`device_prefetch` — the old ``featureset.device_prefetch`` helper,
  absorbed as a thin wrapper over :class:`PrefetchLoader`.

Telemetry: ``zoo_data_prefetch_queue_depth`` (scrape-time gauge over live
loaders), ``zoo_data_prefetch_producer_stall_seconds`` (producer blocked on a
full queue — consumer is the bottleneck) and
``zoo_data_prefetch_consumer_wait_seconds`` (consumer blocked on an empty
queue — the producer-side remainder of the Estimator's DataWaitMs story).
Chaos site: ``data.prefetch`` fires once per produced batch, on the producer
thread, so fault drills exercise the cross-thread propagation path.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Iterator, Optional

from ..common.locks import traced_lock

from ..common import telemetry as _tm
from ..common.chaos import chaos_point

_STALL = _tm.histogram(
    "zoo_data_prefetch_producer_stall_seconds",
    "Producer time blocked on a full prefetch queue (consumer-bound pipeline)")
_WAIT = _tm.histogram(
    "zoo_data_prefetch_consumer_wait_seconds",
    "Consumer time blocked on an empty prefetch queue (producer-bound "
    "pipeline)")

# scrape-time queue-depth gauge over every live loader: depth > 0 at scrape
# means the producer is ahead (healthy); pinned at 0 means the consumer is
# starving and DataWaitMs is about to show it
_LIVE_LOADERS: "weakref.WeakSet[PrefetchLoader]" = weakref.WeakSet()


def _queue_depth_samples():
    return [((), float(sum(l.queue_depth() for l in list(_LIVE_LOADERS))))]


_tm.collector("zoo_data_prefetch_queue_depth",
              "Batches currently buffered across live PrefetchLoaders",
              _queue_depth_samples)


_END = object()           # producer sentinel: source exhausted


class _WorkerError:
    """Exception captured on the producer thread, re-raised at the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchLoader:
    """Bounded-queue async batch loader with a deterministic order contract.

    ``source`` is a FeatureSet (``batches(batch_size, epoch=…, shuffle=…,
    drop_remainder=…)`` is called lazily on the producer thread) or any
    iterable of already-built host batches.  ``put_fn`` runs on the producer
    thread per batch — the place for ``jax.device_put``/batch sharding so the
    HBM upload of batch N+1 overlaps the device step on batch N.

    Determinism: ONE producer walks the source iterator in order, and decode
    parallelism (``decode_map``) reassembles records in order, so the yielded
    stream is byte-identical to iterating the source synchronously.

    Shutdown: ``close()`` is idempotent and safe at any point — epoch end,
    consumer exception, SIGTERM teardown; a producer blocked on a full queue
    observes the stop flag within its put timeout and exits. Exceptions from
    the source iterator, ``put_fn``, or an installed chaos schedule
    (``data.prefetch``) surface at the consumer's next ``__next__``.
    """

    _ids = itertools.count()

    def __init__(self, source, batch_size: Optional[int] = None, *,
                 epoch: int = 0, shuffle: bool = True,
                 drop_remainder: bool = True,
                 put_fn: Optional[Callable[[Any], Any]] = None,
                 depth: int = 2):
        self._put = put_fn
        self.depth = max(0, int(depth))
        if hasattr(source, "batches"):
            if batch_size is None:
                raise TypeError("batch_size is required for FeatureSet sources")
            self._make_iter = lambda: source.batches(
                batch_size, epoch=epoch, shuffle=shuffle,
                drop_remainder=drop_remainder)
        else:
            src_iter = iter(source)
            self._make_iter = lambda: src_iter
        self._closed = False
        self._iterated = False
        if self.depth == 0:        # synchronous control path: no thread
            self._q = None
            self._thread = None
            return
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, name=f"zoo-prefetch-{next(self._ids)}",
            daemon=True)
        _LIVE_LOADERS.add(self)
        self._thread.start()

    # ------------------------------------------------------------- producer
    def _produce(self):
        try:
            for hb in self._make_iter():
                if self._stop.is_set():
                    return
                chaos_point("data.prefetch")
                item = self._put(hb) if self._put is not None else hb
                if not self._enqueue(item):
                    return
            self._enqueue(_END)
        except BaseException as e:  # incl. chaos WorkerKilled (BaseException)
            self._enqueue(_WorkerError(e))

    def _enqueue(self, item) -> bool:
        """Stop-aware bounded put; stall time (queue full) is recorded."""
        try:
            self._q.put_nowait(item)
            return True
        except queue.Full:
            pass
        t0 = time.perf_counter()
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                _STALL.observe(time.perf_counter() - t0)
                return True
            except queue.Full:
                continue
        return False

    # ------------------------------------------------------------- consumer
    def __iter__(self) -> Iterator[Any]:
        # SINGLE-PASS at every depth (the producer thread walks the source
        # once): construct a fresh loader per epoch, like the train loop does
        if self._iterated:
            raise RuntimeError(
                "PrefetchLoader is single-pass; construct a new loader per "
                "epoch instead of re-iterating this one")
        self._iterated = True
        if self._q is None:        # depth=0: produce in-line, same contract
            for hb in self._make_iter():
                chaos_point("data.prefetch")
                yield self._put(hb) if self._put is not None else hb
            return
        while True:
            t0 = time.perf_counter()
            while True:
                try:
                    item = self._q.get(timeout=0.5)
                    break
                except queue.Empty:
                    if self._closed:
                        return
                    if not self._thread.is_alive():
                        # the producer may have enqueued its final item and
                        # exited between our timeout and this check
                        try:
                            item = self._q.get_nowait()
                            break
                        except queue.Empty:
                            raise RuntimeError(
                                "prefetch producer died without a result "
                                "(thread %s)" % self._thread.name) from None
            _WAIT.observe(time.perf_counter() - t0)
            if item is _END:
                return
            if isinstance(item, _WorkerError):
                raise item.exc
            yield item

    # ------------------------------------------------------------ lifecycle
    def queue_depth(self) -> int:
        return self._q.qsize() if self._q is not None else 0

    def close(self, timeout: float = 5.0) -> None:
        """Idempotent teardown: stop the producer, drain the queue so a
        blocked put wakes up, and join the thread."""
        self._closed = True
        if self._q is None:
            return
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def __enter__(self) -> "PrefetchLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # safety net; the owning loop closes explicitly
        try:
            self.close(timeout=0.0)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# shared ordered decode pool (BytesFeatureSet per-record decode)
# ---------------------------------------------------------------------------

class _OrderedThreadPool:
    """Minimal shared thread pool whose ``map`` preserves input order.

    Deliberately NOT ``concurrent.futures.ThreadPoolExecutor``: its workers
    are non-daemon and would trip the session-end rogue-thread report in
    tests/conftest.py. These workers are daemon threads named ``zoo-decode-N``
    and live for the process (like BLAS pools) — they hold no state between
    calls.
    """

    def __init__(self, name: str = "zoo-decode"):
        self._name = name
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads: list = []
        # zoo-lock: guards(_threads)
        self._lock = traced_lock("_OrderedThreadPool._lock")

    def ensure_workers(self, n: int) -> None:
        with self._lock:
            while len(self._threads) < n:
                t = threading.Thread(
                    target=self._worker,
                    name=f"{self._name}-{len(self._threads)}", daemon=True)
                t.start()
                self._threads.append(t)

    def _worker(self):
        while True:
            fn, arg, i, results, state, cond = self._q.get()
            try:
                results[i] = fn(arg)
                exc = None
            except BaseException as e:  # re-raised in map(); worker survives
                exc = e
            with cond:
                if exc is not None and state["exc"] is None:
                    state["exc"] = exc
                state["left"] -= 1
                if not state["left"]:
                    cond.notify_all()

    def map(self, fn: Callable, items) -> list:
        n = len(items)
        results = [None] * n
        state = {"left": n, "exc": None}
        cond = threading.Condition()
        for i in range(n):
            self._q.put((fn, items[i], i, results, state, cond))
        with cond:
            while state["left"]:
                cond.wait()
        if state["exc"] is not None:
            raise state["exc"]
        return results


_DECODE_POOL = _OrderedThreadPool()


def default_decode_workers() -> int:
    """``ZOO_TPU_DECODE_WORKERS`` env override, else ``min(8, cpu_count)``."""
    env = os.environ.get("ZOO_TPU_DECODE_WORKERS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return min(8, os.cpu_count() or 1)


def decode_map(fn: Callable, items, workers: Optional[int] = None) -> list:
    """Ordered parallel map for per-record decoders.

    ``workers=None`` → :func:`default_decode_workers`; ``0``/``1`` (or a
    tiny batch) decodes in-line. Results always come back in input order, and
    the first decoder exception re-raises at the caller.

    The cap is enforced per CALL even though the pool is shared: the batch
    is split into at most ``workers`` contiguous chunk-tasks, so a caller
    asking for 2-way decode gets 2-way decode even when another featureset
    grew the pool to 8 threads.
    """
    n_workers = default_decode_workers() if workers is None else max(0, workers)
    if n_workers <= 1 or len(items) < 4:
        return [fn(x) for x in items]
    _DECODE_POOL.ensure_workers(n_workers)
    n = len(items)
    n_chunks = min(n_workers, n)
    bounds = [(i * n) // n_chunks for i in range(n_chunks + 1)]

    def run_chunk(span):
        lo, hi = span
        return [fn(items[i]) for i in range(lo, hi)]

    chunks = _DECODE_POOL.map(run_chunk, list(zip(bounds, bounds[1:])))
    return [r for chunk in chunks for r in chunk]


# ---------------------------------------------------------------------------
# legacy helper, absorbed (was data/featureset.py::device_prefetch)
# ---------------------------------------------------------------------------

def device_prefetch(batch_iter: Iterable, sharding=None, depth: int = 2):
    """Double-buffer host→device transfer (legacy API, now a thin wrapper
    over :class:`PrefetchLoader`): keep ``depth`` batches in flight, with the
    ``device_put`` running on the producer thread instead of the consumer."""
    import jax

    def put(b):
        from .featureset import _tree_map

        if sharding is None:
            return _tree_map(jax.device_put, b)
        return _tree_map(lambda a: jax.device_put(a, sharding), b)

    loader = PrefetchLoader(batch_iter, put_fn=put, depth=depth)
    try:
        yield from loader
    finally:
        loader.close()
