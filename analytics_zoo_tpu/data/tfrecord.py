"""TFRecord file reading/writing + tf.Example codec — no tensorflow needed.

Parity: the TFDataset family's TFRecord/bytes dataset variants
(``pyzoo/zoo/tfpark/tf_dataset.py:661-1131`` — ``TFBytesDataset``,
tfrecord-backed ``TFDataFeatureSet``). Redesign: records are decoded host-side
by this codec and land in a :class:`FeatureSet` (DRAM or disk tier) feeding the
device like any other tier — no TF runtime in the loop.

Wire formats:
* TFRecord framing: <len u64le><masked_crc32c(len) u32le><data><masked_crc32c
  (data) u32le> per record — the same codec ``common/summary.py`` writes TB
  event files with.
* tf.Example (``tensorflow/core/example/example.proto``):
  Example{features=1 Features{feature=1 map<string, Feature>}};
  Feature{bytes_list=1{value=1}, float_list=2{value=1 packed},
  int64_list=3{value=1 packed}}.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from ..common.summary import _masked_crc
from ..importers.onnx_proto import (_iter_fields, _ld, _read_varint, _s64,
                                    _vi)

# ----------------------------------------------------------------- record IO


def read_records(path: str, verify_crc: bool = False) -> Iterator[bytes]:
    """Iterate raw record payloads of one TFRecord file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            if verify_crc:
                (hcrc,) = struct.unpack("<I", header[8:12])
                if _masked_crc(header[:8]) != hcrc:
                    raise ValueError(f"{path}: corrupt record length CRC")
            data = f.read(length)
            footer = f.read(4)
            if len(data) < length or len(footer) < 4:
                raise ValueError(f"{path}: truncated record")
            if verify_crc:
                (dcrc,) = struct.unpack("<I", footer)
                if _masked_crc(data) != dcrc:
                    raise ValueError(f"{path}: corrupt record data CRC")
            yield data


def write_records(path: str, records: Iterable[bytes]) -> int:
    """Write raw payloads in TFRecord framing (readable by TF). Returns count."""
    n = 0
    with open(path, "wb") as f:
        for data in records:
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))
            n += 1
    return n


# ---------------------------------------------------------------- tf.Example


def decode_example(buf: bytes) -> Dict[str, np.ndarray]:
    """tf.Example bytes → {name: 1-D array} (bytes features → object array)."""
    out: Dict[str, np.ndarray] = {}
    for fnum, _wt, v in _iter_fields(buf):
        if fnum != 1:                      # Features
            continue
        for f2, _w2, v2 in _iter_fields(v):
            if f2 != 1:                    # map<string, Feature> entry
                continue
            name, feat = "", None
            for f3, _w3, v3 in _iter_fields(v2):
                if f3 == 1:
                    name = v3.decode()
                elif f3 == 2:
                    feat = v3
            if feat is None:
                continue
            out[name] = _decode_feature(feat)
    return out


def _decode_feature(buf: bytes) -> np.ndarray:
    for fnum, _wt, v in _iter_fields(buf):
        if fnum == 1:                      # BytesList
            vals = [v2 for f2, _w2, v2 in _iter_fields(v) if f2 == 1]
            return np.asarray(vals, dtype=object)
        if fnum == 2:                      # FloatList (packed or not)
            floats: List[float] = []
            for f2, w2, v2 in _iter_fields(v):
                if f2 == 1:
                    if w2 == 2:
                        floats.extend(
                            struct.unpack(f"<{len(v2) // 4}f", v2))
                    else:
                        floats.append(
                            struct.unpack("<f", struct.pack("<i", v2))[0])
            return np.asarray(floats, dtype=np.float32)
        if fnum == 3:                      # Int64List
            ints: List[int] = []
            for f2, w2, v2 in _iter_fields(v):
                if f2 == 1:
                    if w2 == 2:
                        p = 0
                        while p < len(v2):
                            d, p = _read_varint(v2, p)
                            ints.append(_s64(d))
                    else:
                        ints.append(_s64(v2))
            return np.asarray(ints, dtype=np.int64)
    return np.asarray([], dtype=np.float32)


def encode_example(features: Dict[str, Union[np.ndarray, Sequence]]) -> bytes:
    """{name: array/list} → tf.Example bytes (float32→float_list,
    int→int64_list, bytes/str→bytes_list)."""
    entries = b""
    for name, value in features.items():
        if isinstance(value, (bytes, str)):
            value = [value]
        arr = (value if isinstance(value, (list, tuple))
               else np.asarray(value).reshape(-1))
        if len(arr) and isinstance(arr[0], (bytes, str)):
            vals = b"".join(_ld(1, v.encode() if isinstance(v, str) else v)
                            for v in arr)
            feat = _ld(1, vals)
        elif np.asarray(arr).dtype.kind in "iub":
            vals = b"".join(_vi(1, int(v) & ((1 << 64) - 1)) for v in arr)
            feat = _ld(3, vals)
        else:
            packed = struct.pack(f"<{len(arr)}f",
                                 *[float(v) for v in arr])
            feat = _ld(2, _ld(1, packed))
        entries += _ld(1, _ld(1, name.encode()) + _ld(2, feat))
    return _ld(1, entries)


# ------------------------------------------------------------------ dataset


def read_tfrecord_examples(paths: Union[str, Sequence[str]],
                           max_records: Optional[int] = None,
                           verify_crc: bool = False
                           ) -> Dict[str, np.ndarray]:
    """Read tf.Example TFRecord file(s) → {feature: stacked array}.

    Fixed-length features stack to (N, ...); ragged features raise with a
    clear message (pad upstream or read record-wise via ``read_records``).
    """
    if isinstance(paths, str):
        paths = [paths]
    rows: List[Dict[str, np.ndarray]] = []
    for p in paths:
        for rec in read_records(p, verify_crc=verify_crc):
            rows.append(decode_example(rec))
            if max_records is not None and len(rows) >= max_records:
                break
        if max_records is not None and len(rows) >= max_records:
            break
    if not rows:
        raise ValueError(f"no records in {paths}")
    names = set()
    for r in rows:
        names.update(r)                   # schema = union over ALL records
    out: Dict[str, np.ndarray] = {}
    for name in sorted(names):
        vals = []
        for i, r in enumerate(rows):
            if name not in r:
                raise ValueError(
                    f"feature {name!r} missing from record {i} — optional "
                    "features need a default; iterate read_records/"
                    "decode_example to handle them record-wise")
            vals.append(r[name])
        lens = {len(v) for v in vals}
        if len(lens) != 1:
            raise ValueError(
                f"feature {name!r} is ragged (lengths {sorted(lens)[:5]}...) "
                "— pad upstream or iterate read_records/decode_example")
        # width-1 features keep their axis: (N, 1); FeatureSet.from_tfrecord
        # squeezes LABEL columns only (same contract as from_dataframe)
        out[name] = np.stack(vals)
    return out
