"""XShards — partitioned collections of numpy/pandas data.

Parity: /root/reference/pyzoo/zoo/orca/data/shard.py:23-368 (``XShards``,
``SparkXShards``, ``RayXShards``) — partitioned pandas/numpy over Spark or Ray,
with parquet/csv/json readers. Here a shard is simply a host-side partition list
(the "cluster" being the process set of a multi-host TPU job); ``transform_shard``
maps a function over partitions, and ``collect_tree``/``to_featureset`` hand the
data to the training engine.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np


def _split_df(df, num_partitions: int) -> List[Any]:
    """Row-split a DataFrame into partitions with clean local indices."""
    idx = np.array_split(np.arange(len(df)), num_partitions)
    return [df.iloc[i].reset_index(drop=True) for i in idx]


class XShards:
    """A list of partitions, each an arbitrary python object (dict of ndarrays,
    pandas DataFrame, ...)."""

    def __init__(self, partitions: Sequence[Any]):
        self._parts: List[Any] = list(partitions)

    # ------------------------------------------------------------ constructors
    @classmethod
    def partition(cls, data, num_partitions: int = 4) -> "XShards":
        """Split ndarray/dict-of-ndarray into shards (orca ``XShards.partition``)."""
        if isinstance(data, dict):
            keys = list(data)
            n = len(data[keys[0]])
            splits = np.array_split(np.arange(n), num_partitions)
            return cls([{k: np.asarray(data[k])[idx] for k in keys} for idx in splits])
        if hasattr(data, "iloc"):  # pandas DataFrame/Series: keep columns
            return cls(_split_df(data, num_partitions))
        arr = np.asarray(data)
        return cls([np.ascontiguousarray(p) for p in np.array_split(arr, num_partitions)])

    @classmethod
    def read_csv(cls, path: str, num_partitions: int = 4, **kw) -> "XShards":
        """CSV reader → pandas shards (orca ``read_csv`` parity)."""
        import pandas as pd

        files = sorted(_glob.glob(path)) if any(c in path for c in "*?[") else [path]
        frames = [pd.read_csv(f, **kw) for f in files]
        df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        return cls(_split_df(df, num_partitions))

    @classmethod
    def read_json(cls, path: str, num_partitions: int = 4, **kw) -> "XShards":
        import pandas as pd

        files = sorted(_glob.glob(path)) if any(c in path for c in "*?[") else [path]
        frames = [pd.read_json(f, **kw) for f in files]
        df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        return cls(_split_df(df, num_partitions))

    @classmethod
    def read_parquet(cls, path: str, num_partitions: int = 4, **kw) -> "XShards":
        import pandas as pd

        df = pd.read_parquet(path, **kw)
        return cls(_split_df(df, num_partitions))

    # ------------------------------------------------------------------ ops
    def transform_shard(self, fn: Callable, *args) -> "XShards":
        """Apply ``fn`` to every partition (shard.py ``transform_shard`` parity)."""
        return XShards([fn(p, *args) for p in self._parts])

    def collect(self) -> List[Any]:
        return list(self._parts)

    def num_partitions(self) -> int:
        return len(self._parts)

    def repartition(self, num_partitions: int) -> "XShards":
        flat = self.collect_tree()
        return XShards.partition(flat, num_partitions)

    def __len__(self) -> int:
        first = self._parts[0]
        if isinstance(first, dict):
            k = next(iter(first))
            return sum(len(p[k]) for p in self._parts)
        return sum(len(p) for p in self._parts)

    # -------------------------------------------------------------- conversion
    def collect_tree(self):
        """Concatenate partitions into one array tree (feeds FeatureSet)."""
        first = self._parts[0]
        if isinstance(first, dict):
            return {k: np.concatenate([np.asarray(p[k]) for p in self._parts])
                    for k in first}
        if hasattr(first, "values") and hasattr(first, "columns"):  # DataFrame
            import pandas as pd

            return pd.concat(self._parts, ignore_index=True)
        return np.concatenate([np.asarray(p) for p in self._parts])

    def to_featureset(self, feature_cols: Optional[Sequence[str]] = None,
                      label_cols: Optional[Sequence[str]] = None, **kw):
        """Build a FeatureSet; for DataFrame shards select feature/label columns
        (the NNEstimator fit(df, feature_cols, label_cols) capability)."""
        from .featureset import FeatureSet

        tree = self.collect_tree()
        if feature_cols is not None:
            x = np.stack([np.asarray(tree[c]) for c in feature_cols], axis=-1)
            if label_cols:
                y = np.stack([np.asarray(tree[c]) for c in label_cols], axis=-1)
                if y.shape[-1] == 1:
                    y = y[..., 0]
                return FeatureSet((x, y), **kw)
            return FeatureSet((x,), **kw)
        return FeatureSet(tree if isinstance(tree, tuple) else (tree,), **kw)
