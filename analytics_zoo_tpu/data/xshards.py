"""XShards — partitioned collections of numpy/pandas data.

Parity: /root/reference/pyzoo/zoo/orca/data/shard.py:23-368 (``XShards``,
``SparkXShards``, ``RayXShards``) — partitioned pandas/numpy over Spark or Ray,
with parquet/csv/json readers. Here a shard is simply a host-side partition list
(the "cluster" being the process set of a multi-host TPU job); ``transform_shard``
maps a function over partitions, and ``collect_tree``/``to_featureset`` hand the
data to the training engine.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np


def _split_df(df, num_partitions: int) -> List[Any]:
    """Row-split a DataFrame into partitions with clean local indices."""
    idx = np.array_split(np.arange(len(df)), num_partitions)
    return [df.iloc[i].reset_index(drop=True) for i in idx]


class XShards:
    """A list of partitions, each an arbitrary python object (dict of ndarrays,
    pandas DataFrame, ...).

    Transforms can be **lazy** (``transform_shard(fn, lazy=True)`` records the
    fn; the chain runs on first materialization — SparkXShards' deferred DAG
    semantics) and **parallel** (``parallel_apply`` fans partitions out over an
    ``orca.TaskPool`` of worker processes — the Spark-executor role)."""

    def __init__(self, partitions: Sequence[Any],
                 pending: Sequence[Callable] = ()):
        self._parts: List[Any] = list(partitions)
        self._pending: List[Callable] = list(pending)

    # ------------------------------------------------------------ constructors
    @classmethod
    def partition(cls, data, num_partitions: int = 4) -> "XShards":
        """Split ndarray/dict-of-ndarray into shards (orca ``XShards.partition``)."""
        if isinstance(data, dict):
            keys = list(data)
            n = len(data[keys[0]])
            splits = np.array_split(np.arange(n), num_partitions)
            return cls([{k: np.asarray(data[k])[idx] for k in keys} for idx in splits])
        if hasattr(data, "iloc"):  # pandas DataFrame/Series: keep columns
            return cls(_split_df(data, num_partitions))
        arr = np.asarray(data)
        return cls([np.ascontiguousarray(p) for p in np.array_split(arr, num_partitions)])

    @classmethod
    def read_csv(cls, path: str, num_partitions: int = 4, **kw) -> "XShards":
        """CSV reader → pandas shards (orca ``read_csv`` parity)."""
        import pandas as pd

        files = sorted(_glob.glob(path)) if any(c in path for c in "*?[") else [path]
        frames = [pd.read_csv(f, **kw) for f in files]
        df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        return cls(_split_df(df, num_partitions))

    @classmethod
    def read_json(cls, path: str, num_partitions: int = 4, **kw) -> "XShards":
        import pandas as pd

        files = sorted(_glob.glob(path)) if any(c in path for c in "*?[") else [path]
        frames = [pd.read_json(f, **kw) for f in files]
        df = pd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
        return cls(_split_df(df, num_partitions))

    @classmethod
    def read_parquet(cls, path: str, num_partitions: int = 4, **kw) -> "XShards":
        import pandas as pd

        df = pd.read_parquet(path, **kw)
        return cls(_split_df(df, num_partitions))

    # ------------------------------------------------------------------ ops
    def transform_shard(self, fn: Callable, *args,
                        lazy: bool = False) -> "XShards":
        """Apply ``fn`` to every partition (shard.py ``transform_shard``
        parity). ``lazy=True`` defers execution until materialization
        (collect/len/conversion) so chained transforms traverse each
        partition once."""
        if lazy:
            return XShards(self._parts,
                           pending=self._pending + [lambda p: fn(p, *args)])
        return XShards([fn(self._materialize_one(p), *args)
                        for p in self._parts])

    def parallel_apply(self, fn: Callable, *args, num_workers: int = 4,
                       pool=None) -> "XShards":
        """Apply ``fn`` to partitions in parallel worker PROCESSES (the role
        Spark executors play for SparkXShards). Any pending lazy chain runs
        inside the workers too. Pass ``pool`` to reuse a live
        ``orca.TaskPool``; otherwise a temporary one is spawned."""
        from ..orca.task_pool import TaskPool

        chain = list(self._pending)

        def run(part):
            for g in chain:
                part = g(part)
            return fn(part, *args)

        if pool is not None:
            return XShards(pool.map(run, self._parts))
        with TaskPool(min(num_workers, max(1, len(self._parts)))) as p:
            return XShards(p.map(run, self._parts))

    def _materialize_one(self, part):
        for g in self._pending:
            part = g(part)
        return part

    def cache(self) -> "XShards":
        """Run any pending lazy chain now, in place (persist() analog)."""
        self._parts = [self._materialize_one(p) for p in self._parts]
        self._pending = []
        return self

    def collect(self) -> List[Any]:
        # materialize IN PLACE: a len()/collect() pair must not run the lazy
        # chain over every partition twice
        self.cache()
        return list(self._parts)

    def num_partitions(self) -> int:
        return len(self._parts)

    def repartition(self, num_partitions: int) -> "XShards":
        flat = self.collect_tree()
        return XShards.partition(flat, num_partitions)

    def __len__(self) -> int:
        parts = self.collect()
        first = parts[0]
        if isinstance(first, dict):
            k = next(iter(first))
            return sum(len(p[k]) for p in parts)
        return sum(len(p) for p in parts)

    # -------------------------------------------------------------- conversion
    def collect_tree(self):
        """Concatenate partitions into one array tree (feeds FeatureSet)."""
        parts = self.collect()
        first = parts[0]
        if isinstance(first, dict):
            return {k: np.concatenate([np.asarray(p[k]) for p in parts])
                    for k in first}
        if hasattr(first, "values") and hasattr(first, "columns"):  # DataFrame
            import pandas as pd

            return pd.concat(parts, ignore_index=True)
        return np.concatenate([np.asarray(p) for p in parts])

    def host_split(self, process_index: int, process_count: int) -> "XShards":
        """This host's partitions of a multi-host job (partition i belongs to
        host ``i % process_count`` — Spark partition placement analog). Feed
        the result to ``FeatureSet.from_host_shard`` so each host ingests only
        its own slice instead of materializing the global dataset."""
        return XShards(self._parts[process_index::process_count],
                       pending=self._pending)

    def to_featureset(self, feature_cols: Optional[Sequence[str]] = None,
                      label_cols: Optional[Sequence[str]] = None, **kw):
        """Build a FeatureSet; for DataFrame shards select feature/label columns
        (the NNEstimator fit(df, feature_cols, label_cols) capability)."""
        from .featureset import FeatureSet

        tree = self.collect_tree()
        if feature_cols is not None:
            x = np.stack([np.asarray(tree[c]) for c in feature_cols], axis=-1)
            if label_cols:
                y = np.stack([np.asarray(tree[c]) for c in label_cols], axis=-1)
                if y.shape[-1] == 1:
                    y = y[..., 0]
                return FeatureSet((x, y), **kw)
            return FeatureSet((x,), **kw)
        return FeatureSet(tree if isinstance(tree, tuple) else (tree,), **kw)
