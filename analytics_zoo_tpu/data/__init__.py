"""Data layer: FeatureSet cache tiers, XShards, image/text pipelines."""

from .featureset import FeatureSet, MemoryType, device_prefetch

__all__ = ["FeatureSet", "MemoryType", "device_prefetch"]
