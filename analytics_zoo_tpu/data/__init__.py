"""Data layer: FeatureSet cache tiers, async input pipeline, XShards,
image/text pipelines."""

from .featureset import FeatureSet, MemoryType
from .image import ImageFeature, ImageSet
from .pipeline import PrefetchLoader, decode_map, device_prefetch
from .text import Relation, TextFeature, TextSet

__all__ = ["FeatureSet", "ImageFeature", "ImageSet", "MemoryType",
           "PrefetchLoader", "Relation", "TextFeature", "TextSet",
           "decode_map", "device_prefetch"]
