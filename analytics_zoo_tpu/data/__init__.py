"""Data layer: FeatureSet cache tiers, XShards, image/text pipelines."""

from .featureset import FeatureSet, MemoryType, device_prefetch
from .image import ImageFeature, ImageSet
from .text import Relation, TextFeature, TextSet

__all__ = ["FeatureSet", "ImageFeature", "ImageSet", "MemoryType", "Relation",
           "TextFeature", "TextSet", "device_prefetch"]
