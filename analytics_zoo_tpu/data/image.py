"""Image pipeline: ImageFeature / ImageSet + chained ImageProcessing transforms.

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/feature/image/
(33 files: ImageSet.scala, ImageProcessing.scala, ImageBrightness/Contrast/Hue/
Saturation/ChannelNormalize/ChannelOrder/Resize/AspectScale/CenterCrop/RandomCrop/
FixedCrop/Expand/Filler/HFlip/ColorJitter/PixelNormalizer/RandomResize/
MatToTensor/ImageSetToSample ...) and the python mirror pyzoo/zoo/feature/image/.

TPU-native design: the reference chains OpenCV JNI stages over Spark-distributed
``OpenCVMat``s; here every stage is a pure numpy function over an HWC float32 RGB
array — host-side preprocessing that terminates in dense ``(N, H, W, C)`` NHWC
batches (the layout `jax.lax.conv_general_dilated` consumes directly). Randomness
is explicit: each ImageSet carries a seeded generator, so multi-host pipelines stay
reproducible per shard.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class ImageFeature:
    """One image record (ImageFeature.scala parity): HWC float32 RGB ``image``,
    optional ``label``/``uri``; transform outputs accumulate as keys."""

    def __init__(self, image: Optional[np.ndarray] = None,
                 label: Optional[int] = None, uri: Optional[str] = None):
        self._d: Dict = {}
        if image is not None:
            self._d["image"] = np.asarray(image, dtype="float32")
        if label is not None:
            self._d["label"] = label
        if uri is not None:
            self._d["uri"] = uri

    def get_image(self) -> np.ndarray:
        return self._d["image"]

    def set_image(self, img: np.ndarray) -> "ImageFeature":
        self._d["image"] = np.asarray(img, dtype="float32")
        return self

    def get_label(self):
        return self._d.get("label", -1)

    def get_uri(self):
        return self._d.get("uri")

    def __getitem__(self, k):
        return self._d[k]

    def __setitem__(self, k, v):
        self._d[k] = v

    def __contains__(self, k):
        return k in self._d

    def keys(self):
        return list(self._d.keys())

    def copy(self) -> "ImageFeature":
        out = ImageFeature()
        out._d = dict(self._d)
        return out


# ----------------------------------------------------------------- processing base


class ImageProcessing:
    """One pipeline stage (ImageProcessing.scala parity). Stages operate on the
    HWC array; chain with ``>>``. Random stages draw from the rng handed in by
    ImageSet.transform for reproducibility."""

    def apply_image(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError

    def transform(self, feature: ImageFeature,
                  rng: np.random.Generator) -> ImageFeature:
        return feature.set_image(self.apply_image(feature.get_image(), rng))

    def __rshift__(self, other: "ImageProcessing") -> "ChainedImageProcessing":
        return ChainedImageProcessing([self, other])


class ChainedImageProcessing(ImageProcessing):
    def __init__(self, stages: Sequence[ImageProcessing]):
        self.stages = list(stages)

    def transform(self, feature, rng):
        for s in self.stages:
            feature = s.transform(feature, rng)
        return feature

    def __rshift__(self, other):
        return ChainedImageProcessing(self.stages + [other])


# -------------------------------------------------------------- geometry stages


class ImageResize(ImageProcessing):
    """Bilinear resize to (resize_h, resize_w) (ImageResize.scala parity)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = int(resize_h), int(resize_w)

    def apply_image(self, img, rng):
        return _bilinear_resize(img, self.h, self.w)


class ImageAspectScale(ImageProcessing):
    """Scale the short side to ``min_size``, cap the long side at ``max_size``
    (ImageAspectScale.scala parity)."""

    def __init__(self, min_size: int, max_size: int = 1000, scale_multiple_of: int = 1):
        self.min_size, self.max_size = int(min_size), int(max_size)
        self.multiple = int(scale_multiple_of)

    def apply_image(self, img, rng):
        h, w = img.shape[:2]
        short, long = min(h, w), max(h, w)
        scale = self.min_size / short
        if long * scale > self.max_size:
            scale = self.max_size / long
        nh, nw = int(round(h * scale)), int(round(w * scale))
        if self.multiple > 1:
            nh = max(self.multiple, nh // self.multiple * self.multiple)
            nw = max(self.multiple, nw // self.multiple * self.multiple)
        return _bilinear_resize(img, nh, nw)


class ImageRandomResize(ImageProcessing):
    """Resize to a random size in [min, max] (ImageRandomResize.scala)."""

    def __init__(self, min_size: int, max_size: int):
        self.min_size, self.max_size = int(min_size), int(max_size)

    def apply_image(self, img, rng):
        s = int(rng.integers(self.min_size, self.max_size + 1))
        return _bilinear_resize(img, s, s)


class ImageCenterCrop(ImageProcessing):
    def __init__(self, crop_height: int, crop_width: int):
        self.ch, self.cw = int(crop_height), int(crop_width)

    def apply_image(self, img, rng):
        h, w = img.shape[:2]
        y0, x0 = (h - self.ch) // 2, (w - self.cw) // 2
        return img[y0:y0 + self.ch, x0:x0 + self.cw]


class ImageRandomCrop(ImageProcessing):
    def __init__(self, crop_height: int, crop_width: int):
        self.ch, self.cw = int(crop_height), int(crop_width)

    def apply_image(self, img, rng):
        h, w = img.shape[:2]
        y0 = int(rng.integers(0, h - self.ch + 1))
        x0 = int(rng.integers(0, w - self.cw + 1))
        return img[y0:y0 + self.ch, x0:x0 + self.cw]


class ImageFixedCrop(ImageProcessing):
    """Crop a fixed region; normalized coords if ``normalized`` (ImageFixedCrop)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def apply_image(self, img, rng):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        return img[int(y1):int(y2), int(x1):int(x2)]


class ImageExpand(ImageProcessing):
    """Randomly pad the image into a larger canvas (ImageExpand.scala — SSD aug)."""

    def __init__(self, means_r=123, means_g=117, means_b=104,
                 max_expand_ratio: float = 4.0):
        self.means = np.asarray([means_r, means_g, means_b], dtype="float32")
        self.max_ratio = float(max_expand_ratio)

    def apply_image(self, img, rng):
        ratio = float(rng.uniform(1.0, self.max_ratio))
        h, w, c = img.shape
        nh, nw = int(h * ratio), int(w * ratio)
        out = np.broadcast_to(self.means, (nh, nw, c)).copy()
        y0 = int(rng.integers(0, nh - h + 1))
        x0 = int(rng.integers(0, nw - w + 1))
        out[y0:y0 + h, x0:x0 + w] = img
        return out


class ImageFiller(ImageProcessing):
    """Fill a (normalized) region with ``value`` (ImageFiller.scala)."""

    def __init__(self, start_x: float, start_y: float, end_x: float, end_y: float,
                 value: int = 255):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = float(value)

    def apply_image(self, img, rng):
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        img = img.copy()
        img[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        return img


class ImageHFlip(ImageProcessing):
    def apply_image(self, img, rng):
        return img[:, ::-1]


class ImageRandomPreprocessing(ImageProcessing):
    """Apply an inner stage with probability ``prob``
    (ImageRandomPreprocessing.scala parity — used for random flips etc.)."""

    def __init__(self, inner: ImageProcessing, prob: float = 0.5):
        self.inner = inner
        self.prob = float(prob)

    def transform(self, feature, rng):
        if rng.uniform() < self.prob:
            return self.inner.transform(feature, rng)
        return feature


# ----------------------------------------------------------------- color stages


class ImageBrightness(ImageProcessing):
    """Add a random delta in [delta_low, delta_high] (ImageBrightness.scala)."""

    def __init__(self, delta_low: float = -32.0, delta_high: float = 32.0):
        self.lo, self.hi = float(delta_low), float(delta_high)

    def apply_image(self, img, rng):
        return img + float(rng.uniform(self.lo, self.hi))


class ImageContrast(ImageProcessing):
    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5):
        self.lo, self.hi = float(delta_low), float(delta_high)

    def apply_image(self, img, rng):
        return img * float(rng.uniform(self.lo, self.hi))


class ImageSaturation(ImageProcessing):
    def __init__(self, delta_low: float = 0.5, delta_high: float = 1.5):
        self.lo, self.hi = float(delta_low), float(delta_high)

    def apply_image(self, img, rng):
        factor = float(rng.uniform(self.lo, self.hi))
        gray = img.mean(axis=-1, keepdims=True)
        return gray + (img - gray) * factor


class ImageHue(ImageProcessing):
    """Rotate hue by a random angle in degrees (ImageHue.scala)."""

    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0):
        self.lo, self.hi = float(delta_low), float(delta_high)

    def apply_image(self, img, rng):
        theta = np.deg2rad(float(rng.uniform(self.lo, self.hi)))
        # rotate around the RGB diagonal (YIQ-space hue rotation, float math)
        u, w_ = np.cos(theta), np.sin(theta)
        m = np.array([
            [0.299 + 0.701 * u + 0.168 * w_, 0.587 - 0.587 * u + 0.330 * w_,
             0.114 - 0.114 * u - 0.497 * w_],
            [0.299 - 0.299 * u - 0.328 * w_, 0.587 + 0.413 * u + 0.035 * w_,
             0.114 - 0.114 * u + 0.292 * w_],
            [0.299 - 0.300 * u + 1.250 * w_, 0.587 - 0.588 * u - 1.050 * w_,
             0.114 + 0.886 * u - 0.203 * w_]], dtype="float32")
        return img @ m.T


class ImageColorJitter(ImageProcessing):
    """Random brightness/contrast/saturation in random order
    (ImageColorJitter.scala parity)."""

    def __init__(self, brightness_prob=0.5, brightness_delta=32.0,
                 contrast_prob=0.5, contrast_lower=0.5, contrast_upper=1.5,
                 saturation_prob=0.5, saturation_lower=0.5, saturation_upper=1.5,
                 hue_prob=0.5, hue_delta=18.0):
        self.stages = [
            (brightness_prob, ImageBrightness(-brightness_delta, brightness_delta)),
            (contrast_prob, ImageContrast(contrast_lower, contrast_upper)),
            (saturation_prob, ImageSaturation(saturation_lower, saturation_upper)),
            (hue_prob, ImageHue(-hue_delta, hue_delta)),
        ]

    def apply_image(self, img, rng):
        order = rng.permutation(len(self.stages))
        for i in order:
            prob, stage = self.stages[i]
            if rng.uniform() < prob:
                img = stage.apply_image(img, rng)
        return img


class ImageChannelNormalize(ImageProcessing):
    """(img - mean) / std per channel (ImageChannelNormalize.scala)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0, std_b: float = 1.0):
        self.mean = np.asarray([mean_r, mean_g, mean_b], dtype="float32")
        self.std = np.asarray([std_r, std_g, std_b], dtype="float32")

    def apply_image(self, img, rng):
        return (img - self.mean) / self.std


class ImagePixelNormalizer(ImageProcessing):
    """Subtract a per-pixel mean image (ImagePixelNormalizer.scala)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, dtype="float32")

    def apply_image(self, img, rng):
        return img - self.means


class ImageChannelOrder(ImageProcessing):
    """Swap RGB ↔ BGR (ImageChannelOrder.scala)."""

    def apply_image(self, img, rng):
        return img[..., ::-1]


class ImageMatToTensor(ImageProcessing):
    """Finalize layout (ImageMatToTensor.scala): NHWC is the TPU-native default;
    ``format="NCHW"`` available for checkpoint-porting workflows."""

    def __init__(self, format: str = "NHWC"):
        assert format in ("NHWC", "NCHW")
        self.format = format

    def apply_image(self, img, rng):
        return np.transpose(img, (2, 0, 1)) if self.format == "NCHW" else img


class ImageSetToSample(ImageProcessing):
    """Attach (image, label) sample arrays (ImageSetToSample.scala)."""

    def transform(self, feature, rng):
        feature["sample"] = (feature.get_image(),
                             np.asarray(feature.get_label()))
        return feature


def _bilinear_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Pure-numpy bilinear resize (no OpenCV JNI — vectorized gather math)."""
    h, w = img.shape[:2]
    if (h, w) == (out_h, out_w):
        return img
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    r0, r1 = img[y0], img[y1]
    top = r0[:, x0] * (1 - wx) + r0[:, x1] * wx
    bot = r1[:, x0] * (1 - wx) + r1[:, x1] * wx
    return (top * (1 - wy) + bot * wy).astype("float32")


# ---------------------------------------------------------------------- ImageSet


class ImageSet:
    """Collection of ImageFeatures with chained transforms (ImageSet.scala).

    ``read`` decodes with PIL (host side); the terminal ``to_arrays`` emits the
    dense NHWC batch for the device."""

    def __init__(self, features: Sequence[ImageFeature], seed: int = 0):
        self.features: List[ImageFeature] = list(features)
        self.seed = seed

    @classmethod
    def from_arrays(cls, images: np.ndarray, labels: Optional[Sequence] = None,
                    seed: int = 0) -> "ImageSet":
        labels = labels if labels is not None else [None] * len(images)
        return cls([ImageFeature(im, l) for im, l in zip(images, labels)],
                   seed=seed)

    @classmethod
    def read(cls, path: str, with_label: bool = False) -> "ImageSet":
        """Read image files; with_label: ``<category>/<file>`` dirs map to labels
        (ImageSet.read parity)."""
        from PIL import Image

        feats = []
        if with_label:
            cats = [c for c in sorted(os.listdir(path))
                    if os.path.isdir(os.path.join(path, c))]
            for label, cat in enumerate(cats):
                cat_dir = os.path.join(path, cat)
                for fn in sorted(os.listdir(cat_dir)):
                    img = np.asarray(Image.open(os.path.join(cat_dir, fn))
                                     .convert("RGB"), dtype="float32")
                    feats.append(ImageFeature(img, label, uri=os.path.join(cat, fn)))
        else:
            names = ([path] if os.path.isfile(path) else
                     [os.path.join(path, f) for f in sorted(os.listdir(path))])
            for fn in names:
                img = np.asarray(Image.open(fn).convert("RGB"), dtype="float32")
                feats.append(ImageFeature(img, uri=fn))
        return cls(feats)

    def transform(self, stage: ImageProcessing) -> "ImageSet":
        """Returns a NEW ImageSet; source features are never mutated (matching
        the reference's immutable RDD-map semantics)."""
        rng = np.random.default_rng(self.seed)
        return ImageSet([stage.transform(f.copy(), rng) for f in self.features],
                        seed=self.seed + 1)

    def get_images(self) -> List[np.ndarray]:
        return [f.get_image() for f in self.features]

    def get_labels(self) -> List:
        return [f.get_label() for f in self.features]

    def to_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        xs = np.stack([f.get_image() for f in self.features])
        ys = np.asarray([f.get_label() for f in self.features])
        return xs, ys

    def __len__(self):
        return len(self.features)


# ------------------------------------------------------------------- 3D variants


class ImageProcessing3D(ImageProcessing):
    """Base for volumetric (D, H, W) transforms (feature/image3d/ parity)."""


class Crop3D(ImageProcessing3D):
    """Crop a (D, H, W) patch at ``start`` (image3d/Cropper.scala parity)."""

    def __init__(self, start: Sequence[int], patch_size: Sequence[int]):
        self.start = tuple(int(s) for s in start)
        self.patch = tuple(int(p) for p in patch_size)

    def apply_image(self, vol, rng):
        z, y, x = self.start
        d, h, w = self.patch
        return vol[z:z + d, y:y + h, x:x + w]


class RandomCrop3D(ImageProcessing3D):
    def __init__(self, patch_size: Sequence[int]):
        self.patch = tuple(int(p) for p in patch_size)

    def apply_image(self, vol, rng):
        d, h, w = self.patch
        z = int(rng.integers(0, vol.shape[0] - d + 1))
        y = int(rng.integers(0, vol.shape[1] - h + 1))
        x = int(rng.integers(0, vol.shape[2] - w + 1))
        return vol[z:z + d, y:y + h, x:x + w]


class Rotate3D(ImageProcessing3D):
    """Rotate by Euler angles (yaw, pitch, roll) radians
    (image3d/Rotation.scala parity; scipy affine on host)."""

    def __init__(self, rotation_angles: Sequence[float]):
        self.angles = tuple(float(a) for a in rotation_angles)

    def apply_image(self, vol, rng):
        from scipy.ndimage import affine_transform

        a, b, c = self.angles
        rz = np.array([[np.cos(a), -np.sin(a), 0], [np.sin(a), np.cos(a), 0],
                       [0, 0, 1]])
        ry = np.array([[np.cos(b), 0, np.sin(b)], [0, 1, 0],
                       [-np.sin(b), 0, np.cos(b)]])
        rx = np.array([[1, 0, 0], [0, np.cos(c), -np.sin(c)],
                       [0, np.sin(c), np.cos(c)]])
        m = rz @ ry @ rx
        center = (np.asarray(vol.shape) - 1) / 2
        offset = center - m @ center
        return affine_transform(vol, m, offset=offset, order=1).astype("float32")


class AffineTransform3D(ImageProcessing3D):
    """General 3×3 affine + translation (image3d/AffineTransform.scala parity)."""

    def __init__(self, mat: np.ndarray, translation: Optional[np.ndarray] = None):
        self.mat = np.asarray(mat, dtype="float64")
        self.translation = (np.zeros(3) if translation is None
                            else np.asarray(translation, dtype="float64"))

    def apply_image(self, vol, rng):
        from scipy.ndimage import affine_transform

        center = (np.asarray(vol.shape) - 1) / 2
        offset = center - self.mat @ center - self.translation
        return affine_transform(vol, self.mat, offset=offset,
                                order=1).astype("float32")
