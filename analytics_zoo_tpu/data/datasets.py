"""Built-in dataset utilities (MovieLens for the NCF north-star workload).

Parity: the reference's movielens loader
(/root/reference/pyzoo/zoo/examples/textclassification uses news20; the NCF app
apps/recommendation-ncf/ncf-explicit-feedback.ipynb loads MovieLens-1M ratings.dat).
This environment has no network egress, so ``movielens_1m`` reads a local
``ratings.dat`` when present and otherwise generates a synthetic dataset with the
same shape/statistics (6040 users, 3706 movies, ~1M ratings, 1-5 stars) so
benchmarks and tests run hermetically.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

ML1M_USERS = 6040
ML1M_ITEMS = 3706
ML1M_RATINGS = 1_000_209


def movielens_1m(path: Optional[str] = None, n_ratings: Optional[int] = None,
                 seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Return (pairs, ratings): pairs int32 (N, 2) of 1-based [user, item] ids,
    ratings int32 (N,) in 1..5."""
    if path and os.path.exists(path):
        rows = []
        with open(path, "r", encoding="latin-1") as f:
            for line in f:
                u, m, r, _ = line.strip().split("::")
                rows.append((int(u), int(m), int(r)))
        arr = np.asarray(rows, dtype="int64")
        # remap movie ids to a dense 1..n range (ML-1M ids are sparse up to 3952)
        _, dense = np.unique(arr[:, 1], return_inverse=True)
        pairs = np.stack([arr[:, 0], dense + 1], axis=1).astype("int32")
        return pairs, arr[:, 2].astype("int32")
    return synthetic_movielens(n_ratings or ML1M_RATINGS, seed=seed)


def synthetic_movielens(n_ratings: int, n_users: int = ML1M_USERS,
                        n_items: int = ML1M_ITEMS, n_classes: int = 5,
                        seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic explicit-feedback data with latent structure (so models can
    actually learn and HR@10/accuracy is meaningful, not noise).

    Users/items get latent vectors; rating = quantized affinity + noise. Zipf-like
    item popularity mimics real interaction skew.
    """
    rng = np.random.default_rng(seed)
    d = 8
    u_lat = rng.normal(size=(n_users + 1, d)).astype("float32")
    i_lat = rng.normal(size=(n_items + 1, d)).astype("float32")
    # popularity-skewed sampling (Zipf-ish)
    item_p = 1.0 / np.arange(1, n_items + 1) ** 0.8
    item_p /= item_p.sum()
    users = rng.integers(1, n_users + 1, size=n_ratings).astype("int32")
    items = (rng.choice(n_items, size=n_ratings, p=item_p) + 1).astype("int32")
    affinity = np.einsum("nd,nd->n", u_lat[users], i_lat[items]) / np.sqrt(d)
    affinity += 0.35 * rng.normal(size=n_ratings).astype("float32")
    # quantize to 1..n_classes by rank so classes are roughly balanced like ML-1M
    qs = np.quantile(affinity, np.linspace(0, 1, n_classes + 1)[1:-1])
    ratings = (np.digitize(affinity, qs) + 1).astype("int32")
    pairs = np.stack([users, items], axis=1)
    return pairs, ratings


def train_test_split_by_user(pairs: np.ndarray, ratings: np.ndarray,
                             test_frac: float = 0.1, seed: int = 0):
    """Random split (the reference notebook uses randomSplit(0.8/0.2))."""
    rng = np.random.default_rng(seed)
    n = len(pairs)
    idx = rng.permutation(n)
    cut = int(n * (1 - test_frac))
    tr, te = idx[:cut], idx[cut:]
    return (pairs[tr], ratings[tr]), (pairs[te], ratings[te])


def leave_one_out_eval_sets(pairs: np.ndarray, n_items: int, n_negatives: int = 99,
                            max_users: int = 1000, seed: int = 0) -> np.ndarray:
    """NCF-paper leave-one-out HR@10 layout: per user, 1 held-out positive +
    ``n_negatives`` sampled negatives. Returns int32 (U, 1+n_negatives, 2) pairs
    with the positive at index 0 (matches metrics.HitRate's expected layout)."""
    rng = np.random.default_rng(seed)
    by_user = {}
    for (u, i) in pairs:
        by_user.setdefault(int(u), []).append(int(i))
    users = sorted(by_user)[:max_users]
    out = np.zeros((len(users), 1 + n_negatives, 2), dtype="int32")
    for k, u in enumerate(users):
        seen = set(by_user[u])
        pos = by_user[u][-1]
        # sample WITHOUT replacement from the unseen pool: duplicates would skew
        # HR@10, and rejection sampling never terminates when seen == all items
        unseen = np.setdiff1d(np.arange(1, n_items + 1, dtype="int64"),
                              np.fromiter(seen, dtype="int64"))
        if len(unseen) >= n_negatives:
            negs = rng.choice(unseen, size=n_negatives, replace=False)
        else:  # degenerate tiny-catalog case: pad by cycling the unseen pool
            reps = int(np.ceil(n_negatives / max(len(unseen), 1)))
            negs = np.tile(unseen, reps)[:n_negatives] if len(unseen) else \
                np.full(n_negatives, pos, dtype="int64")
        out[k, 0] = (u, pos)
        out[k, 1:, 0] = u
        out[k, 1:, 1] = negs
    return out
