"""3D image transforms — reference ``zoo/.../feature/image3d/``
(``Cropper.scala`` Crop3D/RandomCrop3D/CenterCrop3D, ``Rotation.scala`` Rotate3D,
``AffineTransform.scala`` Affine3D; used by the image-augmentation-3d app).

Volumes are (D, H, W) or (D, H, W, C) numpy arrays on the host; affine
resampling is trilinear with constant padding, vectorized over the whole output
grid (no per-voxel Python loops).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np

from .image import ImageProcessing


def _as_4d(vol: np.ndarray) -> Tuple[np.ndarray, bool]:
    if vol.ndim == 3:
        return vol[..., None], True
    if vol.ndim == 4:
        return vol, False
    raise ValueError(f"expected (D,H,W[,C]) volume, got shape {vol.shape}")


def crop3d(vol: np.ndarray, start: Sequence[int],
           patch_size: Sequence[int]) -> np.ndarray:
    """Fixed-position crop (Cropper.scala Crop3D parity)."""
    v, squeeze = _as_4d(np.asarray(vol))
    d0, h0, w0 = (int(s) for s in start)
    dd, hh, ww = (int(s) for s in patch_size)
    if d0 < 0 or h0 < 0 or w0 < 0 or d0 + dd > v.shape[0] \
            or h0 + hh > v.shape[1] or w0 + ww > v.shape[2]:
        raise ValueError(f"crop {start}+{patch_size} outside volume "
                         f"{v.shape[:3]}")
    out = v[d0:d0 + dd, h0:h0 + hh, w0:w0 + ww]
    return out[..., 0] if squeeze else out


def center_crop3d(vol: np.ndarray, patch_size: Sequence[int]) -> np.ndarray:
    v, _ = _as_4d(np.asarray(vol))
    start = [(s - p) // 2 for s, p in zip(v.shape[:3], patch_size)]
    return crop3d(vol, start, patch_size)


def random_crop3d(vol: np.ndarray, patch_size: Sequence[int],
                  rng: Optional[np.random.Generator] = None) -> np.ndarray:
    rng = rng or np.random.default_rng()
    v, _ = _as_4d(np.asarray(vol))
    start = [int(rng.integers(0, s - p + 1))
             for s, p in zip(v.shape[:3], patch_size)]
    return crop3d(vol, start, patch_size)


def affine3d(vol: np.ndarray, matrix: np.ndarray,
             translation: Sequence[float] = (0, 0, 0),
             fill: float = 0.0) -> np.ndarray:
    """Affine resample (AffineTransform.scala parity): output voxel o maps to
    input coordinate ``matrix @ (o - c) + c + translation`` (c = center).
    Trilinear interpolation, constant fill outside."""
    v, squeeze = _as_4d(np.asarray(vol, dtype="float32"))
    D, H, W, C = v.shape
    mat = np.asarray(matrix, dtype="float64").reshape(3, 3)
    t = np.asarray(translation, dtype="float64")
    center = (np.asarray([D, H, W], dtype="float64") - 1) / 2

    grid = np.stack(np.meshgrid(np.arange(D), np.arange(H), np.arange(W),
                                indexing="ij"), axis=-1).reshape(-1, 3)
    src = (grid - center) @ mat.T + center + t   # (N, 3) float

    lo = np.floor(src).astype(np.int64)
    frac = src - lo
    out = np.zeros((grid.shape[0], C), dtype="float32")
    for corner in range(8):
        off = np.array([(corner >> 2) & 1, (corner >> 1) & 1, corner & 1])
        idx = lo + off
        w = np.prod(np.where(off == 1, frac, 1 - frac), axis=1)
        valid = ((idx >= 0) & (idx < np.array([D, H, W]))).all(axis=1)
        ci = np.clip(idx, 0, np.array([D, H, W]) - 1)
        vals = v[ci[:, 0], ci[:, 1], ci[:, 2]]
        # out-of-bounds corners contribute the fill value at their weight, so
        # border voxels blend toward fill rather than toward 0
        out += np.where(valid[:, None], vals * w[:, None], fill * w[:, None])
    out = out.reshape(D, H, W, C)
    return out[..., 0] if squeeze else out


def rotation_matrix(yaw: float = 0.0, pitch: float = 0.0,
                    roll: float = 0.0) -> np.ndarray:
    """Rotation about the W (yaw), H (pitch), D (roll) axes, composed R_d·R_h·R_w
    (Rotation.scala convention: Euler angles in radians)."""
    cy, sy = math.cos(yaw), math.sin(yaw)
    cp, sp = math.cos(pitch), math.sin(pitch)
    cr, sr = math.cos(roll), math.sin(roll)
    rw = np.array([[cy, -sy, 0], [sy, cy, 0], [0, 0, 1]])
    rh = np.array([[cp, 0, sp], [0, 1, 0], [-sp, 0, cp]])
    rd = np.array([[1, 0, 0], [0, cr, -sr], [0, sr, cr]])
    return rd @ rh @ rw


def rotate3d(vol: np.ndarray, yaw: float = 0.0, pitch: float = 0.0,
             roll: float = 0.0, fill: float = 0.0) -> np.ndarray:
    return affine3d(vol, rotation_matrix(yaw, pitch, roll), fill=fill)


# ------------------------------------------------------ ImageProcessing stages


class Crop3D(ImageProcessing):
    def __init__(self, start: Sequence[int], patch_size: Sequence[int]):
        self.start = start
        self.patch_size = patch_size

    def apply_image(self, img, rng):
        return crop3d(img, self.start, self.patch_size)


class CenterCrop3D(ImageProcessing):
    def __init__(self, patch_size: Sequence[int]):
        self.patch_size = patch_size

    def apply_image(self, img, rng):
        return center_crop3d(img, self.patch_size)


class RandomCrop3D(ImageProcessing):
    def __init__(self, patch_size: Sequence[int]):
        self.patch_size = patch_size

    def apply_image(self, img, rng):
        return random_crop3d(img, self.patch_size, rng)


class Rotate3D(ImageProcessing):
    def __init__(self, yaw: float = 0.0, pitch: float = 0.0, roll: float = 0.0,
                 fill: float = 0.0):
        self.args = (yaw, pitch, roll, fill)

    def apply_image(self, img, rng):
        return rotate3d(img, *self.args)


class AffineTransform3D(ImageProcessing):
    def __init__(self, matrix: np.ndarray, translation=(0, 0, 0),
                 fill: float = 0.0):
        self.matrix = matrix
        self.translation = translation
        self.fill = fill

    def apply_image(self, img, rng):
        return affine3d(img, self.matrix, self.translation, self.fill)
