"""Text pipeline: TextFeature / TextSet + transformer stages.

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/feature/text/
(TextSet.scala, TextFeature.scala, Tokenizer.scala, Normalizer.scala,
WordIndexer.scala, SequenceShaper.scala, TextFeatureToSample.scala) and the python
mirror /root/reference/pyzoo/zoo/feature/text/{text_set,text_feature,transformer}.py.

TPU-native design: the reference runs each transform as a Spark RDD map; here a
TextSet is a host-side collection whose terminal ``to_arrays()`` emits padded
``(N, L)`` int32 batches — the device-facing contract. Distribution happens at
FeatureSet/pjit level (per-host sharding of the produced arrays), not inside the
text transforms.
"""

from __future__ import annotations

import os
import string
from collections import Counter
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class TextFeature:
    """One text record: raw text, optional label/uri, accumulated transform
    outputs under ``keys()`` (text_feature.py:27-107 parity)."""

    def __init__(self, text: Optional[str] = None, label: Optional[int] = None,
                 uri: Optional[str] = None):
        self._d: Dict = {}
        if text is not None:
            self._d["text"] = text
        if label is not None:
            self._d["label"] = int(label)
        if uri is not None:
            self._d["uri"] = uri

    def get_text(self) -> Optional[str]:
        return self._d.get("text")

    def get_label(self) -> int:
        return self._d.get("label", -1)

    def get_uri(self) -> Optional[str]:
        return self._d.get("uri")

    def has_label(self) -> bool:
        return "label" in self._d

    def set_label(self, label: int) -> "TextFeature":
        self._d["label"] = int(label)
        return self

    def get_tokens(self) -> Optional[List[str]]:
        return self._d.get("tokens")

    def get_indices(self) -> Optional[List[int]]:
        return self._d.get("indexedTokens")

    def get_sample(self):
        return self._d.get("sample")

    def get_predict(self):
        return self._d.get("predict")

    def keys(self) -> List[str]:
        return list(self._d.keys())

    def __getitem__(self, k):
        return self._d[k]

    def __setitem__(self, k, v):
        self._d[k] = v

    def __contains__(self, k):
        return k in self._d

    def copy(self) -> "TextFeature":
        out = TextFeature()
        out._d = dict(self._d)
        return out

    def __repr__(self):
        return f"TextFeature(keys={self.keys()})"


# ------------------------------------------------------------------ transformers


class TextTransformer:
    """Base transform stage (transformer.py:28-41 parity); stages chain with
    ``>>`` like the reference's ``Preprocessing`` chaining."""

    def transform(self, feature: TextFeature) -> TextFeature:
        raise NotImplementedError

    def __call__(self, feature: TextFeature) -> TextFeature:
        return self.transform(feature)

    def __rshift__(self, other: "TextTransformer") -> "ChainedTextTransformer":
        return ChainedTextTransformer([self, other])


class ChainedTextTransformer(TextTransformer):
    def __init__(self, stages: Sequence[TextTransformer]):
        self.stages = list(stages)

    def transform(self, feature: TextFeature) -> TextFeature:
        for s in self.stages:
            feature = s.transform(feature)
        return feature

    def __rshift__(self, other: TextTransformer) -> "ChainedTextTransformer":
        return ChainedTextTransformer(self.stages + [other])


class Tokenizer(TextTransformer):
    """Whitespace tokenizer (Tokenizer.scala parity)."""

    def transform(self, feature: TextFeature) -> TextFeature:
        feature["tokens"] = feature.get_text().split()
        return feature


class Normalizer(TextTransformer):
    """Lower-case + strip punctuation/digits from tokens (Normalizer.scala:
    removes dirty characters and converts to lower case)."""

    _strip = str.maketrans("", "", string.punctuation + string.digits)

    def transform(self, feature: TextFeature) -> TextFeature:
        toks = [t.lower().translate(self._strip) for t in feature.get_tokens()]
        feature["tokens"] = [t for t in toks if t]
        return feature


class WordIndexer(TextTransformer):
    """Map tokens → 1-based indices via ``word_index``; unknown words drop out
    (WordIndexer.scala parity — unknown tokens are removed, not mapped to 0)."""

    def __init__(self, word_index: Dict[str, int]):
        self.word_index = dict(word_index)

    def transform(self, feature: TextFeature) -> TextFeature:
        feature["indexedTokens"] = [self.word_index[t] for t in feature.get_tokens()
                                    if t in self.word_index]
        return feature


class SequenceShaper(TextTransformer):
    """Pad/truncate ``indexedTokens`` to ``len`` (SequenceShaper.scala parity:
    trunc_mode pre|post, pad with ``pad_element`` at the END)."""

    def __init__(self, len: int, trunc_mode: str = "pre", pad_element: int = 0):
        assert trunc_mode in ("pre", "post"), "trunc_mode should be pre or post"
        self.len = int(len)
        self.trunc_mode = trunc_mode
        self.pad_element = int(pad_element)

    def transform(self, feature: TextFeature) -> TextFeature:
        idx = list(feature.get_indices())
        if len(idx) > self.len:
            idx = idx[-self.len:] if self.trunc_mode == "pre" else idx[:self.len]
        else:
            idx = idx + [self.pad_element] * (self.len - len(idx))
        feature["indexedTokens"] = idx
        return feature


class TextFeatureToSample(TextTransformer):
    """Materialize (feature, label) arrays (TextFeatureToSample.scala parity)."""

    def transform(self, feature: TextFeature) -> TextFeature:
        x = np.asarray(feature.get_indices(), dtype="int32")
        y = np.asarray(feature.get_label(), dtype="int32")
        feature["sample"] = (x, y)
        return feature


# ------------------------------------------------------------------------ TextSet


@dataclass
class Relation:
    """(id1, id2, label) relation for text matching (common/relation.py parity)."""

    id1: str
    id2: str
    label: int

    def to_tuple(self):
        return (self.id1, self.id2, self.label)


class TextSet:
    """Collection of TextFeatures with chained transforms (text_set.py:23 parity).

    The reference's Local/Distributed split collapses: transforms always run
    host-side; ``to_arrays``/``generate_sample`` produce the device-ready batch.
    """

    def __init__(self, features: Sequence[TextFeature]):
        self.features: List[TextFeature] = list(features)
        self.word_index: Optional[Dict[str, int]] = None

    # -- constructors ----------------------------------------------------------
    @classmethod
    def from_texts(cls, texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None) -> "TextSet":
        labels = labels if labels is not None else [None] * len(texts)
        return cls([TextFeature(t, l) for t, l in zip(texts, labels)])

    @classmethod
    def read(cls, path: str) -> "TextSet":
        """Read a directory of ``<category>/<file>.txt`` (text_set.py:302 parity:
        category dir name index becomes the label)."""
        feats = []
        cats = [c for c in sorted(os.listdir(path))
                if os.path.isdir(os.path.join(path, c))]
        for label, cat in enumerate(cats):
            cat_dir = os.path.join(path, cat)
            for fn in sorted(os.listdir(cat_dir)):
                with open(os.path.join(cat_dir, fn), encoding="utf-8",
                          errors="ignore") as f:
                    feats.append(TextFeature(f.read(), label,
                                             uri=os.path.join(cat, fn)))
        return cls(feats)

    @classmethod
    def read_csv(cls, path: str) -> "TextSet":
        """CSV of ``uri,text`` rows, no header (text_set.py:332 parity)."""
        import csv

        feats = []
        with open(path, newline="", encoding="utf-8") as f:
            for row in csv.reader(f):
                if len(row) >= 2:
                    # text may itself contain commas: keep everything after uri
                    feats.append(TextFeature(",".join(row[1:]), uri=row[0]))
        return cls(feats)

    @classmethod
    def read_parquet(cls, path: str) -> "TextSet":
        import pandas as pd

        df = pd.read_parquet(path)
        return cls([TextFeature(r["text"], uri=r.get("uri"))
                    for _, r in df.iterrows()])

    # -- accessors -------------------------------------------------------------
    def get_texts(self) -> List[str]:
        return [f.get_text() for f in self.features]

    def get_labels(self) -> List[int]:
        return [f.get_label() for f in self.features]

    def get_uris(self) -> List[Optional[str]]:
        return [f.get_uri() for f in self.features]

    def get_samples(self):
        return [f.get_sample() for f in self.features]

    def get_predicts(self):
        return [f.get_predict() for f in self.features]

    def get_word_index(self) -> Optional[Dict[str, int]]:
        return self.word_index

    def set_word_index(self, vocab: Dict[str, int]) -> "TextSet":
        self.word_index = dict(vocab)
        return self

    def save_word_index(self, path: str) -> None:
        """One ``word index`` pair per line (text_set.py:85 format parity)."""
        with open(path, "w", encoding="utf-8") as f:
            for w, i in sorted(self.word_index.items(), key=lambda kv: kv[1]):
                f.write(f"{w} {i}\n")

    def load_word_index(self, path: str) -> "TextSet":
        vocab = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                w, i = line.rsplit(" ", 1)
                vocab[w] = int(i)
        return self.set_word_index(vocab)

    def __len__(self):
        return len(self.features)

    # -- transforms ------------------------------------------------------------
    def transform(self, transformer: TextTransformer) -> "TextSet":
        """Returns a NEW TextSet; source features are never mutated (matching
        the reference's immutable RDD-map semantics)."""
        out = TextSet([transformer.transform(f.copy()) for f in self.features])
        out.word_index = self.word_index
        return out

    def tokenize(self) -> "TextSet":
        return self.transform(Tokenizer())

    def normalize(self) -> "TextSet":
        return self.transform(Normalizer())

    def word2idx(self, remove_topN: int = 0, max_words_num: int = -1,
                 min_freq: int = 1,
                 existing_map: Optional[Dict[str, int]] = None) -> "TextSet":
        """Build the word index from token frequencies then map tokens
        (text_set.py:224-272 parity): drop the ``remove_topN`` most frequent,
        keep at most ``max_words_num`` with frequency ≥ ``min_freq``; indices
        start from 1 (or extend ``existing_map``)."""
        counts = Counter(t for f in self.features for t in (f.get_tokens() or ()))
        ranked = [w for w, c in counts.most_common() if c >= min_freq]
        ranked = ranked[remove_topN:]
        if max_words_num > 0:
            ranked = ranked[:max_words_num]
        vocab = dict(existing_map or {})
        nxt = max(vocab.values()) + 1 if vocab else 1
        for w in ranked:
            if w not in vocab:
                vocab[w] = nxt
                nxt += 1
        return self.transform(WordIndexer(vocab)).set_word_index(vocab)

    def shape_sequence(self, len: int, trunc_mode: str = "pre",
                       pad_element: int = 0) -> "TextSet":
        return self.transform(SequenceShaper(len, trunc_mode, pad_element))

    def generate_sample(self) -> "TextSet":
        return self.transform(TextFeatureToSample())

    # -- terminal / utilities --------------------------------------------------
    def to_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Stack indexed tokens + labels into device-ready ``(N, L)`` / ``(N,)``
        batches — the TPU-facing contract of this pipeline."""
        xs = np.stack([np.asarray(f.get_indices(), dtype="int32")
                       for f in self.features])
        ys = np.asarray([f.get_label() for f in self.features], dtype="int32")
        return xs, ys

    def random_split(self, weights: Sequence[float],
                     seed: int = 0) -> List["TextSet"]:
        """Random split by weight fractions (text_set.py:193 parity)."""
        w = np.asarray(weights, dtype="float64")
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self.features))
        cuts = np.floor(np.cumsum(w) * len(perm)).astype(int)[:-1]
        out = []
        for chunk in np.split(perm, cuts):
            ts = TextSet([self.features[i] for i in chunk])
            ts.word_index = self.word_index
            out.append(ts)
        return out

    # -- relation constructors (text matching) ---------------------------------
    @classmethod
    def from_relation_pairs(cls, relations: Sequence[Relation], corpus1: "TextSet",
                            corpus2: "TextSet", seed: int = 0) -> "TextSet":
        """Pairwise-training set (text_set.py:369 parity): for each positive
        relation pick a negative with the same id1; sample feature is
        ``(2, L1+L2)`` [positive; negative] with label [1, 0]."""
        c1 = {f.get_uri(): f.get_indices() for f in corpus1.features}
        c2 = {f.get_uri(): f.get_indices() for f in corpus2.features}
        pos: Dict[str, List[str]] = {}
        neg: Dict[str, List[str]] = {}
        for r in relations:
            (pos if r.label > 0 else neg).setdefault(r.id1, []).append(r.id2)
        rng = np.random.default_rng(seed)
        feats = []
        for id1, pos_ids in pos.items():
            negs = neg.get(id1, [])
            if not negs:
                continue
            for pid in pos_ids:
                nid = negs[int(rng.integers(len(negs)))]
                x = np.stack([
                    np.concatenate([c1[id1], c2[pid]]),
                    np.concatenate([c1[id1], c2[nid]]),
                ]).astype("int32")
                tf = TextFeature(uri=id1)
                tf["indexedTokens"] = x
                tf["sample"] = (x, np.asarray([1, 0], dtype="int32"))
                feats.append(tf)
        return cls(feats)

    @classmethod
    def from_relation_lists(cls, relations: Sequence[Relation], corpus1: "TextSet",
                            corpus2: "TextSet") -> "TextSet":
        """Listwise-ranking set (text_set.py:401 parity): group by id1; sample
        feature ``(list_len, L1+L2)``, label ``(list_len, 1)``."""
        c1 = {f.get_uri(): f.get_indices() for f in corpus1.features}
        c2 = {f.get_uri(): f.get_indices() for f in corpus2.features}
        groups: Dict[str, List[Relation]] = {}
        for r in relations:
            groups.setdefault(r.id1, []).append(r)
        feats = []
        for id1, rels in groups.items():
            x = np.stack([np.concatenate([c1[id1], c2[r.id2]])
                          for r in rels]).astype("int32")
            y = np.asarray([[r.label] for r in rels], dtype="int32")
            tf = TextFeature(uri=id1)
            tf["indexedTokens"] = x
            tf["sample"] = (x, y)
            feats.append(tf)
        return cls(feats)
