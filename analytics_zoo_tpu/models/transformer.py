"""TransformerLM — the flagship distributed model (causal LM / classifier).

The reference exposes transformer capability as layers (TransformerLayer.scala,
BERT.scala) used by the text estimators (tfpark/text/). Here the flagship model
additionally exercises every parallelism axis: batch over dp/fsdp, params over
fsdp+tp (megatron layout, parallel.sharding.TP_RULES), sequence over sp via
ring/Ulysses attention. This is the model behind ``__graft_entry__``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import layers as L
from ..nn.layers.attention import TransformerLayer
from ..nn.layers.normalization import LayerNormalization
from ..nn.module import Layer, as_compute, get_initializer, param_dtype
from ..nn.topology import KerasNet
from .common.zoo_model import register_model


@register_model("TransformerLM")
class TransformerLM(Layer, KerasNet):
    """Decoder-only transformer over int token ids (B, T) → logits (B, T, V).

    .. note:: **remat policy remap.** ``remat=True`` now means ``'flash'``
       (checkpoint with the flash-attention save policy: the kernel's
       out/lse are pinned so backward never re-runs the O(T²) attention
       forward — strictly faster than full recompute wherever flash runs).
       Callers wanting the minimum-memory classic behavior — recompute
       EVERYTHING in backward, and the only correct choice when attention
       took the non-flash path — must now pass ``remat='full'`` explicitly.
       ``remat='dots'`` additionally saves matmul outputs (less recompute,
       more memory). See ``_remat_policy`` for the exact policies.
    """

    def __init__(self, vocab: int, hidden_size: int = 256, n_block: int = 4,
                 n_head: int = 8, seq_len: int = 512,
                 intermediate_size: Optional[int] = None,
                 attn_strategy: str = "auto", remat=False, name=None):
        super().__init__(name=name)
        self.vocab = vocab
        self.hidden_size = hidden_size
        self.n_block = n_block
        self.seq_len = seq_len
        self.intermediate_size = intermediate_size
        self.attn_strategy = attn_strategy
        # remat: False | "flash" (True) | "full" | "dots".
        #   "flash": jax.checkpoint with FLASH_REMAT_POLICY — the flash
        #            kernel's out/lse are saved so backward never re-runs the
        #            O(T^2) attention forward; only projections/LN/MLP
        #            recompute. Strictly dominates "full" wherever flash runs
        #            (BENCH batch-32 remat: 0.406 MFU full → ≥0.5 flash).
        #   "full":  plain jax.checkpoint (recompute EVERYTHING incl.
        #            attention) — the minimum-memory fallback, and the only
        #            correct choice when attention took the non-flash path
        #            (full_attention saves no lse to reuse).
        #   "dots":  flash policy + dots_with_no_batch_dims_saveable — also
        #            keeps matmul outputs; less recompute, more memory.
        self.remat = "flash" if remat is True else remat
        self.blocks = [
            TransformerLayer(hidden_size, n_head, intermediate_size, causal=True,
                             attn_strategy=attn_strategy,
                             name=f"{self.name}_block{i}")
            for i in range(n_block)
        ]
        self.ln_f = LayerNormalization(name=f"{self.name}_lnf")
        self.layers = list(self.blocks) + [self.ln_f]  # canonical order (persistence)

    @property
    def input_shape(self):
        return (self.seq_len,)

    def _remat_policy(self):
        """Resolve ``self.remat`` to a jax.checkpoint policy (None = save
        nothing, i.e. classic full rematerialization)."""
        if self.remat == "full":
            return None
        from ..ops.flash_attention import FLASH_REMAT_POLICY

        if self.remat == "dots":
            return jax.checkpoint_policies.save_from_both_policies(
                FLASH_REMAT_POLICY,
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        if self.remat in ("flash", True):
            return FLASH_REMAT_POLICY
        raise ValueError(f"unknown remat mode {self.remat!r}; "
                         "known: False, True/'flash', 'full', 'dots'")

    def build(self, rng, input_shape=None):
        ks = jax.random.split(rng, self.n_block + 3)
        params = {
            "token_embeddings": jax.random.normal(
                ks[0], (self.vocab, self.hidden_size), param_dtype()) * 0.02,
            "pos_embeddings": jax.random.normal(
                ks[1], (self.seq_len, self.hidden_size), param_dtype()) * 0.02,
            "logits_kernel": get_initializer("glorot_uniform")(
                ks[2], (self.hidden_size, self.vocab), param_dtype()),
        }
        for i, blk in enumerate(self.blocks):
            p, _ = blk.build(ks[3 + i], (None, self.hidden_size))
            params[f"block{i}"] = p
        lnf, _ = self.ln_f.build(ks[-1], (None, self.hidden_size))
        params["ln_f"] = lnf
        return params, {}

    def apply_features(self, params, x, *, training=False, rng=None):
        """Hidden states BEFORE the LM head: (B, T, hidden).

        Pair with :func:`analytics_zoo_tpu.ops.fused_ce.fused_softmax_xent`
        (``fused_softmax_xent(h, params["logits_kernel"], labels)``) to train
        without ever materializing the (B, T, vocab) logits — at vocab 32k
        the f32 logits are 1 GB per 8k tokens, which is what pushes big
        batches into rematerialization."""
        ids = jnp.asarray(x, jnp.int32)
        h = jnp.take(params["token_embeddings"], ids, axis=0)
        h = h + params["pos_embeddings"][: ids.shape[1]][None]
        h = as_compute(h)
        rngs = (jax.random.split(rng, self.n_block) if rng is not None
                else [None] * self.n_block)

        for i, blk in enumerate(self.blocks):
            if self.remat:
                # trade FLOPs for HBM: recompute block activations in backward,
                # except what the remat policy pins (see __init__)
                apply_fn = jax.checkpoint(
                    lambda p, h, blk=blk, r=rngs[i]: blk.apply(
                        p, {}, h, training=training, rng=r)[0],
                    policy=self._remat_policy())
                h = apply_fn(params[f"block{i}"], h)
            else:
                h, _ = blk.apply(params[f"block{i}"], {}, h, training=training,
                                 rng=rngs[i])
        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        return h

    def apply(self, params, state, x, *, training=False, rng=None):
        h = self.apply_features(params, x, training=training, rng=rng)
        logits = h @ jnp.asarray(params["logits_kernel"], h.dtype)
        return logits, state

    # -------------------------------------------------------- decode serving
    # prefill()/decode_step(): the autoregressive path behind the continuous
    # batcher (serving/generation.py). Both are pure functions of
    # (params, cache, ...) with shapes fixed by the KVCacheConfig, so each
    # compiles exactly once per (batch, bucket) — the pow2 discipline the
    # one-shot serving path already follows.

    def init_kv_cache(self, n_slots: int, *, page_size: int = 16,
                      max_seq_len: Optional[int] = None,
                      n_pages: Optional[int] = None, dtype=None):
        """Build a paged KV cache for ``n_slots`` concurrent decode
        sequences. Returns ``(KVCacheConfig, cache)`` where ``cache`` is the
        ``{"k", "v"}`` page-pool pytree threaded through
        :meth:`prefill`/:meth:`decode_step`."""
        from ..nn.module import compute_dtype
        from ..ops.kv_cache import KVCacheConfig, init_cache

        max_seq = int(max_seq_len or self.seq_len)
        pps = -(-max_seq // page_size)          # ceil: full pages only
        if pps * page_size > self.seq_len:
            # validate the ROUNDED capacity: pps*page_size is what decode
            # positions can actually reach, and positions past the table
            # would silently clamp to the last row (corrupt embeddings)
            raise ValueError(
                f"max_seq_len {max_seq} rounds up to {pps * page_size} "
                f"(full pages of {page_size}), exceeding the model's "
                f"position table ({self.seq_len}); choose max_seq_len <= "
                f"{self.seq_len // page_size * page_size}")
        attn = self.blocks[0].attn
        cfg = KVCacheConfig(
            n_layers=self.n_block, n_heads=attn.n_head,
            head_dim=attn.head_dim, n_slots=n_slots, page_size=page_size,
            pages_per_slot=pps, n_pages=n_pages,
            dtype=dtype or compute_dtype())
        return cfg, init_cache(cfg)

    def prefill(self, params, cache, ids, lengths, table, *, page_size: int):
        """One batched forward that fills the cache and returns last-token
        logits.

        ``ids``: (B, T_bucket) int32, right-padded to a pow2 bucket that
        divides ``page_size``; ``lengths``: (B,) true prompt lengths;
        ``table``: (B, pages_per_slot) int32 page tables (entries past the
        allocated prefix = scratch). Causal masking means pad positions are
        never attended by valid queries, so their scratch writes are inert.
        Returns ``(logits (B, V) f32 — at position length-1, cache)``.
        """
        from ..ops.kv_cache import prefill_write

        ids = jnp.asarray(ids, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        h = jnp.take(params["token_embeddings"], ids, axis=0)
        h = h + params["pos_embeddings"][: ids.shape[1]][None]
        h = as_compute(h)
        k_cache, v_cache = cache["k"], cache["v"]
        for i, blk in enumerate(self.blocks):
            h, k, v = blk.apply_with_kv(params[f"block{i}"], h)
            k_cache = k_cache.at[i].set(
                prefill_write(k_cache[i], table, k, page_size=page_size))
            v_cache = v_cache.at[i].set(
                prefill_write(v_cache[i], table, v, page_size=page_size))
        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        last = jnp.take_along_axis(
            h, jnp.maximum(lengths - 1, 0)[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]                                    # (B, hidden)
        logits = last @ jnp.asarray(params["logits_kernel"], last.dtype)
        return logits.astype(jnp.float32), {"k": k_cache, "v": v_cache}

    def prefill_from(self, params, cache, ids, start, lengths, table, *,
                     page_size: int):
        """Chunked SUFFIX prefill: run the tokens from the divergence point
        of a shared-prefix hit against an already-populated cache prefix.

        ``ids``: (B, T_bucket) int32 — the suffix tokens, occupying
        positions ``start .. start + T_bucket - 1``; ``start``: (B,) int32
        — the first position to compute (everything below it is already in
        the cache via shared prefix pages); ``lengths``: (B,) — the TOTAL
        true prompt length (``start + true suffix length``). ``table`` must
        map every position below ``lengths`` to a real page and positions
        the bucket padding spills into to scratch. Suffix token ``i``
        attends causally to the whole cached prefix plus suffix tokens
        ``<= i`` (the speculative verify step's masking, reused block by
        block); padding rows' K/V land in-page past the true length,
        invisible through the length mask and overwritten by decode before
        ever becoming visible. Returns ``(logits (B, V) f32 — at position
        ``lengths - 1``, cache)``. With ``start == 0`` this is semantically
        :meth:`prefill` (modulo write path); the warm/cold bit-identity
        tests pin that equivalence.
        """
        start = jnp.asarray(start, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        return self.prefill_chunk(params, cache, ids, start, lengths - start,
                                  table, page_size=page_size)

    def prefill_chunk(self, params, cache, ids, n_done, n_valid, table, *,
                      page_size: int):
        """One fixed-shape prefill CHUNK: run ``ids`` against a cache that
        already holds ``n_done`` tokens of the same prompt — the
        :meth:`prefill_from` machinery generalized from "resume after a
        cached prefix" to "resume after any boundary", so a long prompt is
        many identical chunk dispatches instead of one whole-prompt bucket.

        ``ids``: (B, chunk_tokens) int32 — tokens at positions ``n_done ..
        n_done + chunk_tokens - 1``, right-padded past ``n_valid``;
        ``n_done``: (B,) int32 — tokens already written to the cache (page
        boundary NOT required: a chunk may start mid-page, the verify-step
        write path scatters per position); ``n_valid``: (B,) int32 — true
        tokens in this chunk (``<= chunk_tokens``; the final chunk of a
        prompt is short). ``table`` must be wide enough for every position
        this chunk writes (``(n_done + chunk_tokens - 1) // page_size + 1``
        pages) with entries past the allocated rows pointing at scratch —
        padding-lane K/V land in scratch and their keys read back masked,
        so they contribute exactly 0.0 to every softmax (bit-neutral).
        Returns ``(logits (B, V) f32 — at position ``n_done + n_valid - 1``,
        cache)``; compiled ONCE per (chunk_tokens, B).
        """
        ids = jnp.asarray(ids, jnp.int32)
        n_done = jnp.asarray(n_done, jnp.int32)
        n_valid = jnp.asarray(n_valid, jnp.int32)
        t = ids.shape[1]
        positions = n_done[:, None] + jnp.arange(t, dtype=jnp.int32)[None]
        h = jnp.take(params["token_embeddings"], ids, axis=0)
        h = h + jnp.take(params["pos_embeddings"], positions, axis=0)
        h = as_compute(h)
        k_cache, v_cache = cache["k"], cache["v"]
        for i, blk in enumerate(self.blocks):
            h, kp, vp = blk.verify_step(
                params[f"block{i}"], h, k_cache[i], v_cache[i], table,
                n_done, page_size=page_size)
            k_cache = k_cache.at[i].set(kp)
            v_cache = v_cache.at[i].set(vp)
        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        last_row = jnp.maximum(n_valid - 1, 0)
        last = jnp.take_along_axis(
            h, last_row[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        logits = last @ jnp.asarray(params["logits_kernel"], last.dtype)
        return logits.astype(jnp.float32), {"k": k_cache, "v": v_cache}

    def decode_step(self, params, cache, ids, lengths, table, seeds,
                    token_idx, temperature, *, page_size: int,
                    top_k: int = 0):
        """One fixed-shape decode step over every slot.

        ``ids``: (B,) int32 — the token sampled by the previous step (or
        prefill); ``lengths``: (B,) — tokens already cached, i.e. the
        position ``ids`` occupies; ``seeds``/``token_idx``/``temperature``:
        (B,) per-request sampling state (see
        :func:`analytics_zoo_tpu.ops.kv_cache.sample_tokens`). Returns
        ``(next_ids (B,) int32, logits (B, V) f32, cache)`` — cache shapes
        identical in and out (the decode-shape-stability invariant).
        """
        from ..ops.kv_cache import sample_tokens

        ids = jnp.asarray(ids, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        h = jnp.take(params["token_embeddings"], ids, axis=0)[:, None]
        h = h + jnp.take(params["pos_embeddings"], lengths, axis=0)[:, None]
        h = as_compute(h)
        k_cache, v_cache = cache["k"], cache["v"]
        for i, blk in enumerate(self.blocks):
            h, kp, vp = blk.decode_step(
                params[f"block{i}"], h, k_cache[i], v_cache[i], table,
                lengths, page_size=page_size)
            k_cache = k_cache.at[i].set(kp)
            v_cache = v_cache.at[i].set(vp)
        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        logits = (h[:, 0] @ jnp.asarray(params["logits_kernel"], h.dtype)
                  ).astype(jnp.float32)
        next_ids = sample_tokens(logits, seeds, token_idx, temperature,
                                 top_k=top_k)
        return next_ids, logits, {"k": k_cache, "v": v_cache}

    def verify_step(self, params, cache, ids, lengths, table, seeds,
                    token_idx, temperature, *, page_size: int,
                    top_k: int = 0):
        """One fixed-shape speculative VERIFY step: score ``k`` tokens per
        slot in one dispatch (the multi-token twin of :meth:`decode_step`).

        ``ids``: (B, k) int32 — column 0 is the previous step's sampled
        token (certain), columns 1..k-1 the drafted continuation; they
        occupy positions ``lengths .. lengths + k - 1`` (the caller has
        pages allocated through position ``lengths + k - 1``).
        ``token_idx``: (B,) — ordinal of the FIRST token this step emits.
        Returns ``(accepted (B,) int32, tokens (B, k) int32, draft_probs
        (B, k-1) f32, cache)`` — ``tokens[:, :accepted+1]`` are the emitted
        tokens (see :func:`analytics_zoo_tpu.ops.speculative.
        verify_draft_tokens`); cache shapes identical in and out, same as
        the decode step (ONE compiled executable per (k, slot-count)).
        """
        from ..ops.speculative import verify_draft_tokens

        ids = jnp.asarray(ids, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        k = ids.shape[1]
        positions = lengths[:, None] + jnp.arange(k, dtype=jnp.int32)[None]
        h = jnp.take(params["token_embeddings"], ids, axis=0)
        h = h + jnp.take(params["pos_embeddings"], positions, axis=0)
        h = as_compute(h)
        k_cache, v_cache = cache["k"], cache["v"]
        for i, blk in enumerate(self.blocks):
            h, kp, vp = blk.verify_step(
                params[f"block{i}"], h, k_cache[i], v_cache[i], table,
                lengths, page_size=page_size)
            k_cache = k_cache.at[i].set(kp)
            v_cache = v_cache.at[i].set(vp)
        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        logits = (h @ jnp.asarray(params["logits_kernel"], h.dtype)
                  ).astype(jnp.float32)                       # (B, k, V)
        accepted, tokens, draft_probs = verify_draft_tokens(
            logits, ids[:, 1:], seeds, token_idx, temperature, top_k=top_k)
        return accepted, tokens, draft_probs, {"k": k_cache, "v": v_cache}

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.vocab,)

    def constructor_config(self):
        return dict(vocab=self.vocab, hidden_size=self.hidden_size,
                    n_block=self.n_block, n_head=self.blocks[0].attn.n_head,
                    seq_len=self.seq_len,
                    intermediate_size=self.intermediate_size,
                    attn_strategy=self.attn_strategy, remat=self.remat)


@register_model("PipelinedTransformerLM")
class PipelinedTransformerLM(Layer, KerasNet):
    """TransformerLM whose blocks run as a GPipe pipeline over the ``pp`` axis.

    The pp *training-engine strategy*: block parameters are built STACKED on a
    leading ``(n_block, ...)`` axis (one pytree, congruent across blocks), the
    Estimator shards that axis over ``pp`` via :meth:`param_spec`, and
    ``apply`` runs the blocks through
    :func:`analytics_zoo_tpu.parallel.pipeline_apply` — the ``lax.scan`` +
    ``ppermute`` GPipe schedule, differentiable end to end, so
    ``Estimator.fit`` trains through the pipeline with no engine special
    cases. Embeddings / final LN / LM head stay replicated outside the
    pipeline (they are O(tokens·H) next to the blocks' O(tokens·H²)).

    Off a pp mesh (pp==1 or no context) the same model applies its blocks
    sequentially, so one checkpoint format serves both layouts.

    Parity: the reference has no pipeline engine (single-node BigDL); this is
    the TPU-native extension point SURVEY §2.2 marks as the pp row.
    """

    def __init__(self, vocab: int, hidden_size: int = 256, n_block: int = 4,
                 n_head: int = 8, seq_len: int = 512,
                 intermediate_size: Optional[int] = None,
                 n_microbatches: int = 4, attn_strategy: str = "full",
                 name=None):
        super().__init__(name=name)
        self.vocab = vocab
        self.hidden_size = hidden_size
        self.n_block = n_block
        self.seq_len = seq_len
        self.intermediate_size = intermediate_size
        self.n_microbatches = n_microbatches
        self.attn_strategy = attn_strategy
        # ONE block instance: all blocks share structure; per-block params
        # live on the stacked leading axis
        self.block = TransformerLayer(hidden_size, n_head, intermediate_size,
                                      causal=True, attn_strategy=attn_strategy,
                                      name=f"{self.name}_block")
        self.ln_f = LayerNormalization(name=f"{self.name}_lnf")
        self.layers = [self.block, self.ln_f]

    @property
    def input_shape(self):
        return (self.seq_len,)

    def build(self, rng, input_shape=None):
        ks = jax.random.split(rng, self.n_block + 4)
        params = {
            "token_embeddings": jax.random.normal(
                ks[0], (self.vocab, self.hidden_size), param_dtype()) * 0.02,
            "pos_embeddings": jax.random.normal(
                ks[1], (self.seq_len, self.hidden_size), param_dtype()) * 0.02,
            "logits_kernel": get_initializer("glorot_uniform")(
                ks[2], (self.hidden_size, self.vocab), param_dtype()),
        }
        per_block = [self.block.build(ks[3 + i], (None, self.hidden_size))[0]
                     for i in range(self.n_block)]
        from ..parallel.pipeline import stack_stage_params

        params["blocks"] = stack_stage_params(per_block)
        lnf, _ = self.ln_f.build(ks[-1], (None, self.hidden_size))
        params["ln_f"] = lnf
        return params, {}

    def _pp_mesh(self):
        try:
            from ..common.context import get_zoo_context

            mesh = get_zoo_context(auto_init=False).mesh
        except RuntimeError:
            return None, 1
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        return (mesh, pp) if pp > 1 else (None, 1)

    def param_spec(self, path, leaf):
        """``(path, leaf) -> PartitionSpec`` for Estimator(param_sharding=...):
        stacked block leaves shard their leading block axis over ``pp``
        (each device holds exactly its stage's weights, the GPipe layout);
        everything else is replicated.

        Matches the exact top-level ``'blocks'`` key — a substring test would
        also capture unrelated params that merely mention "blocks" in a
        nested name and mis-shard them."""
        from jax.sharding import PartitionSpec as P

        top = path[0] if path else None
        top_key = getattr(top, "key", getattr(top, "idx", None)) \
            if top is not None else None
        if top_key == "blocks" and getattr(leaf, "ndim", 0) >= 1:
            _, pp = self._pp_mesh()
            if pp > 1 and self.n_block % pp:
                raise ValueError(
                    f"n_block={self.n_block} is not divisible by the mesh's "
                    f"pp={pp}: pipeline stages must hold equal block counts. "
                    f"Choose n_block as a multiple of pp (or shrink pp).")
            return P("pp")
        return P()

    def _apply_block_stack(self, stacked, h, training):
        """Sequentially apply ``k`` stacked blocks (leaves (k, ...)) — the
        per-stage body inside the pipeline, and the whole model off-mesh."""
        k = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        for j in range(k):
            p_j = jax.tree_util.tree_map(lambda p: p[j], stacked)
            h, _ = self.block.apply(p_j, {}, h, training=training)
        return h

    def apply_features(self, params, x, *, training=False, rng=None):
        ids = jnp.asarray(x, jnp.int32)
        h = jnp.take(params["token_embeddings"], ids, axis=0)
        h = h + params["pos_embeddings"][: ids.shape[1]][None]
        h = as_compute(h)
        mesh, pp = self._pp_mesh()
        if pp > 1:
            if self.n_block % pp:
                raise ValueError(f"n_block={self.n_block} not divisible by "
                                 f"pp={pp}")
            from ..parallel.pipeline import pipeline_apply

            k = self.n_block // pp
            # (n_block, ...) -> (pp, k, ...): sharded P('pp') on the leading
            # axis this regroup is device-local (contiguous blocks per stage)
            stages = jax.tree_util.tree_map(
                lambda p: p.reshape((pp, k) + p.shape[1:]), params["blocks"])
            h = pipeline_apply(
                lambda sp, a: self._apply_block_stack(sp, a, training),
                stages, h, mesh, n_microbatches=self.n_microbatches)
        else:
            h = self._apply_block_stack(params["blocks"], h, training)
        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        return h

    def apply(self, params, state, x, *, training=False, rng=None):
        h = self.apply_features(params, x, training=training, rng=rng)
        logits = h @ jnp.asarray(params["logits_kernel"], h.dtype)
        return logits, state

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.vocab,)

    def constructor_config(self):
        return dict(vocab=self.vocab, hidden_size=self.hidden_size,
                    n_block=self.n_block, n_head=self.block.attn.n_head,
                    seq_len=self.seq_len,
                    intermediate_size=self.intermediate_size,
                    n_microbatches=self.n_microbatches,
                    attn_strategy=self.attn_strategy)


def lm_loss(y_true, logits):
    """Next-token cross entropy over (B, T) int targets and (B, T, V) logits.

    lse-form (CE = logsumexp(z) − z[label]) so only (B, T) reductions
    materialize in f32 — the log_softmax form writes a second full (B, T, V)
    f32 tensor, which at batch 32 × seq 2048 × 32k vocab is 8 GB of HBM
    traffic per step for no mathematical difference."""
    logits = jnp.asarray(logits, jnp.float32)
    labels = jnp.asarray(y_true, jnp.int32)
    lse = jax.nn.logsumexp(logits, axis=-1)                      # (B, T)
    picked = jnp.take_along_axis(logits, labels[..., None],
                                 axis=-1)[..., 0]                # (B, T)
    return jnp.mean(lse - picked)
