"""TransformerLM — the flagship distributed model (causal LM / classifier).

The reference exposes transformer capability as layers (TransformerLayer.scala,
BERT.scala) used by the text estimators (tfpark/text/). Here the flagship model
additionally exercises every parallelism axis: batch over dp/fsdp, params over
fsdp+tp (megatron layout, parallel.sharding.TP_RULES), sequence over sp via
ring/Ulysses attention. This is the model behind ``__graft_entry__``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import layers as L
from ..nn.layers.attention import TransformerLayer
from ..nn.layers.normalization import LayerNormalization
from ..nn.module import Layer, as_compute, get_initializer, param_dtype
from ..nn.topology import KerasNet
from .common.zoo_model import register_model


@register_model("TransformerLM")
class TransformerLM(Layer, KerasNet):
    """Decoder-only transformer over int token ids (B, T) → logits (B, T, V)."""

    def __init__(self, vocab: int, hidden_size: int = 256, n_block: int = 4,
                 n_head: int = 8, seq_len: int = 512,
                 intermediate_size: Optional[int] = None,
                 attn_strategy: str = "auto", remat: bool = False, name=None):
        super().__init__(name=name)
        self.vocab = vocab
        self.hidden_size = hidden_size
        self.n_block = n_block
        self.seq_len = seq_len
        self.intermediate_size = intermediate_size
        self.attn_strategy = attn_strategy
        self.remat = remat
        self.blocks = [
            TransformerLayer(hidden_size, n_head, intermediate_size, causal=True,
                             attn_strategy=attn_strategy,
                             name=f"{self.name}_block{i}")
            for i in range(n_block)
        ]
        self.ln_f = LayerNormalization(name=f"{self.name}_lnf")
        self.layers = list(self.blocks) + [self.ln_f]  # canonical order (persistence)

    @property
    def input_shape(self):
        return (self.seq_len,)

    def build(self, rng, input_shape=None):
        ks = jax.random.split(rng, self.n_block + 3)
        params = {
            "token_embeddings": jax.random.normal(
                ks[0], (self.vocab, self.hidden_size), param_dtype()) * 0.02,
            "pos_embeddings": jax.random.normal(
                ks[1], (self.seq_len, self.hidden_size), param_dtype()) * 0.02,
            "logits_kernel": get_initializer("glorot_uniform")(
                ks[2], (self.hidden_size, self.vocab), param_dtype()),
        }
        for i, blk in enumerate(self.blocks):
            p, _ = blk.build(ks[3 + i], (None, self.hidden_size))
            params[f"block{i}"] = p
        lnf, _ = self.ln_f.build(ks[-1], (None, self.hidden_size))
        params["ln_f"] = lnf
        return params, {}

    def apply_features(self, params, x, *, training=False, rng=None):
        """Hidden states BEFORE the LM head: (B, T, hidden).

        Pair with :func:`analytics_zoo_tpu.ops.fused_ce.fused_softmax_xent`
        (``fused_softmax_xent(h, params["logits_kernel"], labels)``) to train
        without ever materializing the (B, T, vocab) logits — at vocab 32k
        the f32 logits are 1 GB per 8k tokens, which is what pushes big
        batches into rematerialization."""
        ids = jnp.asarray(x, jnp.int32)
        h = jnp.take(params["token_embeddings"], ids, axis=0)
        h = h + params["pos_embeddings"][: ids.shape[1]][None]
        h = as_compute(h)
        rngs = (jax.random.split(rng, self.n_block) if rng is not None
                else [None] * self.n_block)

        for i, blk in enumerate(self.blocks):
            apply_fn = blk.apply
            if self.remat:
                # trade FLOPs for HBM: recompute block activations in backward
                apply_fn = jax.checkpoint(
                    lambda p, h, blk=blk, r=rngs[i]: blk.apply(
                        p, {}, h, training=training, rng=r)[0])
                h = apply_fn(params[f"block{i}"], h)
            else:
                h, _ = blk.apply(params[f"block{i}"], {}, h, training=training,
                                 rng=rngs[i])
        h, _ = self.ln_f.apply(params["ln_f"], {}, h)
        return h

    def apply(self, params, state, x, *, training=False, rng=None):
        h = self.apply_features(params, x, training=training, rng=rng)
        logits = h @ jnp.asarray(params["logits_kernel"], h.dtype)
        return logits, state

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.vocab,)

    def constructor_config(self):
        return dict(vocab=self.vocab, hidden_size=self.hidden_size,
                    n_block=self.n_block, n_head=self.blocks[0].attn.n_head,
                    seq_len=self.seq_len,
                    intermediate_size=self.intermediate_size,
                    attn_strategy=self.attn_strategy, remat=self.remat)


def lm_loss(y_true, logits):
    """Next-token cross entropy over (B, T) int targets and (B, T, V) logits.

    lse-form (CE = logsumexp(z) − z[label]) so only (B, T) reductions
    materialize in f32 — the log_softmax form writes a second full (B, T, V)
    f32 tensor, which at batch 32 × seq 2048 × 32k vocab is 8 GB of HBM
    traffic per step for no mathematical difference."""
    logits = jnp.asarray(logits, jnp.float32)
    labels = jnp.asarray(y_true, jnp.int32)
    lse = jax.nn.logsumexp(logits, axis=-1)                      # (B, T)
    picked = jnp.take_along_axis(logits, labels[..., None],
                                 axis=-1)[..., 0]                # (B, T)
    return jnp.mean(lse - picked)
