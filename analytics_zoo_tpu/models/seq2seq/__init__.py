from .seq2seq import Bridge, RNNDecoder, RNNEncoder, Seq2seq

__all__ = ["Bridge", "RNNDecoder", "RNNEncoder", "Seq2seq"]
