"""Seq2seq — generic RNN encoder + bridge + decoder model.

Parity: /root/reference/pyzoo/zoo/models/seq2seq/seq2seq.py:30-295 and
.../models/seq2seq/ (Scala ~875 LoC): ``RNNEncoder``/``RNNDecoder`` (stacked
lstm|gru|simplernn with optional embedding), ``Bridge`` (dense | densenonlinear |
customized) mapping encoder final states to decoder initial states, ``Seq2seq``
with teacher-forced training and step-wise ``infer``.

TPU-native design: encoder and decoder both run their stacked RNNs as ``lax.scan``
chains carrying explicit state tuples — encoder final carries flow to the decoder
as plain pytrees, no SelectTable graph surgery (seq2seq.py:215-221). ``infer`` is a
greedy loop around ONE jit-compiled single-step decode, so generation reuses the
compiled step instead of retracing per length.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...nn import layers as L
from ...nn.layers.recurrent import GRU, LSTM, SimpleRNN, _RNNBase
from ...nn.module import Layer, as_compute, split_rng
from ...nn.topology import KerasNet

_RNN_TYPES = {"lstm": LSTM, "gru": GRU, "simplernn": SimpleRNN}


def _create_rnns(rnn_type: str, nlayers: int, hidden_size: int) -> List[_RNNBase]:
    """lstm | gru | simplernn stack (seq2seq.py:31-41 ``createRNN`` parity)."""
    try:
        cls = _RNN_TYPES[rnn_type.lower()]
    except KeyError:
        raise Exception("Only support lstm|gru|simplernn")
    return [cls(hidden_size, return_sequences=True) for _ in range(nlayers)]


def _scan_rnn(layer: _RNNBase, params, x, carry0=None):
    """Run one RNN layer over (B, T, D) with explicit carry in/out."""
    p = {k: jnp.asarray(v, x.dtype) for k, v in params.items()}
    if carry0 is None:
        carry0 = layer.initial_carry(x.shape[0], x.dtype)

    def step(c, x_t):
        c2, o = layer.step(p, c, x_t)
        return c2, o

    carry, outs = jax.lax.scan(step, carry0, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(outs, 0, 1), carry


class _RNNStack:
    """Shared encoder/decoder core: optional embedding + stacked RNNs."""

    def __init__(self, rnns: Sequence[_RNNBase], embedding: Optional[Layer] = None):
        self.rnns = list(rnns)
        self.embedding = embedding

    @property
    def hidden_size(self) -> int:
        return self.rnns[-1].output_dim

    def build(self, rng, input_shape):
        params = {}
        rngs = split_rng(rng, len(self.rnns) + 1)
        shape = tuple(input_shape)
        if self.embedding is not None:
            p, s = self.embedding.build(rngs[0], shape)
            params["embedding"] = {"params": p, "state": s}
            shape = self.embedding.compute_output_shape(shape)
        for i, (r, rnn) in enumerate(zip(rngs[1:], self.rnns)):
            p, _ = rnn.build(r, shape)
            params[f"rnn_{i}"] = p
            shape = (shape[0], rnn.output_dim)
        return params

    def embed(self, params, x):
        if self.embedding is None:
            return as_compute(x)
        slot = params["embedding"]
        y, _ = self.embedding.apply(slot["params"], slot["state"], x)
        return y

    def run(self, params, x, carries: Optional[List] = None):
        """(B, T, D) → (outputs (B, T, H), final carries per layer)."""
        h = self.embed(params, x)
        finals = []
        for i, rnn in enumerate(self.rnns):
            c0 = carries[i] if carries is not None else None
            h, c = _scan_rnn(rnn, params[f"rnn_{i}"], h, c0)
            finals.append(c)
        return h, finals

    def step(self, params, x_t, carries: List):
        """Single timestep (B, D) → (output (B, H), new carries). For infer."""
        h = self.embed(params, x_t[:, None] if self.embedding is not None else x_t)
        if self.embedding is not None:
            h = h[:, 0]  # embedding adds a time axis for (B,) int input
        new_carries = []
        for i, rnn in enumerate(self.rnns):
            p = {k: jnp.asarray(v, h.dtype) for k, v in params[f"rnn_{i}"].items()}
            c, h = rnn.step(p, carries[i], h)
            new_carries.append(c)
        return h, new_carries


class RNNEncoder(_RNNStack):
    """Stacked-RNN encoder (seq2seq.py:42-80 parity)."""

    def __init__(self, rnns, embedding=None, input_shape=None):
        super().__init__(rnns, embedding)
        self.input_shape_hint = tuple(input_shape) if input_shape else None
        self.spec = None

    @classmethod
    def initialize(cls, rnn_type: str, nlayers: int, hidden_size: int,
                   embedding=None, input_shape=None) -> "RNNEncoder":
        enc = cls(_create_rnns(rnn_type, nlayers, hidden_size), embedding, input_shape)
        enc.spec = dict(rnn_type=rnn_type, nlayers=nlayers, hidden_size=hidden_size)
        return enc


class RNNDecoder(_RNNStack):
    """Stacked-RNN decoder (seq2seq.py:82-120 parity)."""

    def __init__(self, rnns, embedding=None, input_shape=None):
        super().__init__(rnns, embedding)
        self.input_shape_hint = tuple(input_shape) if input_shape else None
        self.spec = None

    @classmethod
    def initialize(cls, rnn_type: str, nlayers: int, hidden_size: int,
                   embedding=None, input_shape=None) -> "RNNDecoder":
        dec = cls(_create_rnns(rnn_type, nlayers, hidden_size), embedding, input_shape)
        dec.spec = dict(rnn_type=rnn_type, nlayers=nlayers, hidden_size=hidden_size)
        return dec


class Bridge:
    """Transforms encoder final states → decoder initial states
    (seq2seq.py:122-158 parity: dense | densenonlinear | customized).

    The dense bridge concatenates every encoder state tensor, applies ONE
    ``(B, n·He) @ (n·He, n·Hd)`` GEMM (single MXU pass) and splits back —
    equivalent to the reference's per-state dense transform.
    """

    def __init__(self, bridge_type: str, decoder_hidden_size: int,
                 bridge_fn: Optional[Callable] = None):
        self.bridge_type = bridge_type.lower()
        self.decoder_hidden_size = int(decoder_hidden_size)
        self.bridge_fn = bridge_fn
        if self.bridge_type not in ("dense", "densenonlinear", "customized"):
            raise ValueError("bridge_type must be dense|densenonlinear|customized")

    @classmethod
    def initialize(cls, bridge_type: str, decoder_hidden_size: int) -> "Bridge":
        return cls(bridge_type, decoder_hidden_size)

    @classmethod
    def initialize_from_fn(cls, fn: Callable) -> "Bridge":
        """Custom bridge from a state-pytree → state-pytree function
        (``initialize_from_keras_layer`` parity)."""
        return cls("customized", 0, fn)

    def build(self, rng, enc_states_template, dec_states_template):
        if self.bridge_type == "customized":
            return {}
        enc_leaves = jax.tree_util.tree_leaves(enc_states_template)
        dec_leaves = jax.tree_util.tree_leaves(dec_states_template)
        in_dim = sum(l.shape[-1] for l in enc_leaves)
        out_dim = sum(l.shape[-1] for l in dec_leaves)
        from ...nn.module import glorot_uniform, param_dtype

        return {"kernel": glorot_uniform(rng, (in_dim, out_dim), param_dtype()),
                "bias": jnp.zeros((out_dim,), param_dtype())}

    def apply(self, params, enc_states, dec_states_template):
        if self.bridge_type == "customized":
            return self.bridge_fn(enc_states)
        enc_leaves = jax.tree_util.tree_leaves(enc_states)
        dec_leaves, treedef = jax.tree_util.tree_flatten(dec_states_template)
        flat = jnp.concatenate(enc_leaves, axis=-1)
        y = flat @ jnp.asarray(params["kernel"], flat.dtype) \
            + jnp.asarray(params["bias"], flat.dtype)
        if self.bridge_type == "densenonlinear":
            y = jnp.tanh(y)
        outs, off = [], 0
        for leaf in dec_leaves:
            d = leaf.shape[-1]
            outs.append(y[..., off:off + d])
            off += d
        return jax.tree_util.tree_unflatten(treedef, outs)


class Seq2seq(Layer, KerasNet):
    """Trainable encoder+decoder model (seq2seq.py:160-295 parity).

    Inputs to ``fit``/``apply``: ``[encoder_input, decoder_input]`` (teacher
    forcing). ``generator`` (a Layer applied per-step, e.g.
    ``TimeDistributed(Dense(vocab, activation="softmax"))``) produces the output.
    """

    def __init__(self, encoder: RNNEncoder, decoder: RNNDecoder,
                 input_shape: Sequence[int], output_shape: Sequence[int],
                 bridge: Optional[Bridge] = None, generator: Optional[Layer] = None):
        if input_shape is None or output_shape is None:
            raise TypeError("input_shape and output_shape cannot be None")
        super().__init__(name="seq2seq")
        self.encoder = encoder
        self.decoder = decoder
        self.enc_input_shape = tuple(input_shape)
        self.dec_input_shape = tuple(output_shape)
        self.bridge = bridge
        self.generator = generator
        if bridge is not None and bridge.bridge_type != "customized" \
                and bridge.decoder_hidden_size != decoder.hidden_size:
            raise ValueError(
                f"Bridge(decoder_hidden_size={bridge.decoder_hidden_size}) does "
                f"not match the decoder's hidden size {decoder.hidden_size}")

    # -- module interface ------------------------------------------------------
    def _state_templates(self):
        def carries(rnns):
            return [r.initial_carry(1, jnp.float32) for r in rnns]

        return carries(self.encoder.rnns), carries(self.decoder.rnns)

    def build(self, rng, input_shape=None):
        k_enc, k_dec, k_br, k_gen = jax.random.split(rng, 4)
        params = {
            "encoder": self.encoder.build(k_enc, self.enc_input_shape),
            "decoder": self.decoder.build(k_dec, self.dec_input_shape),
        }
        if self.bridge is not None:
            enc_t, dec_t = self._state_templates()
            p = self.bridge.build(k_br, enc_t, dec_t)
            if p:
                params["bridge"] = p
        if self.generator is not None:
            dec_out_shape = (self.dec_input_shape[0], self.decoder.hidden_size)
            p, _ = self.generator.build(k_gen, dec_out_shape)
            if p:
                params["generator"] = p
        return params, {}

    def _decoder_init_states(self, params, enc_finals):
        _, dec_t = self._state_templates()
        if self.bridge is not None:
            return self.bridge.apply(params.get("bridge", {}), enc_finals, dec_t)
        # no bridge: pass encoder finals straight through (shapes must match)
        return enc_finals

    def apply(self, params, state, x, *, training=False, rng=None):
        enc_in, dec_in = x
        _, enc_finals = self.encoder.run(params["encoder"], enc_in)
        init = self._decoder_init_states(params, enc_finals)
        dec_out, _ = self.decoder.run(params["decoder"], dec_in, init)
        if self.generator is not None:
            dec_out, _ = self.generator.apply(params.get("generator", {}), {},
                                              dec_out, training=training, rng=rng)
        return dec_out, state

    def compute_output_shape(self, input_shape):
        out = (self.dec_input_shape[0], self.decoder.hidden_size)
        if self.generator is not None:
            out = self.generator.compute_output_shape(out)
        return out

    # -- inference -------------------------------------------------------------
    def infer(self, input: np.ndarray, start_sign: np.ndarray, max_seq_len: int = 30,
              stop_sign: Optional[np.ndarray] = None,
              build_output: Optional[Callable] = None) -> np.ndarray:
        """Greedy step-wise generation (seq2seq.py:263-295 parity).

        ``input``: (B, T_in, ...) encoder input; ``start_sign``: (B, ...) first
        decoder input; ``build_output``: maps a decoder output to the next decoder
        input (default: identity). Stops early if every output equals
        ``stop_sign``.
        """
        self._require_compiled()
        est = self.estimator
        params = est.params
        enc_in = jnp.asarray(input)

        # jitted closures cached on self: repeated infer() calls (a serving loop)
        # reuse the compiled step instead of retracing per invocation
        if not hasattr(self, "_infer_fns"):
            @jax.jit
            def encode(p, e):
                _, enc_finals = self.encoder.run(p["encoder"], e)
                return self._decoder_init_states(p, enc_finals)

            @jax.jit
            def decode_step(p, x_t, carries):
                h, new_carries = self.decoder.step(p["decoder"], x_t, carries)
                y = h
                if self.generator is not None:
                    # generator is built for (T, H) shapes; feed a length-1 sequence
                    y, _ = self.generator.apply(p.get("generator", {}), {}, h[:, None])
                    y = y[:, 0]
                return y, new_carries

            self._infer_fns = (encode, decode_step)
        encode, decode_step = self._infer_fns

        carries = encode(params, enc_in)
        x_t = jnp.asarray(start_sign)
        outs = []
        for _ in range(max_seq_len):
            y, carries = decode_step(params, x_t, carries)
            outs.append(np.asarray(y))
            if stop_sign is not None and np.allclose(outs[-1], stop_sign):
                break
            x_t = jnp.asarray(build_output(outs[-1])) if build_output else y
        return np.stack(outs, axis=1)

    # -- persistence -----------------------------------------------------------
    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        cfg = None
        if self.encoder.spec and self.decoder.spec and self.generator is None \
                and self.encoder.embedding is None and self.decoder.embedding is None:
            cfg = dict(encoder=self.encoder.spec, decoder=self.decoder.spec,
                       input_shape=list(self.enc_input_shape),
                       output_shape=list(self.dec_input_shape),
                       bridge=(dict(bridge_type=self.bridge.bridge_type,
                                    decoder_hidden_size=self.bridge.decoder_hidden_size)
                               if self.bridge and self.bridge.bridge_type != "customized"
                               else None))
        save_model_bundle(path, self, config={"seq2seq": cfg} if cfg else {})

    @classmethod
    def load_model(cls, path: str) -> "Seq2seq":
        import json
        import os

        with open(os.path.join(path, "config.json")) as f:
            cfg = json.load(f)["config"].get("seq2seq")
        if not cfg:
            raise ValueError(
                "this Seq2seq bundle has a custom architecture (embedding/generator/"
                "custom bridge); rebuild it and call model.load_weights(path)")
        enc = RNNEncoder.initialize(**cfg["encoder"])
        dec = RNNDecoder.initialize(**cfg["decoder"])
        bridge = Bridge.initialize(**cfg["bridge"]) if cfg.get("bridge") else None
        model = cls(enc, dec, cfg["input_shape"], cfg["output_shape"], bridge)
        model.load_weights(path)
        return model
