"""Keras-level sequence-labelling text models: NER, SequenceTagger (POS +
chunk), IntentEntity (joint intent + slots).

Parity: ``pyzoo/zoo/tfpark/text/keras/ner.py:21`` (word+char BiLSTM with a CRF
sequence classifier), ``pos_tagging.py:22`` (SequenceTagger: BiLSTM stack with
softmax-or-CRF chunk head and a POS head) and ``intent_extraction.py:21``
(IntentEntity: multi-task intent classification + slot tagging). The reference
delegates to nlp-architect Keras graphs; here each model is one jittable
module over this repo's Embedding/Bidirectional-LSTM/CRF layers.

TPU-first notes: the char feature extractor reshapes (B, T, W) → (B·T, W) so
the per-word BiLSTM runs as ONE batched scan (no vmap over words); the CRF
loss/decode are dense ``lax.scan`` dynamic programs (nn/layers/crf.py); all
sequence lengths are static — padding rides the label tensor (pad_tag=-1).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...nn.layers.crf import (CRF, crf_decode, crf_log_likelihood,
                              crf_nll_from_packed)
from ...nn.layers.embedding import Embedding
from ...nn.layers.recurrent import LSTM, Bidirectional
from ...nn.module import Layer, get_initializer, param_dtype
from ...nn.topology import KerasNet
from ..common.zoo_model import register_model

PAD_TAG = -1


def masked_tag_loss(y_true, y_pred):
    """Masked sparse CE over (B, T) int tags vs (B, T, E) probabilities."""
    logp = jnp.log(jnp.clip(y_pred.astype(jnp.float32), 1e-12, 1.0))
    mask = (y_true != PAD_TAG).astype(jnp.float32)
    labels = jnp.maximum(y_true, 0)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -(ll * mask).sum() / (mask.sum() + 1e-12)


def crf_tag_loss(y_true, y_pred):
    """CRF NLL given a ``(emissions, packed_energies)`` model output pair;
    PAD_TAG label positions are masked ('pad' crf_mode)."""
    emissions, packed = y_pred
    return crf_nll_from_packed(y_true, emissions, packed, pad_tag=PAD_TAG)


def crf_tag_loss_reg(y_true, y_pred):
    """CRF NLL scoring FULL-length sequences — the reference's 'reg' crf_mode
    (all sequences equal length, no masking)."""
    emissions, packed = y_pred
    mask = jnp.ones(y_true.shape, bool)
    trans, start, end = CRF.unpack(packed[0])
    ll = crf_log_likelihood(emissions, jnp.maximum(y_true, 0), mask,
                            trans, start, end)
    return -jnp.mean(ll)


def _dense_params(rng, in_dim, out):
    k = get_initializer("glorot_uniform")(rng, (in_dim, out), param_dtype())
    return {"kernel": k, "bias": jnp.zeros((out,), param_dtype())}


def _dense(p, x):
    return x @ jnp.asarray(p["kernel"], x.dtype) + jnp.asarray(p["bias"], x.dtype)


def _dropout(x, rate, training, rng):
    if not training or rate <= 0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class _WordCharEncoder(Layer):
    """[word_ids (B, T), char_ids (B, T, W)] → (B, T, D_word + 2·char_dim):
    word embeddings concatenated with a per-word char-BiLSTM summary."""

    def __init__(self, word_vocab_size, char_vocab_size, word_emb_dim,
                 char_emb_dim, char_lstm_dim=None, name=None):
        super().__init__(name=name)
        self.word_emb = Embedding(word_vocab_size, word_emb_dim,
                                  name=f"{self.name}_wemb")
        self.char_emb = Embedding(char_vocab_size, char_emb_dim,
                                  name=f"{self.name}_cemb")
        self.char_rnn = Bidirectional(
            LSTM(char_lstm_dim or char_emb_dim, name=f"{self.name}_clstm"))
        self.out_dim = word_emb_dim + 2 * (char_lstm_dim or char_emb_dim)
        self._char_emb_dim = char_emb_dim

    def build(self, rng, input_shape=None):
        k1, k2, k3 = jax.random.split(rng, 3)
        wp, _ = self.word_emb.build(k1, None)
        cp, _ = self.char_emb.build(k2, None)
        rp, _ = self.char_rnn.build(k3, (None, self._char_emb_dim))
        return {"word": wp, "char": cp, "char_rnn": rp}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        word_ids, char_ids = x
        w, _ = self.word_emb.apply(params["word"], {}, word_ids)
        c, _ = self.char_emb.apply(params["char"], {}, char_ids)
        b, t, wl, d = c.shape
        # one batched scan over (B·T, W, D) — the TPU-friendly layout
        cf, _ = self.char_rnn.apply(params["char_rnn"], {},
                                    c.reshape(b * t, wl, d))
        return jnp.concatenate([w, cf.reshape(b, t, -1)], axis=-1), state


@register_model("NER")
class NER(Layer, KerasNet):
    """Word+char BiLSTM-CRF named-entity tagger (ner.py:21 parity).

    Inputs: [word indices (B, T), char indices (B, T, word_length)].
    Output: ``(emissions (B, T, E), packed CRF energies)`` — train with
    ``model.loss`` (CRF NLL); ``predict_tags`` runs Viterbi decoding.
    ``crf_mode='reg'`` scores full-length sequences (the default, like the
    reference); ``'pad'`` handles padded batches — PAD_TAG label positions
    are masked at training time and word id 0 marks padding at decode.
    """

    # class-level default ('reg'); __init__ rebinds per crf_mode
    loss = staticmethod(crf_tag_loss_reg)

    def __init__(self, num_entities: int, word_vocab_size: int,
                 char_vocab_size: int, word_length: int = 12,
                 word_emb_dim: int = 100, char_emb_dim: int = 30,
                 tagger_lstm_dim: int = 100, dropout: float = 0.5,
                 crf_mode: str = "reg", name=None):
        super().__init__(name=name)
        if crf_mode not in ("reg", "pad"):
            raise ValueError("crf_mode should be either 'reg' or 'pad'")
        self.crf_mode = crf_mode
        self.loss = crf_tag_loss if crf_mode == "pad" else crf_tag_loss_reg
        self.config = dict(num_entities=num_entities,
                           word_vocab_size=word_vocab_size,
                           char_vocab_size=char_vocab_size,
                           word_length=word_length, word_emb_dim=word_emb_dim,
                           char_emb_dim=char_emb_dim,
                           tagger_lstm_dim=tagger_lstm_dim, dropout=dropout,
                           crf_mode=crf_mode)
        self.num_entities = int(num_entities)
        self.dropout = float(dropout)
        self.encoder = _WordCharEncoder(word_vocab_size, char_vocab_size,
                                        word_emb_dim, char_emb_dim,
                                        name=f"{self.name}_enc")
        self.rnn1 = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True,
                                       name=f"{self.name}_tag1"))
        self.rnn2 = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True,
                                       name=f"{self.name}_tag2"))
        self.crf = CRF(self.num_entities, name=f"{self.name}_crf")

    def build(self, rng, input_shape=None):
        ks = jax.random.split(rng, 5)
        enc_p, _ = self.encoder.build(ks[0])
        d = self.encoder.out_dim
        r1, _ = self.rnn1.build(ks[1], (None, d))
        h = 2 * self.rnn1.forward.output_dim
        r2, _ = self.rnn2.build(ks[2], (None, h))
        head = _dense_params(ks[3], h, self.num_entities)
        crf_p, _ = self.crf.build(ks[4])
        return {"enc": enc_p, "rnn1": r1, "rnn2": r2, "head": head,
                "crf": crf_p}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        h, _ = self.encoder.apply(params["enc"], {}, x, training=training)
        h = _dropout(h, self.dropout, training, rng)
        h, _ = self.rnn1.apply(params["rnn1"], {}, h)
        h, _ = self.rnn2.apply(params["rnn2"], {}, h)
        emissions = _dense(params["head"], h)
        return self.crf.apply(params["crf"], {}, emissions)[0], state

    def predict_tags(self, x, batch_size: int = 32):
        """Viterbi-decoded entity ids (B, T). In 'pad' mode word id 0 marks
        padding and those positions decode to tag 0."""
        import numpy as np

        emissions, packed = self.predict(x, batch_size=batch_size)
        trans, start, end = CRF.unpack(jnp.asarray(packed[0]))
        if self.crf_mode == "pad":
            words = x[0] if isinstance(x, (list, tuple)) else x
            mask = jnp.asarray(words) != 0
        else:
            mask = jnp.ones(emissions.shape[:2], bool)
        return np.asarray(crf_decode(jnp.asarray(emissions), mask,
                                     trans, start, end))

    def compute_output_shape(self, input_shape):
        t = input_shape[0][0] if input_shape else None
        return [(t, self.num_entities),
                (self.num_entities + 2, self.num_entities)]

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.config)

    @classmethod
    def load_model(cls, path: str) -> "NER":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        model.compile(optimizer="adam", loss=model.loss)  # ready to predict
        return model


@register_model("SequenceTagger")
class SequenceTagger(Layer, KerasNet):
    """Three-BiLSTM sentence tagger with POS and chunk heads
    (pos_tagging.py:22 parity).

    Inputs: word indices (B, T), plus char indices (B, T, word_length) when
    ``char_vocab_size`` is set. Outputs ``(pos_probs (B, T, P),
    chunk_probs (B, T, C))`` with ``classifier='softmax'`` — train with
    ``SequenceTagger.loss`` — or ``(pos_probs, chunk_emissions, packed)`` with
    ``classifier='crf'`` and ``SequenceTagger.crf_loss`` (labels y = (pos,
    chunk) int pairs, PAD_TAG-padded).
    """

    def __init__(self, num_pos_labels: int, num_chunk_labels: int,
                 word_vocab_size: int, char_vocab_size: Optional[int] = None,
                 word_length: int = 12, feature_size: int = 100,
                 dropout: float = 0.2, classifier: str = "softmax", name=None):
        super().__init__(name=name)
        classifier = classifier.lower()
        if classifier not in ("softmax", "crf"):
            raise ValueError("classifier should be either softmax or crf")
        self.config = dict(num_pos_labels=num_pos_labels,
                           num_chunk_labels=num_chunk_labels,
                           word_vocab_size=word_vocab_size,
                           char_vocab_size=char_vocab_size,
                           word_length=word_length, feature_size=feature_size,
                           dropout=dropout, classifier=classifier)
        self.num_pos = int(num_pos_labels)
        self.num_chunk = int(num_chunk_labels)
        self.classifier = classifier
        self.dropout = float(dropout)
        self.has_char = char_vocab_size is not None
        if self.has_char:
            self.encoder = _WordCharEncoder(word_vocab_size, char_vocab_size,
                                            feature_size, feature_size // 2,
                                            name=f"{self.name}_enc")
            in_dim = self.encoder.out_dim
        else:
            self.word_emb = Embedding(word_vocab_size, feature_size,
                                      name=f"{self.name}_wemb")
            in_dim = feature_size
        self._in_dim = in_dim
        self.rnns = [Bidirectional(LSTM(feature_size, return_sequences=True,
                                        name=f"{self.name}_l{i}"))
                     for i in range(3)]
        if classifier == "crf":
            self.crf = CRF(self.num_chunk, name=f"{self.name}_crf")

    @staticmethod
    def loss(y_true, y_pred):
        """softmax mode: summed masked CE of the POS and chunk heads."""
        pos_y, chunk_y = y_true
        pos_p, chunk_p = y_pred
        return masked_tag_loss(pos_y, pos_p) + masked_tag_loss(chunk_y, chunk_p)

    @staticmethod
    def crf_loss(y_true, y_pred):
        """crf mode: POS softmax CE + chunk CRF NLL."""
        pos_y, chunk_y = y_true
        pos_p, chunk_em, packed = y_pred
        return masked_tag_loss(pos_y, pos_p) \
            + crf_tag_loss(chunk_y, (chunk_em, packed))

    def build(self, rng, input_shape=None):
        ks = jax.random.split(rng, 7)
        if self.has_char:
            enc_p, _ = self.encoder.build(ks[0])
            params = {"enc": enc_p}
        else:
            wp, _ = self.word_emb.build(ks[0], None)
            params = {"wemb": wp}
        d = self._in_dim
        for i, rnn in enumerate(self.rnns):
            p, _ = rnn.build(ks[1 + i], (None, d))
            params[f"rnn{i}"] = p
            d = 2 * rnn.forward.output_dim
        params["pos_head"] = _dense_params(ks[4], d, self.num_pos)
        params["chunk_head"] = _dense_params(ks[5], d, self.num_chunk)
        if self.classifier == "crf":
            params["crf"], _ = self.crf.build(ks[6])
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        if self.has_char:
            h, _ = self.encoder.apply(params["enc"], {}, x, training=training)
        else:
            ids = x[0] if isinstance(x, (list, tuple)) else x
            h, _ = self.word_emb.apply(params["wemb"], {}, ids)
        h = _dropout(h, self.dropout, training, rng)
        for i, rnn in enumerate(self.rnns):
            h, _ = rnn.apply(params[f"rnn{i}"], {}, h)
        pos = jax.nn.softmax(
            _dense(params["pos_head"], h).astype(jnp.float32), axis=-1)
        chunk_logits = _dense(params["chunk_head"], h)
        if self.classifier == "crf":
            (em, packed), _ = self.crf.apply(params["crf"], {}, chunk_logits)
            return (pos, em, packed), state
        chunk = jax.nn.softmax(chunk_logits.astype(jnp.float32), axis=-1)
        return (pos, chunk), state

    def compute_output_shape(self, input_shape):
        t = None
        if self.classifier == "crf":
            return [(t, self.num_pos), (t, self.num_chunk),
                    (self.num_chunk + 2, self.num_chunk)]
        return [(t, self.num_pos), (t, self.num_chunk)]

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.config)

    @classmethod
    def load_model(cls, path: str) -> "SequenceTagger":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        loss = cls.crf_loss if model.classifier == "crf" else cls.loss
        model.compile(optimizer="adam", loss=loss)  # ready to predict
        return model


# the reference exposes the same model under the POS-tagging module name
POSTagger = SequenceTagger


@register_model("IntentEntity")
class IntentEntity(Layer, KerasNet):
    """Joint intent classification + slot filling (intent_extraction.py:21
    parity).

    Inputs: [word indices (B, T), char indices (B, T, word_length)].
    Outputs ``(intent_probs (B, num_intents), slot_probs (B, T,
    num_entities))``; train with ``IntentEntity.loss`` on labels
    ``(intent (B,), slots (B, T))`` (slots PAD_TAG-padded).
    """

    def __init__(self, num_intents: int, num_entities: int,
                 word_vocab_size: int, char_vocab_size: int,
                 word_length: int = 12, word_emb_dim: int = 100,
                 char_emb_dim: int = 30, char_lstm_dim: int = 30,
                 tagger_lstm_dim: int = 100, dropout: float = 0.2, name=None):
        super().__init__(name=name)
        self.config = dict(num_intents=num_intents, num_entities=num_entities,
                           word_vocab_size=word_vocab_size,
                           char_vocab_size=char_vocab_size,
                           word_length=word_length, word_emb_dim=word_emb_dim,
                           char_emb_dim=char_emb_dim,
                           char_lstm_dim=char_lstm_dim,
                           tagger_lstm_dim=tagger_lstm_dim, dropout=dropout)
        self.num_intents = int(num_intents)
        self.num_entities = int(num_entities)
        self.dropout = float(dropout)
        self.encoder = _WordCharEncoder(word_vocab_size, char_vocab_size,
                                        word_emb_dim, char_emb_dim,
                                        char_lstm_dim=char_lstm_dim,
                                        name=f"{self.name}_enc")
        self.tagger = Bidirectional(LSTM(tagger_lstm_dim, return_sequences=True,
                                         name=f"{self.name}_tag"))

    @staticmethod
    def loss(y_true, y_pred):
        from ...nn.losses import sparse_categorical_crossentropy

        intent_y, slot_y = y_true
        intent_p, slot_p = y_pred
        return sparse_categorical_crossentropy(intent_y, intent_p) \
            + masked_tag_loss(slot_y, slot_p)

    def build(self, rng, input_shape=None):
        ks = jax.random.split(rng, 4)
        enc_p, _ = self.encoder.build(ks[0])
        tag_p, _ = self.tagger.build(ks[1], (None, self.encoder.out_dim))
        h = 2 * self.tagger.forward.output_dim
        return {"enc": enc_p, "tagger": tag_p,
                "intent_head": _dense_params(ks[2], h, self.num_intents),
                "slot_head": _dense_params(ks[3], h, self.num_entities)}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        h, _ = self.encoder.apply(params["enc"], {}, x, training=training)
        h = _dropout(h, self.dropout, training, rng)
        h, _ = self.tagger.apply(params["tagger"], {}, h)
        # intent reads the mean-pooled tagger states (fixed-shape analog of
        # the reference's final-state readout)
        intent = jax.nn.softmax(
            _dense(params["intent_head"], h.mean(axis=1)).astype(jnp.float32),
            axis=-1)
        slots = jax.nn.softmax(
            _dense(params["slot_head"], h).astype(jnp.float32), axis=-1)
        return (intent, slots), state

    def compute_output_shape(self, input_shape):
        return [(self.num_intents,), (None, self.num_entities)]

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.config)

    @classmethod
    def load_model(cls, path: str) -> "IntentEntity":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        model.compile(optimizer="adam", loss=cls.loss)  # ready to predict
        return model
