"""BERTClassifier — sequence classification over the BERT encoder.

Parity: ``pyzoo/zoo/tfpark/text/estimator/bert_classifier.py`` (BERT + dense
head driven by an estimator) and the Keras-layer BERT (BERT.scala). Here the
encoder and head are one compiled program; fit/evaluate/predict come from the
shared KerasNet facade.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...nn.layers.attention import BERT
from ...nn.module import Layer, get_initializer, param_dtype
from ...nn.topology import KerasNet
from ..common.zoo_model import register_model


@register_model("BERTClassifier")
class BERTClassifier(Layer, KerasNet):
    """ids (B, T) [or [ids, segment_ids]] → class probabilities (B, C)."""

    def __init__(self, num_classes: int, vocab: int = 30522,
                 hidden_size: int = 256, n_block: int = 4, n_head: int = 4,
                 seq_len: int = 128, intermediate_size: Optional[int] = None,
                 name=None):
        super().__init__(name=name)
        self.num_classes = int(num_classes)
        self.cfg = dict(vocab=vocab, hidden_size=hidden_size, n_block=n_block,
                        n_head=n_head, seq_len=seq_len,
                        intermediate_size=intermediate_size or 4 * hidden_size)
        self.bert = BERT(vocab=vocab, hidden_size=hidden_size, n_block=n_block,
                         n_head=n_head, seq_len=seq_len,
                         intermediate_size=self.cfg["intermediate_size"],
                         name=f"{self.name}_bert")

    @property
    def input_shape(self):
        return (self.cfg["seq_len"],)

    def build(self, rng, input_shape=None):
        k_bert, k_head = jax.random.split(rng)
        bert_p, _ = self.bert.build(k_bert, input_shape)
        head_k = get_initializer("glorot_uniform")(
            k_head, (self.cfg["hidden_size"], self.num_classes), param_dtype())
        return {"bert": bert_p, "head_kernel": head_k,
                "head_bias": jnp.zeros((self.num_classes,), param_dtype())}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        (_, pooled), _ = self.bert.apply(params["bert"], {}, x,
                                         training=training, rng=rng)
        logits = pooled @ jnp.asarray(params["head_kernel"], pooled.dtype) \
            + jnp.asarray(params["head_bias"], pooled.dtype)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1), state

    def compute_output_shape(self, input_shape):
        return (self.num_classes,)

    def constructor_config(self):
        return dict(num_classes=self.num_classes, **self.cfg)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "BERTClassifier":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        return model
