"""BERTClassifier — sequence classification over the BERT encoder.

Parity: ``pyzoo/zoo/tfpark/text/estimator/bert_classifier.py`` (BERT + dense
head driven by an estimator) and the Keras-layer BERT (BERT.scala). Here the
encoder and head are one compiled program; fit/evaluate/predict come from the
shared KerasNet facade, and the encoder/head plumbing is shared with the
other fine-tune heads (``bert_estimators._BERTHeadBase``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common.zoo_model import register_model
from .bert_estimators import _BERTHeadBase


@register_model("BERTClassifier")
class BERTClassifier(_BERTHeadBase):
    """ids (B, T) [or [ids, segment_ids]] → class probabilities (B, C)."""

    def __init__(self, num_classes: int, dropout: float = 0.0, **kw):
        self.num_classes = int(num_classes)
        super().__init__(head_units=self.num_classes, dropout=dropout, **kw)

    def apply(self, params, state, x, *, training=False, rng=None):
        (_, pooled), _ = self.bert.apply(params["bert"], {}, x,
                                         training=training, rng=rng)
        logits = pooled @ jnp.asarray(params["head_kernel"], pooled.dtype) \
            + jnp.asarray(params["head_bias"], pooled.dtype)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1), state

    def compute_output_shape(self, input_shape):
        return (self.num_classes,)

    def constructor_config(self):
        return dict(num_classes=self.num_classes,
                    **super().constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "BERTClassifier":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        return model
