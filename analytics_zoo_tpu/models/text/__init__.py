from .bert_classifier import BERTClassifier

__all__ = ["BERTClassifier"]
