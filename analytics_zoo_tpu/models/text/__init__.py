from .bert_classifier import BERTClassifier
from .bert_estimators import BERTNER, BERTSQuAD, ner_token_loss, squad_span_loss
from .sequence_models import (NER, IntentEntity, POSTagger, SequenceTagger,
                              crf_tag_loss, crf_tag_loss_reg, masked_tag_loss)

__all__ = ["BERTClassifier", "BERTNER", "BERTSQuAD", "NER", "SequenceTagger",
           "POSTagger", "IntentEntity", "ner_token_loss", "squad_span_loss",
           "crf_tag_loss", "crf_tag_loss_reg", "masked_tag_loss"]
