"""BERT fine-tuning heads beyond classification: token tagging (NER) and
extractive QA (SQuAD).

Parity: ``pyzoo/zoo/tfpark/text/estimator/bert_ner.py:49`` (BERTNER — dense
softmax over the final encoder sequence output, masked token-level
cross-entropy) and ``bert_squad.py:77`` (BERTSQuAD — a 2-unit dense head whose
columns are start/end span logits trained with mean start/end cross-entropy).

TPU-first design notes: where the reference builds a tf.estimator graph per
mode around a JNI-driven BERT, here encoder+head is one jittable program and
fit/evaluate/predict come from the KerasNet facade; padding is carried in the
labels (``pad_tag``/-1) instead of a separate ``input_mask`` feature so the
train step stays a fixed-shape (ids, labels) pair.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...nn.layers.attention import BERT
from ...nn.module import Layer, get_initializer, param_dtype
from ...nn.topology import KerasNet
from ..common.zoo_model import register_model

PAD_TAG = -1


def ner_token_loss(y_true, y_pred):
    """Masked token-level cross-entropy (bert_ner.py:28-37 parity: loss is
    summed over real tokens and normalized by their count). ``y_true`` (B, T)
    int with PAD_TAG on padding; ``y_pred`` (B, T, E) log-probabilities."""
    y_pred = y_pred.astype(jnp.float32)
    mask = (y_true != PAD_TAG).astype(jnp.float32)
    labels = jnp.maximum(y_true, 0)
    ll = jnp.take_along_axis(y_pred, labels[..., None], axis=-1)[..., 0]
    return -(ll * mask).sum() / (mask.sum() + 1e-12)


def squad_span_loss(y_true, y_pred):
    """Mean of start/end position cross-entropies (bert_squad.py:46-60
    parity). ``y_true`` (B, 2) int [start, end]; ``y_pred`` (B, 2, T)
    log-softmax over positions."""
    y_pred = y_pred.astype(jnp.float32)
    ll = jnp.take_along_axis(y_pred, y_true[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]               # (B, 2)
    return -jnp.mean(ll)


class _BERTHeadBase(Layer, KerasNet):
    """Shared encoder plumbing for the fine-tune heads."""

    def __init__(self, head_units: int, vocab: int = 30522,
                 hidden_size: int = 256, n_block: int = 4, n_head: int = 4,
                 seq_len: int = 128, intermediate_size: Optional[int] = None,
                 dropout: float = 0.1, name=None):
        super().__init__(name=name)
        self.head_units = int(head_units)
        self.dropout = float(dropout)
        self.cfg = dict(vocab=vocab, hidden_size=hidden_size, n_block=n_block,
                        n_head=n_head, seq_len=seq_len,
                        intermediate_size=intermediate_size or 4 * hidden_size)
        self.bert = BERT(vocab=vocab, hidden_size=hidden_size, n_block=n_block,
                         n_head=n_head, seq_len=seq_len,
                         intermediate_size=self.cfg["intermediate_size"],
                         name=f"{self.name}_bert")

    @property
    def input_shape(self):
        return (self.cfg["seq_len"],)

    def build(self, rng, input_shape=None):
        k_bert, k_head = jax.random.split(rng)
        bert_p, _ = self.bert.build(k_bert, input_shape)
        head_k = get_initializer("glorot_uniform")(
            k_head, (self.cfg["hidden_size"], self.head_units), param_dtype())
        return {"bert": bert_p, "head_kernel": head_k,
                "head_bias": jnp.zeros((self.head_units,), param_dtype())}, {}

    def _sequence_logits(self, params, x, *, training, rng):
        """(B, T, head_units) logits over the final encoder sequence output."""
        from .sequence_models import _dropout

        k_drop = k_bert = rng
        if rng is not None:
            k_bert, k_drop = jax.random.split(rng)
        (seq, _pooled), _ = self.bert.apply(params["bert"], {}, x,
                                            training=training, rng=k_bert)
        seq = _dropout(seq, self.dropout, training, k_drop)
        return seq @ jnp.asarray(params["head_kernel"], seq.dtype) \
            + jnp.asarray(params["head_bias"], seq.dtype)

    def constructor_config(self):
        return dict(dropout=self.dropout, **self.cfg)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())


@register_model("BERTNER")
class BERTNER(_BERTHeadBase):
    """ids (B, T) [or [ids, segment_ids]] → per-token entity log-probs
    (B, T, num_entities). Train with :func:`ner_token_loss` (labels padded
    with PAD_TAG); cased vocabularies recommended, as in the reference."""

    def __init__(self, num_entities: int, **kw):
        self.num_entities = int(num_entities)
        super().__init__(head_units=self.num_entities, **kw)

    loss = staticmethod(ner_token_loss)

    def apply(self, params, state, x, *, training=False, rng=None):
        logits = self._sequence_logits(params, x, training=training, rng=rng)
        return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1), state

    def predict_tags(self, x, batch_size: int = 32):
        """argmax entity ids (B, T) — the PREDICT-mode output of the
        reference's estimator spec (bert_ner.py:41-43)."""
        import numpy as np

        logp = self.predict(x, batch_size=batch_size)
        return np.argmax(np.asarray(logp), axis=-1)

    def compute_output_shape(self, input_shape):
        return (self.cfg["seq_len"], self.num_entities)

    def constructor_config(self):
        return dict(num_entities=self.num_entities,
                    **super().constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "BERTNER":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        model.compile(optimizer="adam", loss=cls.loss)  # ready to predict
        return model


@register_model("BERTSQuAD")
class BERTSQuAD(_BERTHeadBase):
    """ids (B, T) [or [ids, segment_ids]] → (B, 2, T) start/end position
    log-probs. Train with :func:`squad_span_loss` on (B, 2) [start, end]
    labels; ``predict_spans`` returns the argmax span per example."""

    def __init__(self, **kw):
        super().__init__(head_units=2, **kw)

    loss = staticmethod(squad_span_loss)

    def apply(self, params, state, x, *, training=False, rng=None):
        logits = self._sequence_logits(params, x, training=training, rng=rng)
        # (B, T, 2) -> (B, 2, T): each row is a distribution over positions
        logits = jnp.swapaxes(logits, 1, 2).astype(jnp.float32)
        return jax.nn.log_softmax(logits, axis=-1), state

    def predict_spans(self, x, batch_size: int = 32):
        """(start, end) argmax positions, each (B,) — the reference PREDICT
        output carries start/end logits per unique_id (bert_squad.py:64-69)."""
        import numpy as np

        logp = np.asarray(self.predict(x, batch_size=batch_size))
        return logp[:, 0].argmax(-1), logp[:, 1].argmax(-1)

    def compute_output_shape(self, input_shape):
        return (2, self.cfg["seq_len"])

    @classmethod
    def load_model(cls, path: str) -> "BERTSQuAD":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        model.compile(optimizer="adam", loss=cls.loss)  # ready to predict
        return model
