"""AnomalyDetector — stacked-LSTM forecaster; anomalies = largest forecast errors.

Parity: /root/reference/pyzoo/zoo/models/anomalydetection/anomaly_detector.py:30-184
and .../models/anomalydetection/AnomalyDetector.scala — stacked LSTM + dropout →
Dense(1), with the ``unroll`` / ``detect_anomalies`` / ``train_test_split`` helpers.

The reference's helpers run as RDD jobs; here they are vectorized numpy (host) —
unrolling a series is a stride trick, not a cluster job.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...nn import layers as L
from ...nn.topology import Sequential
from ..common.zoo_model import register_model


@register_model("AnomalyDetector")
class AnomalyDetector(Sequential):
    """LSTM anomaly detector (anomaly_detector.py:40-75 parity).

    Args:
        feature_shape: (unroll_length, feature_size).
        hidden_layers: LSTM widths per layer.
        dropouts: dropout fraction after each LSTM.
    """

    def __init__(self, feature_shape: Sequence[int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2)):
        assert len(hidden_layers) == len(dropouts), \
            "sizes of dropouts and hidden_layers should be equal"
        super().__init__(name="anomaly_detector")
        self.feature_shape = tuple(int(s) for s in feature_shape)
        self.hidden_layers = [int(u) for u in hidden_layers]
        self.dropouts = [float(d) for d in dropouts]

        self.add(L.InputLayer(self.feature_shape))
        self.add(L.LSTM(self.hidden_layers[0], return_sequences=True,
                        input_shape=self.feature_shape))
        for h, d in zip(self.hidden_layers[1:-1], self.dropouts[1:-1]):
            self.add(L.LSTM(h, return_sequences=True))
            self.add(L.Dropout(d))
        self.add(L.LSTM(self.hidden_layers[-1], return_sequences=False))
        self.add(L.Dropout(self.dropouts[-1]))
        self.add(L.Dense(1))

    def constructor_config(self) -> dict:
        return dict(feature_shape=list(self.feature_shape),
                    hidden_layers=self.hidden_layers, dropouts=self.dropouts)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "AnomalyDetector":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        return model

    # ---- reference static helpers (anomaly_detector.py:105-150) --------------
    unroll = staticmethod(lambda data, unroll_length, predict_step=1: unroll(
        data, unroll_length, predict_step))
    detect_anomalies = staticmethod(lambda y_truth, y_predict, anomaly_size:
                                    detect_anomalies(y_truth, y_predict, anomaly_size))

    @staticmethod
    def standard_scale(data: np.ndarray) -> np.ndarray:
        return standard_scale(data)

    @staticmethod
    def train_test_split(x: np.ndarray, y: np.ndarray, test_size: int):
        """Chronological split — LAST ``test_size`` rows become test
        (anomaly_detector.py:146 parity: cut at count - test_size)."""
        cut = len(x) - int(test_size)
        return (x[:cut], y[:cut]), (x[cut:], y[cut:])


def unroll(data: np.ndarray, unroll_length: int,
           predict_step: int = 1) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding-window unroll of a series into (features, labels)
    (anomaly_detector.py:105-127 parity: data (1..6), len 2, step 1 →
    features [[1,2],[2,3],...], labels [3,4,...]).

    Returns ``x: (N, unroll_length, F)`` and ``y: (N,)`` (first feature column is
    the prediction target, matching the reference example pipelines).
    """
    data = np.asarray(data, dtype="float32")
    if data.ndim == 1:
        data = data[:, None]
    n = len(data) - unroll_length - predict_step + 1
    if n <= 0:
        raise ValueError("series too short for the requested unroll_length")
    idx = np.arange(unroll_length)[None, :] + np.arange(n)[:, None]
    x = data[idx]
    y = data[np.arange(n) + unroll_length + predict_step - 1, 0]
    return x, y


def standard_scale(data: np.ndarray) -> np.ndarray:
    """Column-wise standardization (``standardScaleDF`` parity)."""
    data = np.asarray(data, dtype="float32")
    mean = data.mean(axis=0, keepdims=True)
    std = data.std(axis=0, keepdims=True)
    return (data - mean) / np.where(std == 0, 1.0, std)


def detect_anomalies(y_truth: np.ndarray, y_predict: np.ndarray,
                     anomaly_size: int) -> np.ndarray:
    """Flag the ``anomaly_size`` points with largest |truth - prediction|
    (anomaly_detector.py:129-138 / AnomalyDetector.scala detectAnomalies parity).

    Returns an array of (y_truth, y_predict, anomaly) where ``anomaly`` is NaN for
    normal points and equals ``y_truth`` at anomalies.
    """
    y_truth = np.asarray(y_truth, dtype="float32").reshape(-1)
    y_predict = np.asarray(y_predict, dtype="float32").reshape(-1)
    err = np.abs(y_truth - y_predict)
    threshold_idx = np.argsort(-err)[:int(anomaly_size)]
    anomaly = np.full_like(y_truth, np.nan)
    anomaly[threshold_idx] = y_truth[threshold_idx]
    return np.stack([y_truth, y_predict, anomaly], axis=1)
