from .anomaly_detector import AnomalyDetector, detect_anomalies, standard_scale, unroll

__all__ = ["AnomalyDetector", "detect_anomalies", "standard_scale", "unroll"]
