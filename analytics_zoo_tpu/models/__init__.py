"""Built-in model zoo (reference: zoo/.../models/, pyzoo/zoo/models/)."""

from . import common, recommendation

__all__ = ["common", "recommendation"]
