"""Built-in model zoo (reference: zoo/.../models/, pyzoo/zoo/models/)."""

from . import (anomalydetection, common, recommendation, seq2seq,
               textclassification, textmatching)

__all__ = ["anomalydetection", "common", "recommendation", "seq2seq",
           "textclassification", "textmatching"]
