from .text_classifier import TextClassifier

__all__ = ["TextClassifier"]
