"""TextClassifier — word embedding + (CNN | LSTM | GRU) encoder + dense head.

Parity: /root/reference/pyzoo/zoo/models/textclassification/text_classifier.py:29-176
and .../models/textclassification/TextClassifier.scala — WordEmbedding first layer,
then Convolution1D+GlobalMaxPooling1D / LSTM / GRU, Dense(128)+Dropout+ReLU,
softmax head.

The reference *requires* a GloVe ``embedding_file``; here the embedding may also be
a trainable random table (``vocab_size``/``embed_dim``) so the model is usable
without a 2GB download — pass ``embedding_file`` for exact reference behavior.
"""

from __future__ import annotations

from typing import Dict, Optional

from ...nn import layers as L
from ...nn.topology import Sequential
from ..common.zoo_model import register_model


@register_model("TextClassifier")
class TextClassifier(Sequential):
    """Args mirror text_classifier.py:53-73: ``class_num``, ``embedding_file``,
    ``word_index``, ``sequence_length``, ``encoder``, ``encoder_output_dim``;
    plus ``vocab_size``/``embed_dim`` for the file-less path."""

    def __init__(self, class_num: int, embedding_file: Optional[str] = None,
                 word_index: Optional[Dict[str, int]] = None,
                 sequence_length: int = 500, encoder: str = "cnn",
                 encoder_output_dim: int = 256, vocab_size: Optional[int] = None,
                 embed_dim: int = 200, frozen_embedding: Optional[bool] = None):
        super().__init__(name="text_classifier")
        self.class_num = int(class_num)
        self.sequence_length = int(sequence_length)
        self.encoder = encoder.lower()
        self.encoder_output_dim = int(encoder_output_dim)
        self.embed_dim = int(embed_dim)

        if embedding_file is not None:
            if word_index is None:
                raise ValueError("word_index is required with embedding_file "
                                 "(use TextSet.get_word_index())")
            embedding = L.WordEmbedding.from_glove(embedding_file, word_index,
                                                   output_dim=embed_dim)
            self.vocab_size = embedding.input_dim
            self.frozen_embedding = True
        else:
            if vocab_size is None:
                vocab_size = (max(word_index.values()) + 1) if word_index else 20000
            self.vocab_size = int(vocab_size)
            self.frozen_embedding = bool(frozen_embedding)
            if self.frozen_embedding:
                # frozen table restored from a saved bundle (load_model path)
                embedding = L.WordEmbedding(self.vocab_size, embed_dim)
            else:
                embedding = L.Embedding(self.vocab_size, embed_dim, init="uniform")
        embedding.input_shape_hint = (self.sequence_length,)

        self.add(embedding)
        if self.encoder == "cnn":
            self.add(L.Convolution1D(self.encoder_output_dim, 5, activation="relu"))
            self.add(L.GlobalMaxPooling1D())
        elif self.encoder == "lstm":
            self.add(L.LSTM(self.encoder_output_dim))
        elif self.encoder == "gru":
            self.add(L.GRU(self.encoder_output_dim))
        else:
            raise ValueError(f"Unsupported encoder for TextClassifier: {encoder}")
        self.add(L.Dense(128))
        self.add(L.Dropout(0.2))
        self.add(L.Activation("relu"))
        self.add(L.Dense(self.class_num, activation="softmax"))

    def constructor_config(self) -> dict:
        return dict(class_num=self.class_num, sequence_length=self.sequence_length,
                    encoder=self.encoder, encoder_output_dim=self.encoder_output_dim,
                    vocab_size=self.vocab_size, embed_dim=self.embed_dim,
                    frozen_embedding=self.frozen_embedding)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "TextClassifier":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        return model
