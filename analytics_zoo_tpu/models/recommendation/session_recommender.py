"""SessionRecommender — GRU over session clicks (+ optional purchase history MLP).

Parity: /root/reference/pyzoo/zoo/models/recommendation/session_recommender.py:30-148
and .../models/recommendation/SessionRecommender.scala — stacked GRU over the
session item sequence, optionally summed-embedding history MLP, merged into a
softmax over the item catalog.

TPU-native: the GRU stack is `lax.scan` with fused-gate GEMMs; the history-embedding
sum is a gather + reduction XLA fuses into one pass.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ...nn import layers as L
from ...nn.graph import Input
from ...nn.layers.merge import merge
from ..common.zoo_model import register_model
from .recommender import Recommender


@register_model("SessionRecommender")
class SessionRecommender(Recommender):
    """Args mirror session_recommender.py:45-57: ``item_count``, ``item_embed``,
    ``rnn_hidden_layers``, ``session_length``, ``include_history``,
    ``mlp_hidden_layers``, ``history_length``."""

    def __init__(self, item_count: int, item_embed: int,
                 rnn_hidden_layers: Sequence[int] = (40, 20), session_length: int = 0,
                 include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20), history_length: int = 0):
        assert session_length > 0, "session_length should align with input features"
        if include_history:
            assert history_length > 0, "history_length should align with input features"
        self.item_count = int(item_count)
        self.item_embed = int(item_embed)
        self.rnn_hidden_layers = [int(u) for u in rnn_hidden_layers]
        self.mlp_hidden_layers = [int(u) for u in mlp_hidden_layers]
        self.session_length = int(session_length)
        self.include_history = include_history
        self.history_length = int(history_length)

        input_rnn = Input((self.session_length,), name="session_input")
        x = L.Embedding(self.item_count + 1, self.item_embed, init="uniform")(input_rnn)
        for h in self.rnn_hidden_layers[:-1]:
            x = L.GRU(h, return_sequences=True)(x)
        x = L.GRU(self.rnn_hidden_layers[-1], return_sequences=False)(x)
        rnn_logits = L.Dense(self.item_count)(x)

        if include_history:
            input_mlp = Input((self.history_length,), name="history_input")
            his = L.Embedding(self.item_count + 1, self.item_embed, init="uniform")(input_mlp)
            # sum over the history positions (reference: Sum(dimension=2) + Flatten)
            pooled = L.Lambda(lambda t: jnp.sum(t, axis=1),
                              output_shape_fn=lambda s: (s[-1],))(his)
            m = pooled
            for h in self.mlp_hidden_layers:
                m = L.Dense(h, activation="relu")(m)
            mlp_logits = L.Dense(self.item_count)(m)
            out = L.Activation("softmax")(merge([rnn_logits, mlp_logits], mode="sum"))
            super().__init__([input_rnn, input_mlp], out, name="session_recommender")
        else:
            out = L.Activation("softmax")(rnn_logits)
            super().__init__(input_rnn, out, name="session_recommender")

    # Session models don't do user/item pair scoring (session_recommender.py:100-110)
    def recommend_for_user(self, *a, **k):
        raise Exception("recommend_for_user: Unsupported for SessionRecommender")

    def recommend_for_item(self, *a, **k):
        raise Exception("recommend_for_item: Unsupported for SessionRecommender")

    def predict_user_item_pair(self, *a, **k):
        raise Exception("predict_user_item_pair: Unsupported for SessionRecommender")

    def recommend_for_session(self, sessions, max_items: int,
                              zero_based_label: bool = True) -> List[List[tuple]]:
        """Top-``max_items`` (item, probability) per session
        (session_recommender.py:106-130 parity; batched device sweep here).

        ``sessions``: ``(B, session_length)`` array, or ``[session, history]``
        arrays for ``include_history`` models.
        """
        if isinstance(sessions, (list, tuple)):
            sessions = [np.asarray(s) for s in sessions]
        probs = np.asarray(self.predict(sessions, batch_size=256))
        top = np.argsort(-probs, axis=-1)[:, :max_items]
        offset = 0 if zero_based_label else 1
        return [[(int(i) + offset, float(p[i])) for i in row]
                for row, p in zip(top, probs)]

    def constructor_config(self) -> dict:
        return dict(item_count=self.item_count, item_embed=self.item_embed,
                    rnn_hidden_layers=self.rnn_hidden_layers,
                    session_length=self.session_length,
                    include_history=self.include_history,
                    mlp_hidden_layers=self.mlp_hidden_layers,
                    history_length=self.history_length)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "SessionRecommender":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        return model
