from .features import (ColumnFeatureInfo, categorical_from_vocab_list,
                       get_boundaries, get_deep_tensors, get_negative_samples,
                       get_wide_tensor, hash_bucket, row_to_sample, rows_to_batch)
from .neuralcf import ImplicitNCF, NeuralCF, implicit_bce_loss
from .recommender import Recommender, UserItemPrediction
from .session_recommender import SessionRecommender
from .wide_and_deep import WideAndDeep

__all__ = ["ColumnFeatureInfo", "ImplicitNCF", "NeuralCF", "implicit_bce_loss", "Recommender", "SessionRecommender",
           "UserItemPrediction", "WideAndDeep", "categorical_from_vocab_list",
           "get_boundaries", "get_deep_tensors", "get_negative_samples",
           "get_wide_tensor", "hash_bucket", "row_to_sample", "rows_to_batch"]
