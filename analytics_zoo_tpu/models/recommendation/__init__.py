from .neuralcf import NeuralCF
from .recommender import Recommender, UserItemPrediction

__all__ = ["NeuralCF", "Recommender", "UserItemPrediction"]
