"""Feature engineering helpers for recommendation models.

Parity: /root/reference/pyzoo/zoo/models/recommendation/utils.py — ``hash_bucket``,
``categorical_from_vocab_list``, ``get_boundaries``, ``get_wide_tensor``,
``get_deep_tensors``, ``row_to_sample``, ``get_negative_samples``.

TPU-native difference: the reference emits per-row BigDL ``Sample``s (the wide part
as a JVM SparseTensor); here the converters emit dense numpy batches — multi-hot
wide vectors batch into one ``(B, wide_dim)`` array that XLA consumes directly, and
sparsity would only slow the MXU down at these widths.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def hash_bucket(content, bucket_size: int = 1000, start: int = 0) -> int:
    """Stable string hash into ``[start, start + bucket_size)`` (utils.py:26).

    Uses a deterministic FNV-1a instead of Python's salted ``hash`` so feature
    columns are reproducible across processes/hosts (required for multi-host
    input pipelines to agree on vocabulary buckets).
    """
    data = str(content).encode("utf-8")
    h = np.uint64(14695981039346656037)
    for b in data:
        h = np.uint64((int(h) ^ b) * 1099511628211 % (1 << 64))
    return int(h % np.uint64(bucket_size)) + start


def categorical_from_vocab_list(sth, vocab_list: Sequence, default: int = -1,
                                start: int = 0) -> int:
    """Index of ``sth`` in ``vocab_list`` (+start), or default (utils.py:30)."""
    if sth in vocab_list:
        return list(vocab_list).index(sth) + start
    return default + start


def get_boundaries(target, boundaries: Sequence[float], default: int = -1,
                   start: int = 0) -> int:
    """Bucketize a continuous value by ``boundaries`` (utils.py:37)."""
    if target == "?":
        return default + start
    for i, b in enumerate(boundaries):
        if target < b:
            return i + start
    return len(boundaries) + start


class ColumnFeatureInfo:
    """Column metadata shared by WideAndDeep and its feature generation
    (wide_and_deep.py:30-97 parity; field semantics identical)."""

    def __init__(self, wide_base_cols=None, wide_base_dims=None,
                 wide_cross_cols=None, wide_cross_dims=None,
                 indicator_cols=None, indicator_dims=None,
                 embed_cols=None, embed_in_dims=None, embed_out_dims=None,
                 continuous_cols=None, label: str = "label"):
        self.wide_base_cols = list(wide_base_cols or [])
        self.wide_base_dims = [int(d) for d in (wide_base_dims or [])]
        self.wide_cross_cols = list(wide_cross_cols or [])
        self.wide_cross_dims = [int(d) for d in (wide_cross_dims or [])]
        self.indicator_cols = list(indicator_cols or [])
        self.indicator_dims = [int(d) for d in (indicator_dims or [])]
        self.embed_cols = list(embed_cols or [])
        self.embed_in_dims = [int(d) for d in (embed_in_dims or [])]
        self.embed_out_dims = [int(d) for d in (embed_out_dims or [])]
        self.continuous_cols = list(continuous_cols or [])
        self.label = label

    def to_dict(self) -> Dict:
        return dict(wide_base_cols=self.wide_base_cols,
                    wide_base_dims=self.wide_base_dims,
                    wide_cross_cols=self.wide_cross_cols,
                    wide_cross_dims=self.wide_cross_dims,
                    indicator_cols=self.indicator_cols,
                    indicator_dims=self.indicator_dims,
                    embed_cols=self.embed_cols,
                    embed_in_dims=self.embed_in_dims,
                    embed_out_dims=self.embed_out_dims,
                    continuous_cols=self.continuous_cols,
                    label=self.label)

    @classmethod
    def from_dict(cls, d: Dict) -> "ColumnFeatureInfo":
        return cls(**d)

    @property
    def wide_dim(self) -> int:
        return sum(self.wide_base_dims) + sum(self.wide_cross_dims)

    def __repr__(self):
        return f"ColumnFeatureInfo({self.to_dict()})"


def get_wide_tensor(row, column_info: ColumnFeatureInfo) -> np.ndarray:
    """Multi-hot wide vector for one row (utils.py:52 parity; dense here)."""
    wide_cols = column_info.wide_base_cols + column_info.wide_cross_cols
    wide_dims = column_info.wide_base_dims + column_info.wide_cross_dims
    out = np.zeros((sum(wide_dims),), dtype="float32")
    acc = 0
    for i, col in enumerate(wide_cols):
        if i > 0:
            acc += wide_dims[i - 1]
        out[acc + int(row[col])] = 1.0
    return out


def get_deep_tensors(row, column_info: ColumnFeatureInfo) -> List[np.ndarray]:
    """Deep-side tensors [indicator?, embed?, continuous?] (utils.py:78 parity)."""
    ci = column_info
    tensors: List[np.ndarray] = []
    if ci.indicator_cols:
        ind = np.zeros((sum(ci.indicator_dims),), dtype="float32")
        acc = 0
        for i, col in enumerate(ci.indicator_cols):
            if i > 0:
                acc += ci.indicator_dims[i - 1]
            ind[acc + int(row[col])] = 1.0
        tensors.append(ind)
    if ci.embed_cols:
        tensors.append(np.asarray([float(row[c]) for c in ci.embed_cols], dtype="float32"))
    if ci.continuous_cols:
        tensors.append(np.asarray([float(row[c]) for c in ci.continuous_cols],
                                  dtype="float32"))
    if not tensors:
        raise TypeError("Empty deep tensors")
    return tensors


def row_to_sample(row, column_info: ColumnFeatureInfo,
                  model_type: str = "wide_n_deep") -> Tuple[List[np.ndarray], float]:
    """Convert one row to (features, label) (utils.py:135 parity)."""
    model_type = model_type.lower()
    label = float(row[column_info.label])
    if model_type == "wide":
        return [get_wide_tensor(row, column_info)], label
    if model_type == "deep":
        return get_deep_tensors(row, column_info), label
    if model_type == "wide_n_deep":
        return [get_wide_tensor(row, column_info)] + get_deep_tensors(row, column_info), label
    raise TypeError(f"Unsupported model_type: {model_type}")


def rows_to_batch(rows, column_info: ColumnFeatureInfo,
                  model_type: str = "wide_n_deep"
                  ) -> Tuple[List[np.ndarray], np.ndarray]:
    """Batch many rows into stacked input arrays + labels (TPU-native addition:
    the batched form of ``row_to_sample`` — feeds ``fit`` directly)."""
    feats, labels = [], []
    if hasattr(rows, "iterrows"):
        rows = (r for _, r in rows.iterrows())
    for row in rows:
        f, l = row_to_sample(row, column_info, model_type)
        feats.append(f)
        labels.append(l)
    n_inputs = len(feats[0])
    xs = [np.stack([f[i] for f in feats]) for i in range(n_inputs)]
    return xs, np.asarray(labels, dtype="float32")


def get_negative_samples(indexed, item_col: str = "itemId",
                         user_col: str = "userId", label_col: str = "label",
                         neg_per_pos: int = 1, seed: int = 0):
    """Sample random unseen items per user as negatives (label=1) — parity with
    the JVM ``getNegativeSamples`` used by the NCF notebook (utils.py:47;
    Scala .../models/recommendation/Utils.scala). Input/output: pandas DataFrame."""
    import pandas as pd

    rng = np.random.default_rng(seed)
    items = indexed[item_col].unique()
    seen = indexed.groupby(user_col)[item_col].agg(set).to_dict()
    users, negs = [], []
    for u, pos_items in seen.items():
        need = neg_per_pos * len(pos_items)
        cand = rng.choice(items, size=min(need * 3 + 8, len(items)), replace=False)
        take = [i for i in cand if i not in pos_items][:need]
        users.extend([u] * len(take))
        negs.extend(take)
    return pd.DataFrame({user_col: users, item_col: negs,
                         label_col: np.ones(len(negs), dtype="int64")})
