"""NeuralCF — neural collaborative filtering (the north-star model).

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/models/
recommendation/NeuralCF.scala:45-103 and
/root/reference/pyzoo/zoo/models/recommendation/neuralcf.py:30-97 — GMF + MLP
dual-embedding towers over (user, item) pairs, merged into a softmax rating head.

TPU-native notes:
* The four embedding tables are HBM gathers; under tensor parallelism they shard
  row-wise over the ``tp`` axis (see analytics_zoo_tpu.parallel.sharding).
* The whole forward is one fused XLA program; the MLP matmuls land on the MXU. The
  batch is the only meaningful FLOP axis, so throughput scales with dp sharding.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ...nn import layers as L
from ...nn.graph import Input
from ...nn.layers.merge import merge
from ..common.zoo_model import register_model
from .recommender import Recommender


@register_model("NeuralCF")
class NeuralCF(Recommender):
    """GMF + MLP recommender.

    Args mirror the reference constructor (NeuralCF.scala:45-53): ``user_count``,
    ``item_count``, ``class_num``, ``user_embed``, ``item_embed``,
    ``hidden_layers``, ``include_mf``, ``mf_embed``.
    """

    def __init__(self, user_count: int, item_count: int, class_num: int,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20):
        self.user_count = user_count
        self.item_count = item_count
        self.class_num = class_num
        self.user_embed = user_embed
        self.item_embed = item_embed
        self.hidden_layers = list(hidden_layers)
        self.include_mf = include_mf
        self.mf_embed = mf_embed

        if include_mf:
            assert mf_embed > 0, "provide a meaningful number of mf embedding units"

        # (B, 2) int input: [:, 0]=user id, [:, 1]=item id (NeuralCF.scala:57-60)
        pair = Input((2,), name="user_item_pair")

        # All four logical tables (mlp_user/mlp_item/mf_user/mf_item,
        # NeuralCF.scala:61-78) in ONE gather; +1 rows: ids are 1-based in the
        # reference datasets (NeuralCF.scala:65-66). Output layout:
        # [user_mlp | item_mlp | mf_user*mf_item].
        fused = L.FusedPairEmbedding(
            user_count + 1, item_count + 1, user_embed, item_embed,
            mf_embed if include_mf else 0, init="normal")(pair)

        mlp = L.Narrow(0, 0, user_embed + item_embed)(fused)
        for h in self.hidden_layers:
            mlp = L.Dense(h, activation="relu")(mlp)

        if include_mf:
            gmf = L.Narrow(0, user_embed + item_embed, mf_embed)(fused)
            head_in = merge([mlp, gmf], mode="concat")
        else:
            head_in = mlp
        # class_num >= 2: explicit feedback, softmax over rating classes
        # (reference recipe). class_num == 1: implicit feedback, single
        # sigmoid interaction probability (NCF-paper protocol).
        if class_num == 1:
            out = L.Dense(1, activation="sigmoid")(head_in)
        else:
            out = L.Dense(class_num, activation="softmax")(head_in)

        super().__init__(pair, out, name="neuralcf")

    def constructor_config(self) -> dict:
        return dict(user_count=self.user_count, item_count=self.item_count,
                    class_num=self.class_num, user_embed=self.user_embed,
                    item_embed=self.item_embed, hidden_layers=self.hidden_layers,
                    include_mf=self.include_mf, mf_embed=self.mf_embed)

    @property
    def table_rows(self) -> int:
        """Rows of the fused pair table: ``(user_count+1) + (item_count+1)``
        (+1s are the 1-based-id convention). Row sharding needs this to
        divide the mesh axis — size the counts with
        :func:`analytics_zoo_tpu.parallel.pad_rows` in mind."""
        return self.user_count + 1 + self.item_count + 1

    def shard_tables(self, mesh, *, axis: str = "dp", min_rows: int = 0,
                     shard_batch: bool = True):
        """Row-shard the fused user/item table over ``mesh[axis]`` and return
        the Estimator ``param_sharding`` rule (the million-user path: the
        table never replicates, lookups go through the model-parallel gather,
        Adam moments land 1/n per device). No-op marking — and a replicated
        rule — when :attr:`table_rows` doesn't divide the axis."""
        from ...parallel.embedding_sharding import shard_embedding_tables

        return shard_embedding_tables(self, mesh, axis=axis,
                                      min_rows=min_rows,
                                      shard_batch=shard_batch)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "NeuralCF":
        """Rebuild architecture from config.json + restore weights on the next
        ``compile`` (NeuralCF.loadModel parity)."""
        from ..common.zoo_model import load_model_bundle

        model, _cfg = load_model_bundle(path)
        return model


def implicit_bce_loss(y_true, y_pred):
    """BCE over an ``(B, 1+K)`` score block whose column 0 is the positive
    pair and columns 1..K are sampled negatives (labels are implied by the
    layout, so ``y_true`` is a dummy). NCF-paper eq. 7 objective.

    Scores are cast to float32 before the clip: in bfloat16 the upper bound
    ``1 - 1e-7`` rounds to exactly 1.0 and a saturated sigmoid would reach
    ``log1p(-1) = -inf`` (same rationale as losses._f32)."""
    import jax.numpy as jnp

    p = jnp.asarray(y_pred, jnp.float32)
    labels = jnp.zeros_like(p).at[:, 0].set(1.0)
    p = jnp.clip(p, 1e-7, 1.0 - 1e-7)
    return -jnp.mean(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))


@register_model("ImplicitNCF")
class ImplicitNCF(NeuralCF):
    """NeuralCF trained on the NCF-paper implicit-feedback protocol
    (He et al. 2017; reference recipe at /root/reference/pyzoo/zoo/models/
    recommendation/neuralcf.py:30-97 covers the explicit variant only).

    Input is the ``(B, 2)`` POSITIVE pairs; during training the forward
    samples ``n_negatives`` random items per positive *inside the jitted
    step* (fresh negatives every step from the step-folded rng — the
    TPU-native replacement for the paper's per-epoch host-side resampling;
    the dataset stays device-cached and the epoch remains one ``lax.scan``).
    Uniform sampling may rarely hit a seen item (~4.5% on ML-1M), the
    standard approximation in public NCF implementations. Training output is
    ``(B, 1+K)`` sigmoid scores for ``implicit_bce_loss``; inference output
    is the plain ``(B, 1)`` interaction probability.
    """

    def __init__(self, user_count: int, item_count: int, n_negatives: int = 4,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20):
        self.n_negatives = int(n_negatives)
        super().__init__(user_count, item_count, class_num=1,
                         user_embed=user_embed, item_embed=item_embed,
                         hidden_layers=hidden_layers, include_mf=include_mf,
                         mf_embed=mf_embed)

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training:
            return super().apply(params, state, x, training=training, rng=rng)
        import jax
        import jax.numpy as jnp

        if rng is None:
            rng = jax.random.PRNGKey(0)
        pos = jnp.asarray(x, jnp.int32)
        b, k = pos.shape[0], self.n_negatives
        neg_items = jax.random.randint(rng, (b, k), 1, self.item_count + 1,
                                       dtype=jnp.int32)
        users = jnp.broadcast_to(pos[:, 0:1], (b, k))
        neg = jnp.stack([users, neg_items], axis=-1).reshape(b * k, 2)
        scores, new_state = super().apply(
            params, state, jnp.concatenate([pos, neg], axis=0),
            training=training, rng=rng)
        block = jnp.concatenate([scores[:b, 0:1],
                                 scores[b:, 0].reshape(b, k)], axis=1)
        return block, new_state

    def constructor_config(self) -> dict:
        cfg = super().constructor_config()
        cfg.pop("class_num", None)
        cfg["n_negatives"] = self.n_negatives
        return cfg
