"""NeuralCF — neural collaborative filtering (the north-star model).

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/models/
recommendation/NeuralCF.scala:45-103 and
/root/reference/pyzoo/zoo/models/recommendation/neuralcf.py:30-97 — GMF + MLP
dual-embedding towers over (user, item) pairs, merged into a softmax rating head.

TPU-native notes:
* The four embedding tables are HBM gathers; under tensor parallelism they shard
  row-wise over the ``tp`` axis (see analytics_zoo_tpu.parallel.sharding).
* The whole forward is one fused XLA program; the MLP matmuls land on the MXU. The
  batch is the only meaningful FLOP axis, so throughput scales with dp sharding.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ...nn import layers as L
from ...nn.graph import Input
from ...nn.layers.merge import merge
from ..common.zoo_model import register_model
from .recommender import Recommender


@register_model("NeuralCF")
class NeuralCF(Recommender):
    """GMF + MLP recommender.

    Args mirror the reference constructor (NeuralCF.scala:45-53): ``user_count``,
    ``item_count``, ``class_num``, ``user_embed``, ``item_embed``,
    ``hidden_layers``, ``include_mf``, ``mf_embed``.
    """

    def __init__(self, user_count: int, item_count: int, class_num: int,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20):
        self.user_count = user_count
        self.item_count = item_count
        self.class_num = class_num
        self.user_embed = user_embed
        self.item_embed = item_embed
        self.hidden_layers = list(hidden_layers)
        self.include_mf = include_mf
        self.mf_embed = mf_embed

        # (B, 2) int input: [:, 0]=user id, [:, 1]=item id (NeuralCF.scala:57-60)
        pair = Input((2,), name="user_item_pair")
        user_id = L.Select(0, 0)(pair)
        item_id = L.Select(0, 1)(pair)

        # +1 rows: ids are 1-based in the reference datasets (NeuralCF.scala:65-66)
        mlp_user = L.Embedding(user_count + 1, user_embed, init="normal")(user_id)
        mlp_item = L.Embedding(item_count + 1, item_embed, init="normal")(item_id)
        mlp = merge([mlp_user, mlp_item], mode="concat")
        for h in self.hidden_layers:
            mlp = L.Dense(h, activation="relu")(mlp)

        if include_mf:
            assert mf_embed > 0, "provide a meaningful number of mf embedding units"
            mf_user = L.Embedding(user_count + 1, mf_embed, init="normal")(user_id)
            mf_item = L.Embedding(item_count + 1, mf_embed, init="normal")(item_id)
            gmf = merge([mf_user, mf_item], mode="mul")
            head_in = merge([mlp, gmf], mode="concat")
        else:
            head_in = mlp
        out = L.Dense(class_num, activation="softmax")(head_in)

        super().__init__(pair, out, name="neuralcf")

    def constructor_config(self) -> dict:
        return dict(user_count=self.user_count, item_count=self.item_count,
                    class_num=self.class_num, user_embed=self.user_embed,
                    item_embed=self.item_embed, hidden_layers=self.hidden_layers,
                    include_mf=self.include_mf, mf_embed=self.mf_embed)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "NeuralCF":
        """Rebuild architecture from config.json + restore weights on the next
        ``compile`` (NeuralCF.loadModel parity)."""
        from ..common.zoo_model import load_model_bundle

        model, _cfg = load_model_bundle(path)
        return model
