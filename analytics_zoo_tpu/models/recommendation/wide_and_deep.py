"""WideAndDeep recommender.

Parity: /root/reference/pyzoo/zoo/models/recommendation/wide_and_deep.py:99-239 and
/root/reference/zoo/src/main/scala/com/intel/analytics/zoo/models/recommendation/
WideAndDeep.scala — wide (linear over multi-hot crosses) + deep (embeddings +
indicators + continuous through an MLP), summed into a softmax head.

TPU-native notes:
* The wide input is a dense multi-hot ``(B, wide_dim)`` — the reference uses a JVM
  SparseTensor + SparseDense; on TPU the dense GEMV batched over B is one MXU pass
  and avoids gather-scatter (wide_dim is small: thousands at most).
* Each embed column keeps its own table (row-sharded over ``tp`` when meshed).
"""

from __future__ import annotations

from typing import List, Sequence

from ...nn import layers as L
from ...nn.graph import Input
from ...nn.layers.merge import merge
from ..common.zoo_model import register_model
from .features import ColumnFeatureInfo
from .recommender import Recommender


@register_model("WideAndDeep")
class WideAndDeep(Recommender):
    """Wide & Deep model (wide_and_deep.py:99 parity).

    Args:
        class_num: number of rating classes.
        column_info: :class:`ColumnFeatureInfo`.
        model_type: ``"wide" | "deep" | "wide_n_deep"``.
        hidden_layers: deep-MLP widths.

    Input order matches ``row_to_sample``: ``[wide?, indicator?, embed?, continuous?]``.
    """

    def __init__(self, class_num: int, column_info, model_type: str = "wide_n_deep",
                 hidden_layers: Sequence[int] = (40, 20, 10)):
        if isinstance(column_info, dict):
            column_info = ColumnFeatureInfo.from_dict(column_info)
        ci = column_info
        assert len(ci.wide_base_cols) == len(ci.wide_base_dims), \
            "size of wide_base_columns should match"
        assert len(ci.wide_cross_cols) == len(ci.wide_cross_dims), \
            "size of wide_cross_columns should match"
        assert len(ci.indicator_cols) == len(ci.indicator_dims), \
            "size of indicator_columns should match"
        assert len(ci.embed_cols) == len(ci.embed_in_dims) == len(ci.embed_out_dims), \
            "size of embed_columns should match"
        self.class_num = int(class_num)
        self.column_info = ci
        self.model_type = model_type
        self.hidden_layers = [int(u) for u in hidden_layers]

        wide_dim = ci.wide_dim
        input_wide = Input((wide_dim,), name="wide_input") if wide_dim else None

        if model_type == "wide":
            out = L.Activation("softmax")(L.SparseDense(self.class_num)(input_wide))
            super().__init__(input_wide, out, name="wide_and_deep")
        elif model_type == "deep":
            deep_inputs, deep_out = self._build_deep()
            out = L.Activation("softmax")(deep_out)
            super().__init__(self._inp(deep_inputs), out, name="wide_and_deep")
        elif model_type == "wide_n_deep":
            wide_linear = L.SparseDense(self.class_num)(input_wide)
            deep_inputs, deep_out = self._build_deep()
            summed = merge([wide_linear, deep_out], mode="sum")
            out = L.Activation("softmax")(summed)
            super().__init__([input_wide] + deep_inputs, out, name="wide_and_deep")
        else:
            raise TypeError(f"Unsupported model_type: {model_type}")

    @staticmethod
    def _inp(nodes: List):
        return nodes[0] if len(nodes) == 1 else nodes

    def _build_deep(self):
        """Deep tower: indicators ++ per-column embeddings ++ continuous → MLP
        (wide_and_deep.py:171-216 ``_deep_merge``/``_deep_hidden`` parity)."""
        ci = self.column_info
        inputs, merged = [], []
        if ci.indicator_cols:
            ind = Input((sum(ci.indicator_dims),), name="indicator_input")
            inputs.append(ind)
            merged.append(ind)
        if ci.embed_cols:
            emb_in = Input((len(ci.embed_cols),), name="embed_input")
            inputs.append(emb_in)
            for i, (in_dim, out_dim) in enumerate(zip(ci.embed_in_dims, ci.embed_out_dims)):
                col_id = L.Select(0, i)(emb_in)
                merged.append(L.Embedding(in_dim + 1, out_dim, init="normal")(col_id))
        if ci.continuous_cols:
            cont = Input((len(ci.continuous_cols),), name="continuous_input")
            inputs.append(cont)
            merged.append(cont)
        if not merged:
            raise TypeError(f"Empty deep model for: {self.model_type}")
        x = merged[0] if len(merged) == 1 else merge(merged, mode="concat")
        for h in self.hidden_layers:
            x = L.Dense(h, activation="relu")(x)
        return inputs, L.Dense(self.class_num, activation="relu")(x)

    def constructor_config(self) -> dict:
        return dict(class_num=self.class_num, column_info=self.column_info.to_dict(),
                    model_type=self.model_type, hidden_layers=self.hidden_layers)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "WideAndDeep":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        return model
