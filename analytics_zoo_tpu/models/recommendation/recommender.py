"""Recommender base — user/item pair prediction + top-K recommendation.

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/models/
recommendation/Recommender.scala and the python mirror
/root/reference/pyzoo/zoo/models/recommendation/recommender.py:79-133
(``predict_user_item_pair``, ``recommend_for_user``, ``recommend_for_item``).

The reference operates on RDDs of ``UserItemFeature``; here the same operations run
as batched device computations: scoring all candidate items for a user is ONE
embedding-gather + matmul sweep on the MXU instead of an RDD map.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...nn.topology import Model


@dataclass
class UserItemPrediction:
    user_id: int
    item_id: int
    prediction: int
    probability: float


class Recommender(Model):
    """Base class: subclasses build a graph scoring (user, item) int pairs into
    class probabilities (rating classes, 1-based like the reference)."""

    def predict_user_item_pair(self, user_item_pairs: np.ndarray,
                               batch_size: int = 4096) -> List[UserItemPrediction]:
        """Score explicit (user, item) pairs (recommender.py:79 parity)."""
        pairs = np.asarray(user_item_pairs, dtype="int32")
        probs = self.predict(pairs, batch_size=batch_size)
        cls = probs.argmax(-1)
        return [UserItemPrediction(int(u), int(i), int(c) + 1, float(p[c]))
                for (u, i), c, p in zip(pairs, cls, probs)]

    def recommend_for_user(self, user_item_pairs: np.ndarray, max_items: int
                           ) -> List[UserItemPrediction]:
        """Top-``max_items`` per user among the candidate pairs given
        (recommender.py:99 parity — candidates come from the input set)."""
        pairs = np.asarray(user_item_pairs, dtype="int32")
        preds = self.predict_user_item_pair(pairs)
        by_user = {}
        for p in preds:
            by_user.setdefault(p.user_id, []).append(p)
        out: List[UserItemPrediction] = []
        for u in sorted(by_user):
            # Recommender.scala:55 orders by (-prediction, -probability): the
            # predicted rating class ranks first, confidence breaks ties.
            ranked = sorted(by_user[u],
                            key=lambda p: (-p.prediction, -p.probability))
            out.extend(ranked[:max_items])
        return out

    def recommend_for_item(self, user_item_pairs: np.ndarray, max_users: int
                           ) -> List[UserItemPrediction]:
        """Top-``max_users`` per item (recommender.py:116 parity)."""
        pairs = np.asarray(user_item_pairs, dtype="int32")
        preds = self.predict_user_item_pair(pairs)
        by_item = {}
        for p in preds:
            by_item.setdefault(p.item_id, []).append(p)
        out: List[UserItemPrediction] = []
        for i in sorted(by_item):
            ranked = sorted(by_item[i],
                            key=lambda p: (-p.prediction, -p.probability))
            out.extend(ranked[:max_users])
        return out
