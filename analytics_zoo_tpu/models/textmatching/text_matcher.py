"""TextMatcher base class for text-matching/ranking models.

Parity: /root/reference/pyzoo/zoo/models/textmatching/text_matcher.py:23-40 —
holds (text1_length, vocab_size, embed_size, embed_weights, train_embed,
target_mode) and mixes in Ranker evaluation.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...nn.topology import Model
from ..common.ranker import Ranker


class TextMatcher(Model, Ranker):
    """Base for matching models; subclasses build the scoring graph."""

    def _init_matcher(self, text1_length: int, vocab_size: int, embed_size: int = 300,
                      embed_weights: Optional[np.ndarray] = None,
                      train_embed: bool = True, target_mode: str = "ranking"):
        assert target_mode in ("ranking", "classification"), \
            "target_mode should be either ranking or classification"
        self.text1_length = int(text1_length)
        self.vocab_size = int(vocab_size)
        self.embed_size = int(embed_size)
        self.embed_weights = embed_weights
        self.train_embed = bool(train_embed)
        self.target_mode = target_mode
