from .knrm import KNRM
from .text_matcher import TextMatcher

__all__ = ["KNRM", "TextMatcher"]
