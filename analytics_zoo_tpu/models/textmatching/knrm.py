"""KNRM — Kernel-pooling Neural Ranking Model (https://arxiv.org/abs/1706.06613).

Parity: /root/reference/pyzoo/zoo/models/textmatching/knrm.py:32-139 and
.../models/textmatching/KNRM.scala — shared embedding over the concatenated
(query ++ doc) token sequence, translation matrix Q·Dᵀ, RBF kernel pooling,
linear (ranking) or sigmoid (classification) head.

TPU-native: the reference loops over kernels building one autograd graph each
(knrm.py:104-116); here ALL kernels evaluate as one vectorized ``(B,Q,D,K)``
broadcast that XLA fuses into the batched matmul epilogue — kernel pooling costs
one HBM pass instead of K.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ...nn import layers as L
from ...nn.graph import Input
from ..common.zoo_model import register_model
from .text_matcher import TextMatcher


@register_model("KNRM")
class KNRM(TextMatcher):
    """Args mirror knrm.py:67-76: ``text1_length``, ``text2_length``,
    ``embedding_file``/``word_index`` (or ``vocab_size``/``embed_size`` for the
    file-less path), ``train_embed``, ``kernel_num``, ``sigma``, ``exact_sigma``,
    ``target_mode``."""

    def __init__(self, text1_length: int, text2_length: int,
                 embedding_file: Optional[str] = None,
                 word_index: Optional[Dict[str, int]] = None,
                 train_embed: bool = True, kernel_num: int = 21, sigma: float = 0.1,
                 exact_sigma: float = 0.001, target_mode: str = "ranking",
                 vocab_size: Optional[int] = None, embed_size: int = 300):
        assert kernel_num > 1, "kernel_num must be an int larger than 1"
        if embedding_file is not None:
            if word_index is None:
                raise ValueError("word_index is required with embedding_file")
            # prepare_embedding(randomize_unknown=True, normalize=True) parity
            # (knrm.py:70-71)
            from ...nn.layers.embedding import load_glove_table

            table = load_glove_table(embedding_file, word_index, embed_size,
                                     randomize_unknown=True, normalize=True)
            vocab_size, embed_size = table.shape
        else:
            vocab_size = int(vocab_size or ((max(word_index.values()) + 1)
                                            if word_index else 20000))
            table = None
        self._init_matcher(text1_length, vocab_size, embed_size, table,
                           train_embed, target_mode)
        self.text2_length = int(text2_length)
        self.kernel_num = int(kernel_num)
        self.sigma = float(sigma)
        self.exact_sigma = float(exact_sigma)

        # kernel centers: mu_i = 1/(K-1) + 2i/(K-1) - 1, exact-match kernel at 1.0
        # (knrm.py:105-110)
        mus, sigmas = [], []
        for i in range(self.kernel_num):
            mu = 1.0 / (self.kernel_num - 1) + (2.0 * i) / (self.kernel_num - 1) - 1.0
            if mu > 1.0:
                mus.append(1.0)
                sigmas.append(self.exact_sigma)
            else:
                mus.append(mu)
                sigmas.append(self.sigma)
        mu_arr = np.asarray(mus, dtype="float32")
        sigma_arr = np.asarray(sigmas, dtype="float32")
        t1 = self.text1_length

        def kernel_pooling(embed):
            # embed: (B, Q+D, E) → Phi: (B, K)   [all kernels in one broadcast]
            q, d = embed[:, :t1, :], embed[:, t1:, :]
            mm = jnp.einsum("bqe,bde->bqd", q, d)  # translation matrix
            diff = mm[..., None] - mu_arr          # (B, Q, D, K)
            mm_exp = jnp.exp(-0.5 * diff * diff / (sigma_arr * sigma_arr))
            mm_doc_sum = jnp.sum(mm_exp, axis=2)   # soft-TF per query term
            mm_log = jnp.log1p(mm_doc_sum)
            return jnp.sum(mm_log, axis=1)         # (B, K)

        inp = Input((self.text1_length + self.text2_length,), name="input")
        embedding = L.Embedding(self.vocab_size, self.embed_size,
                                weights=self.embed_weights,
                                trainable=self.train_embed, init="uniform")(inp)
        phi = L.Lambda(kernel_pooling,
                       output_shape_fn=lambda s: (self.kernel_num,))(embedding)
        if target_mode == "ranking":
            out = L.Dense(1, init="uniform")(phi)
        else:
            out = L.Dense(1, init="uniform", activation="sigmoid")(phi)
        super().__init__(inp, out, name="knrm")

    def constructor_config(self) -> dict:
        return dict(text1_length=self.text1_length, text2_length=self.text2_length,
                    train_embed=self.train_embed, kernel_num=self.kernel_num,
                    sigma=self.sigma, exact_sigma=self.exact_sigma,
                    target_mode=self.target_mode, vocab_size=self.vocab_size,
                    embed_size=self.embed_size)

    def save_model(self, path: str):
        from ..common.zoo_model import save_model_bundle

        save_model_bundle(path, self, config=self.constructor_config())

    @classmethod
    def load_model(cls, path: str) -> "KNRM":
        from ..common.zoo_model import load_model_bundle

        model, _ = load_model_bundle(path)
        return model
