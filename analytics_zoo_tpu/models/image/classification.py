"""ImageClassifier — config-driven image classification
(reference ``models/image/imageclassification/ImageClassifier.scala`` +
``ImageClassificationConfig.scala``: named backbone + dataset preprocessing +
label map, ``predictImageSet`` returning top-k classes).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...data.image import (ImageCenterCrop, ImageChannelNormalize, ImageResize,
                           ImageSet)
from ..common.zoo_model import save_model_bundle
from .backbones import build_backbone


class ImagenetConfig:
    """Per-dataset preprocessing recipe (ImagenetConfig parity: resize 256 →
    center-crop 224 → channel-mean normalize). ``resize`` defaults to the
    standard 256/224 ratio of the crop size."""

    MEANS = (123.68, 116.779, 103.939)

    @staticmethod
    def preprocessing(crop_h: int = 224, crop_w: int = 224,
                      resize: Optional[int] = None):
        if resize is None:
            resize = max(crop_h, crop_w) * 256 // 224
        return (ImageResize(resize, resize)
                >> ImageCenterCrop(crop_h, crop_w)
                >> ImageChannelNormalize(*ImagenetConfig.MEANS))


class ImageClassifier:
    """Named-backbone classifier with ImageSet predict
    (ImageClassifier.scala ``predictImageSet``/``setTopN`` capability)."""

    def __init__(self, model_name: str = "resnet-50",
                 input_shape: Tuple[int, int, int] = (224, 224, 3),
                 num_classes: int = 1000,
                 label_map: Optional[Sequence[str]] = None,
                 model=None):
        self.model_name = model_name
        self.input_shape = tuple(input_shape)
        self.num_classes = int(num_classes)
        self.label_map = list(label_map) if label_map is not None else None
        self.model = model if model is not None else build_backbone(
            model_name, self.input_shape, self.num_classes)
        self.top_n = 5

    def set_top_n(self, n: int) -> "ImageClassifier":
        self.top_n = int(n)
        return self

    def compile(self, optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=("accuracy",), **kw):
        self.model.compile(optimizer=optimizer, loss=loss,
                           metrics=list(metrics), **kw)
        return self

    def fit(self, x, y=None, **kw):
        self.model.fit(x, y, **kw)
        return self

    def fit_image_set(self, image_set: ImageSet, labels=None, **kw):
        """Train with the SAME preprocessing chain predict_image_set applies —
        use this (not raw-array fit) when predicting via predict_image_set."""
        x = self._preprocess_set(image_set)
        y = np.asarray(labels if labels is not None
                       else image_set.get_labels(), dtype="int32")
        self.model.fit(x, y, **kw)
        return self

    # ------------------------------------------------------------- prediction
    def _preprocess_set(self, image_set: ImageSet) -> np.ndarray:
        h, w, _ = self.input_shape
        processed = image_set.transform(ImagenetConfig.preprocessing(h, w))
        return np.stack([f.get_image().astype("float32")
                         for f in processed.features])

    def predict_image_set(self, image_set: ImageSet, batch_size: int = 32):
        """Returns per-image list of (class_index_or_label, probability) top-n."""
        x = self._preprocess_set(image_set)
        probs = np.asarray(self.model.predict(x, batch_size=batch_size))
        order = np.argsort(-probs, axis=1)[:, :self.top_n]
        results = []
        for row, idx in zip(probs, order):
            labels = [self.label_map[i] if self.label_map else int(i) for i in idx]
            results.append(list(zip(labels, row[idx].tolist())))
        return results

    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        return np.asarray(self.model.predict(np.asarray(x), batch_size=batch_size))

    # ------------------------------------------------------------ persistence
    def save_model(self, path: str):
        save_model_bundle(path, self.model, config={
            "model_name": self.model_name, "input_shape": list(self.input_shape),
            "num_classes": self.num_classes, "label_map": self.label_map})

    @classmethod
    def load_model(cls, path: str) -> "ImageClassifier":
        import json
        import os

        with open(os.path.join(path, "config.json")) as f:
            config = json.load(f)["config"]
        clf = cls(model_name=config["model_name"],
                  input_shape=tuple(config["input_shape"]),
                  num_classes=config["num_classes"],
                  label_map=config.get("label_map"))
        clf.compile()
        clf.model.load_weights(path)
        return clf
