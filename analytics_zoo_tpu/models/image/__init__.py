"""Image model zoo — classification backbones + SSD object detection
(reference ``zoo/.../models/image/``: imageclassification/, objectdetection/,
SURVEY.md §2.8)."""

from .backbones import BACKBONES, build_backbone
from .classification import ImageClassifier, ImagenetConfig
from .objectdetection import (MeanAveragePrecision, ObjectDetector, SSDModel,
                              decode_predictions, generate_anchors, multibox_loss,
                              nms)

__all__ = ["BACKBONES", "build_backbone", "ImageClassifier", "ImagenetConfig",
           "MeanAveragePrecision", "ObjectDetector", "SSDModel",
           "decode_predictions", "generate_anchors", "multibox_loss", "nms"]
