"""SSD object detection — reference ``models/image/objectdetection/``
(``ObjectDetector.scala``, ssd/ graph + ``common/loss/MultiBoxLoss`` +
``common/evaluation/MeanAveragePrecision.scala``, ``Postprocessor.scala``).

TPU-native design:
* anchors are generated once on the host per feature-map pyramid (static shapes);
* the detection head emits one dense ``(B, num_anchors, 4 + num_classes)``
  tensor — matching, loc smooth-L1, conf cross-entropy, and hard negative
  mining are all fixed-shape vectorized ops (top-k replaces the reference's
  sort-based mining loop), so the whole multibox loss jits into the train step;
* decode+NMS runs host-side per image at predict time (variable-length output).

Box convention: (cy, cx, h, w) normalized to [0, 1] for anchors; corner boxes
(y1, x1, y2, x2) at the API edge. Class 0 is background.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ...nn import layers as L
from ...nn.graph import Input
from ...nn.module import Layer
from ...nn.topology import Model

# ----------------------------------------------------------------- anchors


def generate_anchors(feature_sizes: Sequence[int],
                     scales: Optional[Sequence[float]] = None,
                     aspect_ratios: Sequence[float] = (1.0, 2.0, 0.5)) -> np.ndarray:
    """Anchor pyramid (SSD Prior boxes): for each feature map cell, one anchor
    per aspect ratio at that level's scale. Returns (A, 4) center-form
    normalized (cy, cx, h, w)."""
    n_levels = len(feature_sizes)
    if scales is None:
        scales = np.linspace(0.2, 0.9, n_levels)
    out = []
    for fs, scale in zip(feature_sizes, scales):
        cy, cx = np.meshgrid(np.arange(fs), np.arange(fs), indexing="ij")
        cy = (cy.reshape(-1) + 0.5) / fs
        cx = (cx.reshape(-1) + 0.5) / fs
        # cell-major, aspect-ratio-minor — MUST match the head's Reshape of the
        # conv output (H, W, n_ar*(4+C)) → ((h*W+w)*n_ar + ar, 4+C), so that
        # prediction slot i trains/decodes against the anchor at its own cell
        per_cell = []
        for ar in aspect_ratios:
            h = scale / np.sqrt(ar)
            w = scale * np.sqrt(ar)
            per_cell.append(np.stack([cy, cx, np.full_like(cy, h),
                                      np.full_like(cx, w)], axis=1))
        level = np.stack(per_cell, axis=1)          # (cells, n_ar, 4)
        out.append(level.reshape(-1, 4))
    return np.concatenate(out, axis=0).astype("float32")


def generate_ssd_anchors(feature_sizes: Sequence[int],
                         scales: Sequence[float],
                         aspect_ratios_per_level: Sequence[Sequence[float]]
                         ) -> np.ndarray:
    """Paper-scheme SSD prior boxes (Liu et al. 2016 §2.2; reference
    ssd/PriorBox): per level k with scale s_k — one ar=1 box at s_k, one extra
    ar=1 box at sqrt(s_k·s_{k+1}), and one box per additional aspect ratio
    (h = s/√ar, w = s·√ar). ``scales`` has ``len(feature_sizes)+1`` entries.
    Ordering is cell-major, box-minor (must match the head reshape).
    SSD-300: sizes [38,19,10,5,3,1] → 8732 anchors."""
    out = []
    for level, (fs, s_k) in enumerate(zip(feature_sizes, scales)):
        s_next = scales[level + 1]
        cy, cx = np.meshgrid(np.arange(fs), np.arange(fs), indexing="ij")
        cy = (cy.reshape(-1) + 0.5) / fs
        cx = (cx.reshape(-1) + 0.5) / fs
        hw = [(s_k, s_k), (np.sqrt(s_k * s_next),) * 2]
        for ar in aspect_ratios_per_level[level]:
            if ar == 1.0:
                continue
            hw.append((s_k / np.sqrt(ar), s_k * np.sqrt(ar)))
        per_cell = [np.stack([cy, cx, np.full_like(cy, h), np.full_like(cx, w)],
                             axis=1) for h, w in hw]
        out.append(np.stack(per_cell, axis=1).reshape(-1, 4))
    return np.concatenate(out, axis=0).astype("float32")


def boxes_per_cell(aspect_ratios: Sequence[float]) -> int:
    """ar=1 contributes 2 boxes (s_k + the extra sqrt scale)."""
    return len(aspect_ratios) + 1


def corner_to_center(boxes: np.ndarray) -> np.ndarray:
    y1, x1, y2, x2 = np.moveaxis(boxes, -1, 0)
    return np.stack([(y1 + y2) / 2, (x1 + x2) / 2, y2 - y1, x2 - x1], axis=-1)


def center_to_corner(boxes: np.ndarray) -> np.ndarray:
    cy, cx, h, w = np.moveaxis(boxes, -1, 0)
    return np.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], axis=-1)


def iou_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """IoU of corner boxes a (N,4) × b (M,4) → (N, M)."""
    a = a[:, None, :]
    b = b[None, :, :]
    inter_y1 = np.maximum(a[..., 0], b[..., 0])
    inter_x1 = np.maximum(a[..., 1], b[..., 1])
    inter_y2 = np.minimum(a[..., 2], b[..., 2])
    inter_x2 = np.minimum(a[..., 3], b[..., 3])
    ih = np.clip(inter_y2 - inter_y1, 0, None)
    iw = np.clip(inter_x2 - inter_x1, 0, None)
    inter = ih * iw
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return inter / np.clip(area_a + area_b - inter, 1e-9, None)


# ------------------------------------------------------------------ matching


def match_anchors(anchors: np.ndarray, gt_boxes: np.ndarray,
                  gt_labels: np.ndarray, iou_threshold: float = 0.5,
                  variances=(0.1, 0.2)) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side target assignment (BboxUtil/MultiBoxLoss matching):
    each anchor gets the best-overlapping gt (label 0 = background below
    threshold); every gt's best anchor is force-matched. Returns
    (loc_targets (A,4) encoded offsets, cls_targets (A,) int)."""
    A = anchors.shape[0]
    loc_t = np.zeros((A, 4), dtype="float32")
    cls_t = np.zeros((A,), dtype="int32")
    if len(gt_boxes) == 0:
        return loc_t, cls_t
    anchors_corner = center_to_corner(anchors)
    ious = iou_matrix(anchors_corner, gt_boxes)       # (A, G)
    best_gt = ious.argmax(axis=1)
    best_iou = ious.max(axis=1)
    # force-match each gt's best anchor
    best_anchor_per_gt = ious.argmax(axis=0)
    best_iou[best_anchor_per_gt] = 1.0
    best_gt[best_anchor_per_gt] = np.arange(len(gt_boxes))
    pos = best_iou >= iou_threshold
    cls_t[pos] = gt_labels[best_gt[pos]]
    matched = corner_to_center(gt_boxes[best_gt])
    vc, vs = variances
    loc = np.stack([
        (matched[:, 0] - anchors[:, 0]) / anchors[:, 2] / vc,
        (matched[:, 1] - anchors[:, 1]) / anchors[:, 3] / vc,
        np.log(np.clip(matched[:, 2] / anchors[:, 2], 1e-9, None)) / vs,
        np.log(np.clip(matched[:, 3] / anchors[:, 3], 1e-9, None)) / vs,
    ], axis=1)
    loc_t[pos] = loc[pos]
    return loc_t, cls_t


# ---------------------------------------------------------------- loss (jit)


def multibox_loss(preds, loc_targets, cls_targets, num_classes: int,
                  neg_pos_ratio: float = 3.0):
    """MultiBoxLoss (common/loss/MultiBoxLoss capability): smooth-L1 on positive
    anchors' offsets + softmax CE with hard negative mining at
    ``neg_pos_ratio``. All fixed-shape jnp — jits into the train step.

    preds: (B, A, 4 + C); loc_targets: (B, A, 4); cls_targets: (B, A) int.
    """
    loc_pred = preds[..., :4]
    cls_pred = preds[..., 4:].astype(jnp.float32)
    pos = (cls_targets > 0)
    n_pos = jnp.maximum(pos.sum(), 1)

    # smooth L1
    diff = jnp.abs(loc_pred - loc_targets)
    sl1 = jnp.where(diff < 1.0, 0.5 * diff ** 2, diff - 0.5).sum(-1)
    loc_loss = jnp.where(pos, sl1, 0.0).sum() / n_pos

    import jax.nn as jnn

    log_probs = jnn.log_softmax(cls_pred, axis=-1)
    ce = -jnp.take_along_axis(log_probs, cls_targets[..., None].astype(jnp.int32),
                              axis=-1)[..., 0]
    pos_ce = jnp.where(pos, ce, 0.0)
    # hard negative mining: per-batch-row top-k negatives by loss
    neg_ce = jnp.where(pos, -jnp.inf, ce)
    k = jnp.minimum((neg_pos_ratio * pos.sum(axis=1)).astype(jnp.int32),
                    jnp.asarray(neg_ce.shape[1] - 1, jnp.int32))
    sorted_neg = jnp.sort(neg_ce, axis=1)[:, ::-1]     # descending
    idx = jnp.arange(neg_ce.shape[1])[None, :]
    neg_mask_sorted = idx < k[:, None]
    neg_loss = jnp.where(neg_mask_sorted,
                         jnp.where(jnp.isfinite(sorted_neg), sorted_neg, 0.0),
                         0.0).sum()
    cls_loss = (pos_ce.sum() + neg_loss) / n_pos
    return loc_loss + cls_loss


# ------------------------------------------------------------------- decode


def decode_predictions(preds: np.ndarray, anchors: np.ndarray,
                       variances=(0.1, 0.2)):
    """(A, 4+C) raw preds → (corner_boxes (A,4), class_probs (A,C))."""
    vc, vs = variances
    loc = preds[:, :4]
    cy = loc[:, 0] * vc * anchors[:, 2] + anchors[:, 0]
    cx = loc[:, 1] * vc * anchors[:, 3] + anchors[:, 1]
    h = np.exp(np.clip(loc[:, 2] * vs, -10, 10)) * anchors[:, 2]
    w = np.exp(np.clip(loc[:, 3] * vs, -10, 10)) * anchors[:, 3]
    boxes = center_to_corner(np.stack([cy, cx, h, w], axis=1))
    logits = preds[:, 4:] - preds[:, 4:].max(axis=1, keepdims=True)
    e = np.exp(logits)
    probs = e / e.sum(axis=1, keepdims=True)
    return boxes, probs


def nms(boxes: np.ndarray, scores: np.ndarray, iou_threshold: float = 0.45,
        top_k: int = 200) -> List[int]:
    """Greedy per-class NMS (Postprocessor.scala parity), host side."""
    order = np.argsort(-scores)[:top_k]
    keep = []
    while len(order) > 0:
        i = order[0]
        keep.append(int(i))
        if len(order) == 1:
            break
        rest = order[1:]
        ious = iou_matrix(boxes[i:i + 1], boxes[rest])[0]
        order = rest[ious <= iou_threshold]
    return keep


# -------------------------------------------------------------------- model


class SSDModel(Model):
    """Small SSD graph: conv backbone with ``len(feature_sizes)`` detection
    scales, each contributing ``len(aspect_ratios)`` anchors/cell. The head is
    one conv per level emitting (4 + num_classes) per anchor — reshaped and
    concatenated into the dense (B, A, 4+C) tensor the loss consumes."""

    def __init__(self, num_classes: int, image_size: int = 96,
                 aspect_ratios: Sequence[float] = (1.0, 2.0, 0.5),
                 base_filters: int = 32):
        self.num_classes_ = int(num_classes)
        self.image_size = int(image_size)
        self.aspect_ratios = tuple(aspect_ratios)
        n_out = len(aspect_ratios) * (4 + num_classes)

        inp = Input((image_size, image_size, 3))
        x = inp
        feature_sizes = []
        heads = []
        filters = base_filters
        size = image_size
        # downsample until the map is small; tap a head at each scale ≤ size/8
        level = 0
        while size > 2 and level < 6:
            x = L.Convolution2D(filters, 3, 3, subsample=(2, 2),
                                border_mode="same", use_bias=False)(x)
            # 0.9 momentum: detector fits are short (few hundred steps), the
            # keras-default 0.99 EMA never catches the final weights
            x = L.BatchNormalization(momentum=0.9)(x)
            x = L.Activation("relu")(x)
            size = -(-size // 2)
            level += 1
            if level >= 3:  # tap scales from stride-8 down
                feature_sizes.append(size)
                h = L.Convolution2D(n_out, 3, 3, border_mode="same")(x)
                h = L.Reshape((size * size * len(aspect_ratios),
                               4 + num_classes))(h)
                heads.append(h)
            filters = min(filters * 2, 256)
        out = heads[0] if len(heads) == 1 else L.Merge(
            mode="concat", concat_axis=0)(heads)
        super().__init__(inp, out, name="ssd")
        self.feature_sizes = feature_sizes
        self.anchors = generate_anchors(feature_sizes,
                                        aspect_ratios=self.aspect_ratios)


class L2NormScale(Layer):
    """Channel-wise L2 normalization with a learnable per-channel scale
    (reference ssd NormalizeScale on conv4_3; init 20)."""

    def __init__(self, init_scale: float = 20.0, name=None, input_shape=None):
        super().__init__(name=name, input_shape=input_shape)
        self.init_scale = float(init_scale)

    def build(self, rng, input_shape):
        return {"scale": jnp.full((input_shape[-1],), self.init_scale,
                                  jnp.float32)}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + 1e-10)
        return x / norm * jnp.asarray(params["scale"], x.dtype), state


# SSD-300 paper config (Liu et al. 2016, table in §3.1; reference
# objectdetection zoo "ssd-vgg16-300x300" models)
_SSD300_FEATURE_SIZES = (38, 19, 10, 5, 3, 1)
_SSD300_SCALES = (0.1, 0.2, 0.375, 0.55, 0.725, 0.9, 1.075)
_SSD300_ASPECT_RATIOS = ((1.0, 2.0, 0.5),
                         (1.0, 2.0, 0.5, 3.0, 1.0 / 3),
                         (1.0, 2.0, 0.5, 3.0, 1.0 / 3),
                         (1.0, 2.0, 0.5, 3.0, 1.0 / 3),
                         (1.0, 2.0, 0.5),
                         (1.0, 2.0, 0.5))

VOC_CLASSES = ("__background__", "aeroplane", "bicycle", "bird", "boat",
               "bottle", "bus", "car", "cat", "chair", "cow", "diningtable",
               "dog", "horse", "motorbike", "person", "pottedplant", "sheep",
               "sofa", "train", "tvmonitor")


class SSD300VGG(Model):
    """Full SSD-300 with a VGG16 feature extractor (the reference's production
    detector, ``models/image/objectdetection/`` ssd-vgg16-300x300):

    * VGG16 conv1_1..conv4_3 (tap 1, 38×38, L2-normalized + scaled),
    * conv5 + fc6 as a dilation-6 atrous 3×3 (MXU-friendly: XLA rhs_dilation,
      no kernel materialization) + fc7 1×1 (tap 2, 19×19×1024),
    * conv8..conv11 extra feature layers (taps 3-6: 10, 5, 3, 1),
    * per level one conv head emitting n_boxes·(4+C), reshaped cell-major and
      concatenated to the dense (B, 8732, 4+C) tensor multibox_loss consumes.
    """

    def __init__(self, num_classes: int, base_filters: int = 64):
        self.num_classes_ = int(num_classes)
        self.image_size = 300
        bf = base_filters

        def conv(x, f, k=3, s=1, mode="same", dil=1, activation="relu"):
            return L.AtrousConvolution2D(
                f, k, k, subsample=(s, s), atrous_rate=(dil, dil),
                border_mode=mode, activation=activation)(x)

        inp = Input((300, 300, 3))
        x = inp
        for f, n in ((bf, 2), (bf * 2, 2)):
            for _ in range(n):
                x = conv(x, f)
            x = L.MaxPooling2D((2, 2), border_mode="same")(x)   # 150 → 75
        for _ in range(3):
            x = conv(x, bf * 4)
        x = L.MaxPooling2D((2, 2), border_mode="same")(x)        # 75 → 38
        for _ in range(3):
            x = conv(x, bf * 8)
        conv4_3 = L2NormScale()(x)                               # tap 1: 38
        x = L.MaxPooling2D((2, 2), border_mode="same")(x)        # 38 → 19
        for _ in range(3):
            x = conv(x, bf * 8)
        x = conv(x, bf * 16, k=3, dil=6)                         # fc6, atrous
        fc7 = conv(x, bf * 16, k=1)                              # tap 2: 19
        x = conv(fc7, bf * 4, k=1)
        conv8 = conv(x, bf * 8, s=2)                             # tap 3: 10
        x = conv(conv8, bf * 2, k=1)
        conv9 = conv(x, bf * 4, s=2)                             # tap 4: 5
        x = conv(conv9, bf * 2, k=1)
        conv10 = conv(x, bf * 4, mode="valid")                   # tap 5: 3
        x = conv(conv10, bf * 2, k=1)
        conv11 = conv(x, bf * 4, mode="valid")                   # tap 6: 1

        taps = (conv4_3, fc7, conv8, conv9, conv10, conv11)
        heads = []
        for tap, fs, ars in zip(taps, _SSD300_FEATURE_SIZES,
                                _SSD300_ASPECT_RATIOS):
            nb = boxes_per_cell(ars)
            h = L.Convolution2D(nb * (4 + num_classes), 3, 3,
                                border_mode="same")(tap)
            heads.append(L.Reshape((fs * fs * nb, 4 + num_classes))(h))
        out = L.Merge(mode="concat", concat_axis=0)(heads)
        super().__init__(inp, out, name="ssd300_vgg")
        self.feature_sizes = list(_SSD300_FEATURE_SIZES)
        self.anchors = generate_ssd_anchors(
            _SSD300_FEATURE_SIZES, _SSD300_SCALES, _SSD300_ASPECT_RATIOS)


# config-driven zoo (reference ObjectDetector.loadObjectDetectionModel name
# scheme "ssd-vgg16-300x300_PASCAL_*" + ImageClassificationConfig pattern)
DETECTION_CONFIGS = {
    "ssd-vgg16-300x300": dict(builder=lambda C, **kw: SSD300VGG(C, **kw),
                              image_size=300, classes=VOC_CLASSES),
    "ssd-vgg16-300x300-pascal": dict(
        builder=lambda C, **kw: SSD300VGG(C, **kw), image_size=300,
        classes=VOC_CLASSES),
    "ssd-lite": dict(builder=lambda C, **kw: SSDModel(C, **kw),
                     image_size=96, classes=None),
}


class ObjectDetector:
    """User-facing SSD detector (ObjectDetector.scala capability:
    fit on (images, gt) and predictImageSet → [(label, score, box), ...]).

    ``model_name`` selects from DETECTION_CONFIGS (config-driven zoo loading);
    the default 'ssd-lite' is the small generic-backbone variant, pass
    'ssd-vgg16-300x300' for the full production architecture.
    """

    def __init__(self, num_classes: int, image_size: int = 96,
                 score_threshold: float = 0.3, iou_threshold: float = 0.45,
                 model_name: str = "ssd-lite", class_names=None, **model_kw):
        cfg = DETECTION_CONFIGS.get(model_name)
        if cfg is None:
            raise ValueError(f"unknown detection model {model_name!r}; "
                             f"known: {sorted(DETECTION_CONFIGS)}")
        if model_name.startswith("ssd-lite"):
            self.model = cfg["builder"](num_classes, image_size=image_size,
                                        **model_kw)
        else:
            self.model = cfg["builder"](num_classes, **model_kw)
            image_size = cfg["image_size"]
        self.model_kw = dict(model_kw)   # persisted so load_model rebuilds
        self.model_name = model_name
        self.class_names = tuple(class_names or cfg.get("classes") or ())
        self.num_classes = int(num_classes)
        self.image_size = int(image_size)
        self.score_threshold = score_threshold
        self.iou_threshold = iou_threshold

    @classmethod
    def from_config(cls, model_name: str, num_classes: Optional[int] = None,
                    **kw) -> "ObjectDetector":
        """Zoo-style entry: ``ObjectDetector.from_config('ssd-vgg16-300x300')``
        builds the named architecture with its dataset's class count."""
        cfg = DETECTION_CONFIGS.get(model_name)
        if cfg is None:
            raise ValueError(f"unknown detection model {model_name!r}; "
                             f"known: {sorted(DETECTION_CONFIGS)}")
        if num_classes is None:
            classes = cfg.get("classes")
            if classes is None:
                raise ValueError(f"{model_name!r} needs num_classes")
            num_classes = len(classes)
        return cls(num_classes, model_name=model_name, **kw)

    # -- persistence (ZooModel bundle format) ---------------------------------
    def save_model(self, path: str):
        from ...models.common.zoo_model import save_model_bundle

        save_model_bundle(path, self.model, config={
            "model_name": self.model_name, "num_classes": self.num_classes,
            "image_size": self.image_size, "class_names": list(self.class_names),
            "score_threshold": self.score_threshold,
            "iou_threshold": self.iou_threshold,
            "model_kw": self.model_kw})

    @classmethod
    def load_model(cls, path: str) -> "ObjectDetector":
        import json
        import os

        from ...models.common.zoo_model import load_model_bundle

        with open(os.path.join(path, "config.json")) as f:
            config = json.load(f)["config"]
        det = cls(config["num_classes"], image_size=config["image_size"],
                  score_threshold=config.get("score_threshold", 0.3),
                  iou_threshold=config.get("iou_threshold", 0.45),
                  model_name=config.get("model_name", "ssd-lite"),
                  class_names=config.get("class_names") or None,
                  **config.get("model_kw", {}))
        load_model_bundle(path, model=det.model)
        det.compile()
        return det

    def compile(self, optimizer="adam", **kw):
        anchors = self.model.anchors
        C = self.num_classes

        def loss(y_true, y_pred):
            loc_t = y_true[..., :4]
            cls_t = y_true[..., 4].astype(jnp.int32)
            return multibox_loss(y_pred, loc_t, cls_t, C)

        self.model.compile(optimizer=optimizer, loss=loss, **kw)
        return self

    def encode_targets(self, gt_boxes_list, gt_labels_list) -> np.ndarray:
        """Per-image gt → dense (A, 5) targets [loc(4), cls(1)]."""
        out = []
        for boxes, labels in zip(gt_boxes_list, gt_labels_list):
            loc_t, cls_t = match_anchors(self.model.anchors,
                                         np.asarray(boxes, dtype="float32"),
                                         np.asarray(labels, dtype="int32"))
            out.append(np.concatenate([loc_t, cls_t[:, None].astype("float32")],
                                      axis=1))
        return np.stack(out)

    def fit(self, images, gt_boxes_list, gt_labels_list,
            recalibrate_bn: bool = True, **kw):
        targets = self.encode_targets(gt_boxes_list, gt_labels_list)
        images = np.asarray(images, dtype="float32")
        self.model.fit(images, targets, **kw)
        if recalibrate_bn:
            # short detector fits leave the 0.99-EMA BatchNorm stats lagging
            # the final weights → eval-mode confidences collapse; re-estimate
            # under the trained weights (Estimator.recalibrate_batchnorm)
            self.model.estimator.recalibrate_batchnorm(
                images, batch_size=int(kw.get("batch_size", 16)))
        return self

    def predict(self, images, batch_size: int = 16):
        """Returns per-image list of (class_id, score, (y1,x1,y2,x2))."""
        raw = np.asarray(self.model.predict(np.asarray(images, dtype="float32"),
                                            batch_size=batch_size))
        results = []
        for pred in raw:
            boxes, probs = decode_predictions(pred, self.model.anchors)
            dets = []
            for c in range(1, self.num_classes):
                scores = probs[:, c]
                mask = scores >= self.score_threshold
                if not mask.any():
                    continue
                kept = nms(boxes[mask], scores[mask], self.iou_threshold)
                idx = np.nonzero(mask)[0][kept]
                dets.extend((c, float(scores[i]), tuple(boxes[i].tolist()))
                            for i in idx)
            dets.sort(key=lambda d: -d[1])
            results.append(dets)
        return results


# ---------------------------------------------------------------- evaluation


class MeanAveragePrecision:
    """VOC-style mAP (common/evaluation/MeanAveragePrecision.scala parity):
    11-point interpolated AP per class over ranked detections."""

    def __init__(self, num_classes: int, iou_threshold: float = 0.5):
        self.num_classes = num_classes
        self.iou_threshold = iou_threshold

    def __call__(self, detections, gt_boxes_list, gt_labels_list) -> float:
        aps = []
        for c in range(1, self.num_classes):
            aps.append(self._ap_for_class(c, detections, gt_boxes_list,
                                          gt_labels_list))
        aps = [a for a in aps if a is not None]
        return float(np.mean(aps)) if aps else 0.0

    def _ap_for_class(self, c, detections, gt_boxes_list, gt_labels_list):
        scores, tps = [], []
        n_gt = 0
        for dets, gboxes, glabels in zip(detections, gt_boxes_list,
                                         gt_labels_list):
            gboxes = np.asarray(gboxes, dtype="float32").reshape(-1, 4)
            glabels = np.asarray(glabels)
            cls_gt = gboxes[glabels == c]
            n_gt += len(cls_gt)
            used = np.zeros(len(cls_gt), dtype=bool)
            for (dc, score, box) in sorted([d for d in dets if d[0] == c],
                                           key=lambda d: -d[1]):
                scores.append(score)
                hit = False
                if len(cls_gt):
                    ious = iou_matrix(np.asarray([box], dtype="float32"),
                                      cls_gt)[0]
                    j = int(ious.argmax())
                    if ious[j] >= self.iou_threshold and not used[j]:
                        used[j] = True
                        hit = True
                tps.append(hit)
        if n_gt == 0:
            return None
        if not scores:
            return 0.0
        order = np.argsort(-np.asarray(scores))
        tp = np.asarray(tps, dtype="float64")[order]
        cum_tp = np.cumsum(tp)
        recall = cum_tp / n_gt
        precision = cum_tp / (np.arange(len(tp)) + 1)
        ap = 0.0
        for r in np.linspace(0, 1, 11):
            p = precision[recall >= r]
            ap += (p.max() if len(p) else 0.0) / 11
        return float(ap)
