"""Config-driven classification backbones.

Reference parity: ``models/image/imageclassification/ImageClassificationConfig
.scala:15-40`` enumerates the model zoo (alexnet, inception-v1, resnet-50,
vgg-16/19, densenet-161, squeezenet, mobilenet, mobilenet-v2). Here each name
maps to a builder producing a functional :class:`~analytics_zoo_tpu.nn.graph`
``Model`` for NHWC inputs — TPU-native graphs (BN+conv fuse under XLA; all
convs NHWC so the MXU tiles them directly), not weight-compatible ports.

Every builder accepts ``input_shape=(H, W, 3)`` and ``num_classes`` so the same
topology scales from unit-test size to ImageNet size.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from ...nn import layers as L
from ...nn.graph import Input
from ...nn.topology import Model


def _conv_bn(x, filters, k, stride=1, activation="relu", mode="same"):
    x = L.Convolution2D(filters, k, k, subsample=(stride, stride),
                        border_mode=mode, use_bias=False)(x)
    x = L.BatchNormalization()(x)
    return L.Activation(activation)(x)


# --------------------------------------------------------------------- alexnet
def alexnet(input_shape=(224, 224, 3), num_classes=1000):
    inp = Input(input_shape)
    x = L.Convolution2D(64, 11, 11, subsample=(4, 4), border_mode="same",
                        activation="relu")(inp)
    x = L.MaxPooling2D((3, 3), strides=(2, 2))(x)
    x = L.Convolution2D(192, 5, 5, border_mode="same", activation="relu")(x)
    x = L.MaxPooling2D((3, 3), strides=(2, 2))(x)
    x = L.Convolution2D(384, 3, 3, border_mode="same", activation="relu")(x)
    x = L.Convolution2D(256, 3, 3, border_mode="same", activation="relu")(x)
    x = L.Convolution2D(256, 3, 3, border_mode="same", activation="relu")(x)
    x = L.GlobalAveragePooling2D()(x)
    x = L.Dense(num_classes, activation="softmax")(x)
    return Model(inp, x, name="alexnet")


# ------------------------------------------------------------------------ vgg
def _vgg(blocks, input_shape, num_classes, name):
    inp = Input(input_shape)
    x = inp
    for filters, reps in blocks:
        for _ in range(reps):
            x = L.Convolution2D(filters, 3, 3, border_mode="same",
                                activation="relu")(x)
        x = L.MaxPooling2D((2, 2))(x)
    x = L.GlobalAveragePooling2D()(x)
    x = L.Dense(num_classes, activation="softmax")(x)
    return Model(inp, x, name=name)


def vgg16(input_shape=(224, 224, 3), num_classes=1000):
    return _vgg([(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)],
                input_shape, num_classes, "vgg-16")


def vgg19(input_shape=(224, 224, 3), num_classes=1000):
    return _vgg([(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)],
                input_shape, num_classes, "vgg-19")


# --------------------------------------------------------------------- resnet
def _res_block(x, filters, stride, bottleneck):
    shortcut = x
    if bottleneck:
        y = _conv_bn(x, filters, 1, stride)
        y = _conv_bn(y, filters, 3)
        y = L.Convolution2D(filters * 4, 1, 1, border_mode="same",
                            use_bias=False)(y)
        y = L.BatchNormalization()(y)
        out_ch = filters * 4
    else:
        y = _conv_bn(x, filters, 3, stride)
        y = L.Convolution2D(filters, 3, 3, border_mode="same", use_bias=False)(y)
        y = L.BatchNormalization()(y)
        out_ch = filters
    if stride != 1 or shortcut.shape[-1] != out_ch:
        shortcut = L.Convolution2D(out_ch, 1, 1, subsample=(stride, stride),
                                   border_mode="same", use_bias=False)(shortcut)
        shortcut = L.BatchNormalization()(shortcut)
    y = L.Merge(mode="sum")([y, shortcut])
    return L.Activation("relu")(y)


def _resnet(layers_per_stage, bottleneck, input_shape, num_classes, name):
    inp = Input(input_shape)
    x = _conv_bn(inp, 64, 7, stride=2)
    x = L.MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    filters = 64
    for stage, reps in enumerate(layers_per_stage):
        for i in range(reps):
            stride = 2 if (stage > 0 and i == 0) else 1
            x = _res_block(x, filters, stride, bottleneck)
        filters *= 2
    x = L.GlobalAveragePooling2D()(x)
    x = L.Dense(num_classes, activation="softmax")(x)
    return Model(inp, x, name=name)


def resnet18(input_shape=(224, 224, 3), num_classes=1000):
    return _resnet([2, 2, 2, 2], False, input_shape, num_classes, "resnet-18")


def resnet50(input_shape=(224, 224, 3), num_classes=1000):
    return _resnet([3, 4, 6, 3], True, input_shape, num_classes, "resnet-50")


# ------------------------------------------------------------------ mobilenet
def mobilenet(input_shape=(224, 224, 3), num_classes=1000, alpha=1.0):
    inp = Input(input_shape)
    x = _conv_bn(inp, int(32 * alpha), 3, stride=2)
    cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
           (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
           (1024, 1)]
    for filters, stride in cfg:
        x = L.DepthwiseConv2D((3, 3), subsample=(stride, stride))(x)
        x = L.BatchNormalization()(x)
        x = L.Activation("relu")(x)
        x = _conv_bn(x, int(filters * alpha), 1)
    x = L.GlobalAveragePooling2D()(x)
    x = L.Dense(num_classes, activation="softmax")(x)
    return Model(inp, x, name="mobilenet")


def mobilenet_v2(input_shape=(224, 224, 3), num_classes=1000):
    def inverted_residual(x, filters, stride, expand):
        in_ch = x.shape[-1]
        y = _conv_bn(x, in_ch * expand, 1) if expand > 1 else x
        y = L.DepthwiseConv2D((3, 3), subsample=(stride, stride))(y)
        y = L.BatchNormalization()(y)
        y = L.Activation("relu")(y)
        y = L.Convolution2D(filters, 1, 1, border_mode="same", use_bias=False)(y)
        y = L.BatchNormalization()(y)
        if stride == 1 and in_ch == filters:
            y = L.Merge(mode="sum")([x, y])
        return y

    inp = Input(input_shape)
    x = _conv_bn(inp, 32, 3, stride=2)
    cfg = [(16, 1, 1, 1), (24, 2, 2, 6), (32, 3, 2, 6), (64, 4, 2, 6),
           (96, 3, 1, 6), (160, 3, 2, 6), (320, 1, 1, 6)]
    for filters, reps, stride, expand in cfg:
        for i in range(reps):
            x = inverted_residual(x, filters, stride if i == 0 else 1, expand)
    x = _conv_bn(x, 1280, 1)
    x = L.GlobalAveragePooling2D()(x)
    x = L.Dense(num_classes, activation="softmax")(x)
    return Model(inp, x, name="mobilenet-v2")


# ----------------------------------------------------------------- squeezenet
def squeezenet(input_shape=(224, 224, 3), num_classes=1000):
    def fire(x, squeeze, expand):
        s = L.Convolution2D(squeeze, 1, 1, border_mode="same",
                            activation="relu")(x)
        e1 = L.Convolution2D(expand, 1, 1, border_mode="same",
                             activation="relu")(s)
        e3 = L.Convolution2D(expand, 3, 3, border_mode="same",
                             activation="relu")(s)
        return L.Merge(mode="concat")([e1, e3])

    inp = Input(input_shape)
    x = L.Convolution2D(96, 7, 7, subsample=(2, 2), border_mode="same",
                        activation="relu")(inp)
    x = L.MaxPooling2D((3, 3), strides=(2, 2))(x)
    for squeeze, expand in [(16, 64), (16, 64), (32, 128)]:
        x = fire(x, squeeze, expand)
    x = L.MaxPooling2D((3, 3), strides=(2, 2))(x)
    for squeeze, expand in [(32, 128), (48, 192), (48, 192), (64, 256)]:
        x = fire(x, squeeze, expand)
    x = L.Convolution2D(num_classes, 1, 1, border_mode="same",
                        activation="relu")(x)
    x = L.GlobalAveragePooling2D()(x)
    x = L.Activation("softmax")(x)
    return Model(inp, x, name="squeezenet")


# ---------------------------------------------------------------- inception-v1
def inception_v1(input_shape=(224, 224, 3), num_classes=1000):
    def module(x, c1, c3r, c3, c5r, c5, pp):
        b1 = L.Convolution2D(c1, 1, 1, border_mode="same", activation="relu")(x)
        b3 = L.Convolution2D(c3r, 1, 1, border_mode="same", activation="relu")(x)
        b3 = L.Convolution2D(c3, 3, 3, border_mode="same", activation="relu")(b3)
        b5 = L.Convolution2D(c5r, 1, 1, border_mode="same", activation="relu")(x)
        b5 = L.Convolution2D(c5, 5, 5, border_mode="same", activation="relu")(b5)
        bp = L.MaxPooling2D((3, 3), strides=(1, 1), border_mode="same")(x)
        bp = L.Convolution2D(pp, 1, 1, border_mode="same", activation="relu")(bp)
        return L.Merge(mode="concat")([b1, b3, b5, bp])

    inp = Input(input_shape)
    x = L.Convolution2D(64, 7, 7, subsample=(2, 2), border_mode="same",
                        activation="relu")(inp)
    x = L.MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    x = L.Convolution2D(192, 3, 3, border_mode="same", activation="relu")(x)
    x = L.MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    x = module(x, 64, 96, 128, 16, 32, 32)
    x = module(x, 128, 128, 192, 32, 96, 64)
    x = L.MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    x = module(x, 192, 96, 208, 16, 48, 64)
    x = module(x, 256, 160, 320, 32, 128, 128)
    x = L.GlobalAveragePooling2D()(x)
    x = L.Dense(num_classes, activation="softmax")(x)
    return Model(inp, x, name="inception-v1")


BACKBONES: Dict[str, Callable] = {
    "alexnet": alexnet,
    "vgg-16": vgg16,
    "vgg-19": vgg19,
    "resnet-18": resnet18,
    "resnet-50": resnet50,
    "mobilenet": mobilenet,
    "mobilenet-v2": mobilenet_v2,
    "squeezenet": squeezenet,
    "inception-v1": inception_v1,
}


def build_backbone(name: str, input_shape: Tuple[int, int, int] = (224, 224, 3),
                   num_classes: int = 1000):
    try:
        builder = BACKBONES[name]
    except KeyError:
        raise ValueError(f"unknown backbone {name!r}; known: {sorted(BACKBONES)}")
    return builder(input_shape=input_shape, num_classes=num_classes)
