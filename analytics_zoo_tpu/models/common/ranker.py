"""Ranker — NDCG / MAP evaluation mixin for ranking models.

Parity: /root/reference/pyzoo/zoo/models/common/ranker.py:28-63 (``evaluate_ndcg``,
``evaluate_map``) and .../models/common/Ranker.scala:81-99. The reference evaluates
over a TextSet of per-query batches; here each "query group" is one batch of
(features, labels) and scoring is a single device sweep.
"""

from __future__ import annotations

from typing import Iterable, Sequence, Tuple

import numpy as np

from ...nn.metrics import map_at_k, ndcg_at_k


class Ranker:
    """Mixin for models whose ``predict`` scores query/candidate batches."""

    def _group_scores(self, groups: Iterable[Tuple[np.ndarray, np.ndarray]]):
        for x, labels in groups:
            scores = np.asarray(self.predict(x)).reshape(-1)
            yield np.asarray(labels, dtype="float32").reshape(-1), scores

    def evaluate_ndcg(self, groups, k: int, threshold: float = 0.0) -> float:
        """Mean NDCG@k over query groups (Ranker.scala:99 parity).

        ``groups``: iterable of (features, labels) — one entry per query. Labels
        ≤ ``threshold`` contribute zero gain; graded labels keep their grade
        (gain ``2^label``, Ranker.scala:134).
        """
        vals = [ndcg_at_k(np.where(labels > threshold, labels, 0.0), scores, k)
                for labels, scores in self._group_scores(groups)]
        if not vals:
            raise ValueError("no query groups to evaluate")
        return float(np.mean(vals))

    def evaluate_map(self, groups, threshold: float = 0.0) -> float:
        """Mean average precision over query groups (Ranker.scala:81 parity)."""
        vals = []
        for labels, scores in self._group_scores(groups):
            rel = (labels > threshold).astype("float32")
            vals.append(map_at_k(rel, scores, len(scores)))
        if not vals:
            raise ValueError("no query groups to evaluate")
        return float(np.mean(vals))
