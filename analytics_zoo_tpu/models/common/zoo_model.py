"""ZooModel persistence — save/load of model definition + weights.

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/models/common/
ZooModel.scala:38-149 (``saveModel``/``loadModel`` of the ``.analytics-zoo``
format). The TPU-native format is a directory bundle:

    <path>/
      config.json     # model class + constructor kwargs (rebuildable models)
      weights.npz     # flat leaves of (params, model_state)
      tree.json       # key paths for the leaves

Built-in models register themselves in ``MODEL_REGISTRY`` so ``load_model`` can
reconstruct the architecture, then restore weights.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

MODEL_REGISTRY: Dict[str, Callable] = {}


def register_model(name: str):
    def deco(cls):
        MODEL_REGISTRY[name] = cls
        return cls
    return deco


def _flatten(tree) -> Tuple[Dict[str, np.ndarray], Any]:
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)
    flat = {}
    for path, leaf in leaves_with_paths[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = np.asarray(jax.device_get(leaf))
    return flat, leaves_with_paths[1]


def save_weights(path: str, params, model_state=None) -> None:
    os.makedirs(path, exist_ok=True)
    flat, _ = _flatten({"params": params, "state": model_state or {}})
    np.savez(os.path.join(path, "weights.npz"), **flat)
    with open(os.path.join(path, "tree.json"), "w") as f:
        json.dump(sorted(flat.keys()), f)


def load_weights(path: str, params_template, state_template=None):
    """Restore weights into pytrees shaped like the templates."""
    data = np.load(os.path.join(path, "weights.npz"))
    tree = {"params": params_template, "state": state_template or {}}
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    new_leaves = []
    for p, leaf in paths_and_leaves:
        key = "/".join(str(getattr(q, "key", getattr(q, "idx", q))) for q in p)
        if key not in data:
            raise KeyError(f"weight {key!r} missing from {path}")
        arr = data[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"{key}: saved {arr.shape} != expected {np.shape(leaf)}")
        new_leaves.append(arr)
    restored = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return restored["params"], restored["state"]


def save_model_bundle(path: str, model, config: Optional[Dict] = None) -> None:
    """Save a compiled KerasNet (weights + reconstruction config)."""
    os.makedirs(path, exist_ok=True)
    est = getattr(model, "estimator", None)
    if est is None or est.train_state is None:
        raise RuntimeError("model has no trained state; compile+fit (or build) first")
    save_weights(path, est.train_state["params"], est.train_state["model_state"])
    cfg = {"class": type(model).__name__, "config": config or {}}
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(cfg, f)


def load_model_bundle(path: str, model=None):
    """Load a bundle. If ``model`` is given, restore weights into it; otherwise
    reconstruct from MODEL_REGISTRY (built-in zoo models)."""
    with open(os.path.join(path, "config.json")) as f:
        cfg = json.load(f)
    if model is None:
        cls = MODEL_REGISTRY.get(cfg["class"])
        if cls is None:
            raise ValueError(
                f"unknown model class {cfg['class']!r}; pass model= explicitly "
                f"(registered: {sorted(MODEL_REGISTRY)})")
        model = cls(**cfg["config"])
    return model, cfg
