"""ZooModel persistence — save/load of model definition + weights.

Parity: /root/reference/zoo/src/main/scala/com/intel/analytics/zoo/models/common/
ZooModel.scala:38-149 (``saveModel``/``loadModel`` of the ``.analytics-zoo``
format). The TPU-native format is a directory bundle:

    <path>/
      config.json     # model class + constructor kwargs (rebuildable models)
      weights.npz     # params/state leaves keyed by their pytree path
      manifest.json   # sorted key list (integrity check)

Key determinism: container modules (GraphModule/SequentialModule) key params by
POSITIONAL slots (``0_dense``), and custom modules use fixed string keys, so pytree
paths are identical across processes for the same architecture. Both missing and
unexpected keys fail loudly on load.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

MODEL_REGISTRY: Dict[str, Callable] = {}


def register_model(name: str):
    def deco(cls):
        MODEL_REGISTRY[name] = cls
        return cls
    return deco


def _leaf_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def _flatten_tree(tree) -> Dict[str, np.ndarray]:
    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {_leaf_key(p): np.asarray(jax.device_get(l)) for p, l in paths_and_leaves}


def save_weights(path: str, module, params, model_state=None) -> None:
    """Save (params, state) as a weights bundle. ``module`` is accepted for
    signature stability (future per-layer remapping) but keys come from the
    pytree paths, which the slot convention makes deterministic."""
    del module
    os.makedirs(path, exist_ok=True)
    flat = _flatten_tree({"params": params, "state": model_state or {}})
    if not flat:
        raise ValueError("refusing to save an empty weight tree")
    np.savez(os.path.join(path, "weights.npz"), **flat)
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(sorted(flat.keys()), f)


def load_weights(path: str, module, params_template, state_template=None):
    """Restore a bundle into templates from a structurally-identical module.

    Fails loudly on ANY mismatch: missing keys, unexpected keys, or shape
    disagreement (no silent partial restores).
    """
    del module
    state_template = state_template or {}
    data = np.load(os.path.join(path, "weights.npz"))
    tree = {"params": params_template, "state": state_template}
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    expected = {_leaf_key(p) for p, _ in paths_and_leaves}
    saved = set(data.files)
    if expected != saved:
        missing = sorted(expected - saved)[:5]
        extra = sorted(saved - expected)[:5]
        raise ValueError(
            f"weight bundle mismatch at {path}: "
            f"{len(expected - saved)} missing (e.g. {missing}), "
            f"{len(saved - expected)} unexpected (e.g. {extra})")
    leaves = []
    for p, leaf in paths_and_leaves:
        key = _leaf_key(p)
        arr = data[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"{key}: saved {arr.shape} != expected {np.shape(leaf)}")
        leaves.append(arr)
    restored = jax.tree_util.tree_unflatten(treedef, leaves)
    return restored["params"], restored["state"]


def save_model_bundle(path: str, model, config: Optional[Dict] = None) -> None:
    """Save a compiled KerasNet (weights + reconstruction config)."""
    os.makedirs(path, exist_ok=True)
    est = getattr(model, "estimator", None)
    if est is None or est.train_state is None:
        raise RuntimeError("model has no trained state; compile+fit (or build) first")
    save_weights(path, model, est.train_state["params"],
                 est.train_state["model_state"])
    cfg = {"class": type(model).__name__, "config": config or {}}
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(cfg, f)


def load_model_bundle(path: str, model=None):
    """Load a bundle. If ``model`` is given, restore into it (immediately when it
    is compiled, else on its next ``compile``); otherwise rebuild the architecture
    from MODEL_REGISTRY (built-in zoo models) and defer weights to ``compile``."""
    with open(os.path.join(path, "config.json")) as f:
        cfg = json.load(f)
    if model is None:
        cls = MODEL_REGISTRY.get(cfg["class"])
        if cls is None:
            raise ValueError(
                f"unknown model class {cfg['class']!r}; pass model= explicitly "
                f"(registered: {sorted(MODEL_REGISTRY)})")
        model = cls(**cfg["config"])
    model.load_weights(path)
    return model, cfg
