from .ranker import Ranker
from .zoo_model import (MODEL_REGISTRY, load_model_bundle, load_weights,
                        register_model, save_model_bundle, save_weights)

__all__ = ["MODEL_REGISTRY", "Ranker", "load_model_bundle", "load_weights",
           "register_model", "save_model_bundle", "save_weights"]
