"""Training engine: the ``Estimator`` / ``InternalDistriOptimizer`` replacement.

Parity map (reference → here):
* ``AbstractEstimator.train/evaluate`` (/root/reference/zoo/.../pipeline/estimator/
  Estimator.scala:33-46) → :class:`Estimator.fit/evaluate`.
* ``InternalDistriOptimizer.train`` (Topology.scala:1086-1269): per-iteration Spark
  job + AllReduceParameter block-manager gradient exchange → ONE jitted step over a
  ``jax.sharding.Mesh``; the batch is sharded over the ``dp``(+``fsdp``) axes, params
  are replicated (or fsdp-sharded), and XLA inserts the gradient ``psum`` over ICI.
  The whole hot loop (Topology.scala:1188-1207's optimizeModels) is a single
  device-side program — no driver round-trips.
* Failure retry from checkpoint (Topology.scala:1181-1263) → :meth:`Estimator.fit`'s
  retry loop (``retry_times`` = ``bigdl.failure.retryTimes`` default 5).
* Gradient clipping config (Topology.scala:161-194) → ``TrainConfig.gradient_clip_*``.
* TB summaries Loss/LearningRate/Throughput (Topology.scala:196-239) → TrainSummary.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common import memwitness as _mw
from ..common import telemetry as _tm
from ..common.chaos import chaos_point
from ..common.config import TrainConfig
from ..common.context import get_zoo_context
from ..common.resilience import ResilienceError, RetryPolicy
from ..common.summary import TrainSummary, ValidationSummary
from ..common.triggers import (EveryEpoch, MaxEpoch, SeveralIteration, Trigger,
                               TrainerState)
from ..data.featureset import FeatureSet
from ..data.pipeline import PrefetchLoader
from ..nn.losses import get_loss
from ..nn.metrics import Metric, get_metric
from ..nn.module import Layer, cast_params, precision_policy
from ..nn.optimizers import get_optimizer, with_clipping
from ..parallel import update_sharding as upd
from . import checkpoint as ckpt

logger = logging.getLogger("analytics_zoo_tpu.estimator")

# per-step training breakdown (ISSUE 3): is the loop data-bound or
# device-bound? DataWait = time blocked on the host input pipeline;
# Compute = everything else in the step window (dispatch + device execution,
# synced at each log point by the loss transfer). The same numbers flush to
# TrainSummary (TensorBoard + metrics.jsonl) and land here for /metrics.
_STEPS = _tm.counter("zoo_train_steps_total", "Optimizer steps run")
_DATA_WAIT = _tm.histogram("zoo_train_data_wait_seconds",
                           "Per-step host wait on the input pipeline")
_COMPUTE = _tm.histogram("zoo_train_compute_seconds",
                         "Per-step dispatch + device time (window mean, "
                         "synced at log points)")
_COMPILES = _tm.counter("zoo_train_compiles_total",
                        "Train-step executables built (first dispatch of a "
                        "jitted step/scan-block)")
_COMPILE_TIME = _tm.histogram("zoo_train_compile_seconds",
                              "Wall time of first-dispatch (compile) steps",
                              buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30,
                                       60, 120))
_ROLLBACKS = _tm.counter("zoo_train_rollbacks_total",
                         "Checkpoint rollbacks taken by fit's retry loop")
_CHECKPOINTS = _tm.counter("zoo_train_checkpoints_total",
                           "Checkpoints saved")
_SIGTERM_EXITS = _tm.counter("zoo_train_sigterm_exits_total",
                             "Graceful SIGTERM teardowns (final checkpoint "
                             "+ exit 143)")
_GRAD_NORM = _tm.histogram("zoo_train_grad_norm",
                           "f32 global (pre-clip) gradient L2 norm, observed "
                           "at log points",
                           buckets=(0.001, 0.01, 0.1, 0.5, 1, 2.5, 5, 10, 25,
                                    100, 1000))
_COMM = _tm.histogram("zoo_train_comm_seconds",
                      "Measured one-round gradient-exchange time (param-sized "
                      "collective probe on the dp axis, timed off the hot "
                      "path at each log point)",
                      buckets=(.0001, .0005, .001, .0025, .005, .01, .025,
                               .05, .1, .25, 1))


class _GracefulStop(BaseException):
    """Raised inside the epoch loop when SIGTERM requested a clean exit.
    BaseException so the retry-from-checkpoint handler cannot absorb it."""


def _overlay(base: dict, donated: dict) -> dict:
    """Deep-merge donated weights over a fresh init (missing keys keep their
    fresh values — the transfer-learning partial-donor path)."""
    out = dict(base)
    for k, v in donated.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _overlay(out[k], v)
        else:
            out[k] = v
    return out


def _walk_layers(module):
    """Yield every layer reachable through nested containers (graph/sequential
    sub-modules expose ``.layers``)."""
    seen = set()
    stack = [module]
    while stack:
        m = stack.pop()
        if id(m) in seen:
            continue
        seen.add(id(m))
        yield m
        stack.extend(getattr(m, "layers", ()) or ())


def _as_featureset(data, batch_size=None) -> FeatureSet:
    if isinstance(data, FeatureSet):
        return data
    if isinstance(data, tuple) and len(data) == 2:
        return FeatureSet.from_numpy(data[0], data[1])
    raise TypeError(f"cannot build FeatureSet from {type(data)}")


class Estimator:
    """Drives a compiled train step over the global mesh."""

    def __init__(self, model: Layer, optimizer="adam", loss="mse",
                 mesh=None, config: Optional[TrainConfig] = None,
                 param_sharding: Optional[Callable] = None):
        self.model = model
        self.loss_fn = get_loss(loss)
        self.config = config or TrainConfig()
        self._base_tx = get_optimizer(optimizer)
        self._train_step = None
        self._step_shapes: set = set()
        self._rebuild_tx()
        # flat (BigDL AllReduceParameter-layout) update sharding: static
        # flattening meta, built by _init_state when the mode engages
        self._flat_meta = None
        self._comm_probe_cache = None
        self.mesh = mesh if mesh is not None else get_zoo_context().mesh
        # models that carry their own placement strategy (e.g.
        # PipelinedTransformerLM's stage-over-pp layout) expose
        # ``param_spec(path, leaf) -> PartitionSpec``; an explicit
        # param_sharding argument still wins
        if param_sharding is None:
            param_sharding = getattr(model, "param_spec", None)
        self.param_sharding = param_sharding
        self.train_state: Optional[Dict[str, Any]] = None
        self.trainer_state = TrainerState()
        # _step_shapes/_scan_shapes: compile-event detection keys on the
        # dispatched batch signature (jit re-traces per shape/dtype): a second
        # fit() with a new batch_size is a fresh compile that must be
        # attributed to zoo_train_compile_*, not silently smeared into that
        # window's ComputeMs (_step_shapes is created before _rebuild_tx above)
        self._scan_shapes: set = set()
        self.train_summary: Optional[TrainSummary] = None
        self.val_summary: Optional[ValidationSummary] = None
        self._eval_cache: Dict[Any, Callable] = {}
        # optional (params, model_state) replacing the fresh init — used by
        # model-bundle loading (ZooModel.loadModel); weights were already read
        # from disk eagerly by KerasNet.load_weights
        self.initial_weights: Optional[tuple] = None
        # set True when initial_weights holds only SOME layers' params
        # (transfer learning) — missing slots then keep a fresh init
        self.initial_weights_partial = False
        # at-most-one-in-flight async checkpoint writer (created lazily on
        # the first save when config.async_checkpoint)
        self._ckpt_writer: Optional[ckpt.CheckpointWriter] = None
        # recompilation-hazard tracker over step signatures (lazy; see
        # _note_step_signature)
        self._recompile_tracker = None

    def _rebuild_tx(self) -> "Estimator":
        """(Re)compose the optimizer chain from ``_base_tx``: clipping first,
        then — under mixed precision (TrainConfig.compute_dtype="bfloat16",
        where fwd/bwd run in the compute dtype against f32 master weights
        living ONLY in the possibly-dp-sharded optimizer state) — the
        ``with_master_weights`` wrapper whose "updates" ARE the new
        low-precision params. Invalidates the compiled step. The single
        authority for this wiring — __init__, set_gradient_clipping, and
        _refresh_precision all go through here."""
        self.tx = with_clipping(self._base_tx, self.config.gradient_clip_norm,
                                self.config.gradient_clip_value)
        self._mp_dtype = None
        if (self.config.compute_dtype is not None
                and jnp.dtype(self.config.compute_dtype) != jnp.float32):
            self._mp_dtype = jnp.dtype(self.config.compute_dtype)
            self.tx = upd.with_master_weights(self.tx)
        self._train_step = None
        self._step_shapes.clear()
        return self

    def set_gradient_clipping(self, clip_norm: Optional[float] = None,
                              clip_value: Optional[tuple] = None) -> "Estimator":
        """Re-wrap the optimizer with clipping after construction
        (setGradientClippingByL2Norm / setConstantGradientClipping parity).

        Must be called before the first fit step; it rebuilds the compiled step.
        """
        if self.train_state is not None:
            raise RuntimeError("set clipping before training starts: optimizer "
                               "state is already initialized")
        self.config.gradient_clip_norm = clip_norm
        self.config.gradient_clip_value = clip_value
        return self._rebuild_tx()

    def _refresh_precision(self) -> "Estimator":
        """Recompute the mixed-precision wiring after ``config.compute_dtype``
        changed post-construction (the orca facade's per-fit override). Must
        run before the first fit step — the state dtype layout is built once."""
        if self.train_state is not None:
            raise RuntimeError("compute_dtype must be set before training "
                               "starts: params/optimizer dtypes are already "
                               "laid out")
        return self._rebuild_tx()

    # ------------------------------------------------------------------ shardings
    def _batch_axes(self) -> Tuple[str, ...]:
        return ("dp", "fsdp")

    def _batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self._batch_axes()))

    def _replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def _update_mode(self) -> Optional[str]:
        """Weight-update sharding mode: ``None`` (replicated update),
        ``"flat"`` (BigDL-layout reduce-scatter/shard-update/all-gather inside
        shard_map — pure-dp meshes), or ``"gspmd"`` (per-leaf dp-extended
        optimizer-state placement composed with the fsdp/tp rules)."""
        us = self.config.update_sharding
        if not us:
            return None
        dp = self.mesh.shape.get("dp", 1)
        if dp <= 1:
            return None
        pure_dp = all(size == 1 for name, size in self.mesh.shape.items()
                      if name != "dp")
        if us == "gspmd":
            return "gspmd"
        if pure_dp and self.param_sharding is None:
            return "flat"
        if us == "flat":
            logger.warning("update_sharding='flat' needs a pure-dp mesh and "
                           "no param_sharding rules; using gspmd placement")
        return "gspmd"

    def _state_spec(self, path, leaf, mode, upd_rule) -> P:
        """PartitionSpec for one train-state leaf: base param rule everywhere,
        with the opt_state subtree overridden by the update-sharding mode."""
        in_opt = bool(path) and str(getattr(path[0], "key", "")) == "opt_state"
        if mode == "flat":
            if (in_opt and self._flat_meta is not None
                    and tuple(getattr(leaf, "shape", ()))
                    == (self._flat_meta.npad,)):
                return P("dp")
            return P()       # flat mode implies no base rules (pure-dp mesh)
        if in_opt and upd_rule is not None:
            return upd_rule(path, leaf)
        return (self.param_sharding(path, leaf)
                if self.param_sharding is not None else P())

    def _place_state(self, state):
        """Lay train state onto the mesh: replicated by default, per
        ``param_sharding(path, leaf) -> PartitionSpec`` (fsdp/tp rules), and —
        under update sharding — the opt_state subtree dp-sharded congruent
        with the grad shards (ZeRO-1: each replica owns 1/dp of the optimizer
        state, master weights included)."""
        mode = self._update_mode()
        if self.param_sharding is None and mode is None:
            return jax.device_put(state, self._replicated())
        upd_rule = (upd.make_update_sharding(self.mesh, self.param_sharding)
                    if mode == "gspmd" else None)

        def put(path, leaf):
            spec = self._state_spec(path, leaf, mode, upd_rule)
            return jax.device_put(leaf, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map_with_path(put, state)

    def _to_global(self, host_batch):
        """Host-local shard → global sharded jax.Array (multi-host safe).

        Partial trailing batches that don't divide the dp axes fall back to a
        replicated layout (evaluate/predict only; training drops remainders).
        """
        sharding = self._batch_sharding()
        n_shards = 1
        for ax in self._batch_axes():
            n_shards *= self.mesh.shape[ax]

        def put(a):
            a = np.asarray(a)
            local_ok = (a.shape[0] * get_zoo_context().process_count) % n_shards == 0
            s = sharding if local_ok else self._replicated()
            return jax.make_array_from_process_local_data(s, a)

        return jax.tree_util.tree_map(put, host_batch)

    # ------------------------------------------------------------------- build
    def _init_state(self, sample_batch, seed: int = 0):
        x = sample_batch[0]
        in_shape = (tuple(x[0].shape[1:]) if isinstance(x, (tuple, list))
                    else tuple(x.shape[1:]))
        if isinstance(x, (tuple, list)):
            in_shape = [tuple(xi.shape[1:]) for xi in x]
        rng = jax.random.PRNGKey(seed)
        k_init, k_train = jax.random.split(rng)
        if self.initial_weights is not None:
            params, mstate = self.initial_weights
            if self.initial_weights_partial and isinstance(params, dict):
                # partial donation (transfer learning: some layers donated,
                # new heads freshly initialized) — overlay on a fresh init.
                # Opt-in flag: the common full-donation/resume path must not
                # pay a throwaway fresh build.
                fresh_p, fresh_s = self.model.build(k_init, in_shape)
                params = _overlay(fresh_p, params)
                mstate = _overlay(fresh_s, mstate or {})
        else:
            params, mstate = self.model.build(k_init, in_shape)
        # params come out of build() in f32 (param_dtype policy); under mixed
        # precision the MODEL copy is cast down and the f32 values survive
        # only as master weights inside the optimizer state
        model_params = (cast_params(params, self._mp_dtype)
                        if self._mp_dtype is not None else params)
        mode = self._update_mode()
        if mode == "flat":
            self._flat_meta = upd.flat_meta(model_params,
                                            self.mesh.shape["dp"])
            opt_state = upd.flat_opt_init(
                self._base_tx, params, self._flat_meta,
                keep_master=self._mp_dtype is not None)
        else:
            opt_state = self.tx.init(params)
        state = {
            "params": model_params,
            "opt_state": opt_state,
            "model_state": mstate,
            "step": jnp.zeros((), jnp.int32),
            "rng": k_train,
        }
        return self._place_state(state)

    def _grads_fn(self, micro_constraint=None):
        """Build ``(params, mstate, rng, batch) -> (loss, new_mstate, grads)``.

        With ``config.grad_accum_steps == K > 1`` the batch is reshaped to K
        microbatches consumed by a ``lax.scan`` inside the jitted step (the
        grad accumulator rides the scan carry, which XLA updates in place —
        the donated-carry property): grads accumulate in f32 and are divided
        by K once, so the result is the global-batch mean gradient and any
        gradient collective pays once per GLOBAL step, amortizing comm K×.
        ``micro_constraint``: NamedSharding for the (K, micro, ...) layout on
        the GSPMD paths (None inside shard_map, where data is already local).
        """
        model, loss_fn = self.model, self.loss_fn
        K = max(1, int(self.config.grad_accum_steps))

        def loss_of(p, mstate, rng, x, y):
            y_hat, new_mstate = model.apply(p, mstate, x, training=True,
                                            rng=rng)
            total = loss_fn(y, y_hat)
            # 0.0 unless layers carry w/b regularizers
            reg_fn = getattr(model, "regularization", None)
            if reg_fn is not None:
                total = total + reg_fn(p)
            return total, new_mstate

        grad_of = jax.value_and_grad(loss_of, has_aux=True)

        def single(params, mstate, rng, batch):
            x, y = batch
            (loss, new_mstate), grads = grad_of(params, mstate, rng, x, y)
            return loss, new_mstate, grads

        if K == 1:
            return single

        def accum(params, mstate, rng, batch):
            def to_micro(a):
                return a.reshape((K, a.shape[0] // K) + a.shape[1:])

            micro = jax.tree_util.tree_map(to_micro, batch)
            if micro_constraint is not None:
                micro = jax.tree_util.tree_map(
                    lambda a: jax.lax.with_sharding_constraint(
                        a, micro_constraint), micro)

            def body(carry, mb):
                acc, mst, i = carry
                loss, mst2, g = single(params, mst,
                                       jax.random.fold_in(rng, i), mb)
                acc = jax.tree_util.tree_map(
                    lambda a, gg: a + gg.astype(a.dtype), acc, g)
                return (acc, mst2, i + jnp.int32(1)), loss

            zero = jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
            (acc, new_mstate, _), losses = jax.lax.scan(
                body, (zero, mstate, jnp.int32(0)), micro)
            grads = jax.tree_util.tree_map(lambda g: g / K, acc)
            return jnp.mean(losses), new_mstate, grads

        return accum

    def _step_fn(self):
        """The raw (state, batch) -> (state, (loss, grad_norm)) transition
        shared by the per-batch jitted step and the scanned device-cached
        epoch runner. Three update layouts (see parallel/update_sharding.py):
        replicated (classic), "gspmd" (grads constrained to dp-extended specs
        so the partitioner reduce-scatters into the sharded optimizer state
        and all-gathers params back), and "flat" (the shard_map BigDL-layout
        exchange, built by _flat_step_fn)."""
        mode = self._update_mode()
        if mode == "flat":
            return self._flat_step_fn()
        cfg = self.config
        mesh = self.mesh
        mp = self._mp_dtype is not None
        tx = self.tx
        base_rule = self.param_sharding
        micro_ns = (NamedSharding(mesh, P(None, self._batch_axes()))
                    if cfg.grad_accum_steps > 1 else None)
        grads_fn = self._grads_fn(micro_constraint=micro_ns)
        upd_rule = (upd.make_update_sharding(mesh, base_rule)
                    if mode == "gspmd" else None)

        def step(state, batch):
            rng = jax.random.fold_in(state["rng"], state["step"])
            loss, new_mstate, grads = grads_fn(
                state["params"], state["model_state"], rng, batch)
            # f32 grads from here on: the accumulation path already summed in
            # f32; the single-batch mixed-precision path casts up so clipping
            # and the update run against full-precision values
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            gnorm = optax.global_norm(grads)
            if upd_rule is not None:
                # dp-sharded grad placement congruent with the optimizer
                # state: the partial→sharded transition is the reduce-scatter
                grads = jax.tree_util.tree_map_with_path(
                    lambda p, g: jax.lax.with_sharding_constraint(
                        g, NamedSharding(mesh, upd_rule(p, g))), grads)
            updates, new_opt = tx.update(grads, state["opt_state"],
                                         state["params"])
            if mp:
                # with_master_weights returns the NEW low-precision params
                new_params = updates
            else:
                new_params = optax.apply_updates(state["params"], updates)
            if upd_rule is not None:
                # back to the base (replicated / fsdp/tp) layout: the
                # sharded→base transition is the params all-gather
                def back(path, leaf):
                    spec = base_rule(path, leaf) if base_rule else P()
                    return jax.lax.with_sharding_constraint(
                        leaf, NamedSharding(mesh, spec))

                new_params = jax.tree_util.tree_map_with_path(back, new_params)
            new_state = {
                "params": new_params,
                "opt_state": new_opt,
                "model_state": new_mstate,
                "step": state["step"] + 1,
                "rng": state["rng"],
            }
            return new_state, (loss, gnorm)

        return step

    def _flat_step_fn(self):
        """Pure-dp weight-update sharding: the whole step runs inside
        ``shard_map`` (manual over the mesh), so per-replica grads stay local
        through the accumulation scan and the exchange is structurally ONE
        reduce-scatter + one params all-gather per global step —
        BigDL ``AllReduceParameter``'s slice-owner update, TPU-native."""
        from ..common.compat import shard_map

        cfg = self.config
        mesh = self.mesh
        base_tx = self._base_tx
        batch_axes = self._batch_axes()
        grads_fn = self._grads_fn()

        def step(state, batch):
            meta = self._flat_meta

            def body(st, bt):
                rng = jax.random.fold_in(st["rng"], st["step"])
                # decorrelate per-replica dropout/negative-sampling masks
                rng = jax.random.fold_in(rng, jax.lax.axis_index("dp"))
                loss, mstate2, grads = grads_fn(st["params"],
                                                st["model_state"], rng, bt)
                new_params, new_opt, gnorm = upd.flat_exchange(
                    st["params"], grads, st["opt_state"], meta, base_tx,
                    clip_norm=cfg.gradient_clip_norm,
                    clip_value=cfg.gradient_clip_value)
                loss = jax.lax.pmean(loss, "dp")
                # keep float model state (batchnorm EMAs computed from LOCAL
                # batch stats) replicated-consistent across replicas
                mstate2 = jax.tree_util.tree_map(
                    lambda a: (jax.lax.pmean(a, "dp")
                               if jnp.issubdtype(jnp.asarray(a).dtype,
                                                 jnp.floating) else a),
                    mstate2)
                new_state = {
                    "params": new_params,
                    "opt_state": new_opt,
                    "model_state": mstate2,
                    "step": st["step"] + 1,
                    "rng": st["rng"],
                }
                return new_state, (loss, gnorm)

            mode_rule = None  # flat mode: no per-leaf gspmd rule
            state_specs = jax.tree_util.tree_map_with_path(
                lambda p, l: self._state_spec(p, l, "flat", mode_rule), state)
            batch_specs = jax.tree_util.tree_map(
                lambda _: P(batch_axes), batch)
            fn = shard_map(body, mesh=mesh,
                           in_specs=(state_specs, batch_specs),
                           out_specs=(state_specs, (P(), P())),
                           check_vma=False)
            return fn(state, batch)

        return step

    def _with_policy(self, fn):
        """Engage TrainConfig.compute_dtype as the precision policy for the
        dynamic extent of each dispatch (policy is read at TRACE time by the
        layers' ``as_compute``; wrapping the call covers the trace)."""
        if self.config.compute_dtype is None:
            return fn
        dt = self.config.compute_dtype

        def wrapped(*args):
            with precision_policy(compute_dtype=dt):
                return fn(*args)

        return wrapped

    def _make_train_step(self):
        donate = (0,) if self.config.donate_state else ()
        return self._with_policy(jax.jit(self._step_fn(),
                                         donate_argnums=donate))

    def _make_scan_block(self):
        """Device-cached mode: one jitted call running ``scan_block_steps``
        train steps via ``lax.scan``, gathering each batch from the
        HBM-resident dataset by index (TPU-first replacement for the
        reference's per-iteration Spark job — zero host work per step)."""
        step = self._step_fn()
        batch_sharding = self._batch_sharding()

        def block(state, data, idx_mat):
            def body(st, idxs):
                batch = jax.tree_util.tree_map(
                    lambda a: jax.lax.with_sharding_constraint(
                        jnp.take(a, idxs, axis=0), batch_sharding), data)
                return step(st, batch)

            return jax.lax.scan(body, state, idx_mat)

        donate = (0,) if self.config.donate_state else ()
        return self._with_policy(jax.jit(block, donate_argnums=donate))

    # --------------------------------------------------------------------- fit
    def fit(self, data, batch_size: Optional[int] = None,
            epochs: Optional[int] = None, end_trigger: Optional[Trigger] = None,
            validation_data=None, validation_metrics: Sequence = (),
            checkpoint_trigger: Optional[Trigger] = None, seed: int = 0):
        """Train until ``end_trigger`` (default: MaxEpoch(config.max_epochs)).

        ``data``: FeatureSet or (x, y) arrays. ``batch_size`` is global.
        The loop structure mirrors InternalDistriOptimizer.train
        (Topology.scala:1086-1269) including retry-from-checkpoint — the
        retry budget is policy-driven (TrainConfig.retry_times /
        retry_backoff_s / retry_deadline_s through a
        :class:`~analytics_zoo_tpu.common.resilience.RetryPolicy`), and with
        ``config.graceful_shutdown`` a SIGTERM mid-fit saves one final
        checkpoint before exiting with status 143 — the preemption-safe
        teardown a supervisor (k8s, borg) expects.
        """
        cfg = self.config
        batch_size = batch_size or cfg.batch_size
        accum = max(1, int(cfg.grad_accum_steps))
        if accum > 1:
            n_shards = 1
            for ax in self._batch_axes():
                n_shards *= self.mesh.shape[ax]
            if batch_size % (accum * n_shards):
                raise ValueError(
                    f"batch_size={batch_size} must divide by "
                    f"grad_accum_steps={accum} x dp-shards={n_shards}: each "
                    f"of the {accum} microbatches is itself sharded over the "
                    f"dp axes — pick batch_size as a multiple of "
                    f"{accum * n_shards}")
        train_set = _as_featureset(data)
        end_trigger = end_trigger or MaxEpoch(epochs if epochs is not None
                                              else cfg.max_epochs)
        # Default cadence is the epoch-end save built into _run_epoch; a mid-epoch
        # trigger is only installed when explicitly requested (EveryEpoch parity).
        if checkpoint_trigger is None and cfg.checkpoint_every_n_iters:
            checkpoint_trigger = SeveralIteration(cfg.checkpoint_every_n_iters)

        if self._train_step is None:
            self._train_step = self._make_train_step()

        # init or resume
        first = None
        if self.train_state is None:
            first = next(train_set.batches(batch_size, epoch=0, shuffle=False))
            self.train_state = self._init_state(first, seed=seed)
            if cfg.checkpoint_dir:
                latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
                if latest:
                    restored, meta = ckpt.load_checkpoint(latest, self.train_state)
                    self.train_state = self._place_state(restored)
                    self.trainer_state.iteration = meta["iteration"]
                    self.trainer_state.epoch = meta["epoch"]
                    logger.info("resumed from %s (iter %d)", latest, meta["iteration"])

        # opt-in trace-time static analysis of the step about to train
        # (TrainConfig.graph_checks): a broken structural invariant —
        # collective budget, closure-captured weights, host round-trips,
        # dtype leaks — surfaces HERE, before the first (expensive) compile,
        # instead of at the next bench run
        if cfg.graph_checks and cfg.graph_checks != "off":
            if first is None:
                first = next(train_set.batches(batch_size, epoch=0,
                                               shuffle=False))
            self._run_graph_checks(first)

        # retry-from-checkpoint budget (Topology.scala:1181-1263), now policy-
        # driven: retry_times attempts with exponential backoff between
        # rollbacks and an optional overall deadline. The policy is the shared
        # resilience primitive; the rollback side effects stay here.
        retry_policy = RetryPolicy(
            max_attempts=cfg.retry_times + 1, base_delay_s=cfg.retry_backoff_s,
            max_delay_s=cfg.retry_max_backoff_s,
            deadline_s=cfg.retry_deadline_s, jitter=0.1, seed=seed)
        tracker = retry_policy.tracker()
        self._sigterm = False
        prev_handler = None
        handler_installed = (cfg.graceful_shutdown
                             and threading.current_thread()
                             is threading.main_thread())
        if handler_installed:
            prev_handler = signal.signal(
                signal.SIGTERM,
                lambda *_: setattr(self, "_sigterm", True))
        try:
            while not end_trigger(self.trainer_state):
                try:
                    self._run_epoch(train_set, batch_size, checkpoint_trigger)
                except (KeyboardInterrupt, ValueError, TypeError):
                    raise
                except Exception as e:  # retry-from-checkpoint
                    if not cfg.checkpoint_dir:
                        raise
                    # a rollback must never pick a checkpoint whose write is
                    # still in flight (half-written / about to be replaced by
                    # the newer snapshot): drain the async writer first. A
                    # FAILED in-flight write is logged and forfeited — the
                    # rollback falls back to the last durable snapshot.
                    self._drain_checkpoints(raise_errors=False)
                    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
                    if latest is None:
                        raise
                    try:
                        delay = tracker.record_failure(e)
                    except ResilienceError:
                        # budget exhausted / deadline passed: surface the
                        # ORIGINAL failure (reference semantics — callers see
                        # what actually broke, with the policy error chained)
                        raise e
                    _ROLLBACKS.inc()
                    logger.warning("step failed (%s); retry %d/%d from %s "
                                   "in %.2fs", e, tracker.attempts,
                                   cfg.retry_times, latest, delay)
                    if delay > 0:
                        (retry_policy.sleep or time.sleep)(delay)
                    restored, meta = ckpt.load_checkpoint(latest, self.train_state)
                    self.train_state = self._place_state(restored)
                    self.trainer_state.iteration = meta["iteration"]
                    self.trainer_state.epoch = meta["epoch"]
                    continue

                if validation_data is not None and validation_metrics:
                    results = self.evaluate(validation_data, batch_size=batch_size,
                                            metrics=validation_metrics)
                    # the FIRST metric is the primary score (max() would pick an
                    # error metric like mse when mixed with accuracies)
                    self.trainer_state.last_score = next(iter(results.values()))
                    if self.val_summary:
                        self.val_summary.add_scalars(self.trainer_state.iteration,
                                                     results)
                    logger.info("epoch %d validation: %s",
                                self.trainer_state.epoch, results)
            # training finished: block once on the async writer so fit()
            # returning implies the newest checkpoint is DURABLE (and a
            # failed write surfaces here instead of dying silently)
            self._drain_checkpoints()
        except _GracefulStop:
            # SIGTERM: persist one final checkpoint so the replacement run
            # resumes exactly here, then exit 143 (128+SIGTERM) — the
            # conventional graceful-termination status
            jax.block_until_ready(self.train_state)
            _SIGTERM_EXITS.inc()
            if cfg.checkpoint_dir:
                # durable save: the supervisor's replacement run must find
                # this final snapshot on disk the moment exit(143) is seen.
                # A previously failed async write must not abort it — exit
                # 143 with the freshest possible snapshot beats a traceback.
                self._save(cfg.checkpoint_dir, durable=True,
                           raise_drain_errors=False)
                logger.warning("SIGTERM: final checkpoint saved at iter %d; "
                               "exiting", self.trainer_state.iteration)
            raise SystemExit(143)
        finally:
            if handler_installed:
                signal.signal(signal.SIGTERM, prev_handler)
            # thread hygiene on ANY exit: an in-flight write never outlives
            # fit(). During an exceptional unwind errors are logged, not
            # raised (the original failure must not be masked); the normal
            # path already drained with raise_errors=True above.
            self._drain_checkpoints(raise_errors=False)
        # fit() returning means training FINISHED: epochs only dispatch work
        # (epoch-final losses stay lazy device scalars — one host transfer per
        # epoch would cost a full network RTT on remote-chip topologies), so
        # block once here. Otherwise a caller could observe fit() "done" while
        # this rank's collectives are still in flight — e.g. checkpointing or
        # exiting the process mid-psum, wedging every peer rank. A one-element
        # host transfer backs up block_until_ready because through the axon
        # tunnel the latter can return before the device is actually done
        # (same workaround as bench.py's _sync).
        jax.block_until_ready(self.train_state)
        leaves = jax.tree_util.tree_leaves(self.train_state)
        if leaves:
            try:
                jax.device_get(jnp.ravel(leaves[0])[:1])
            except TypeError:   # exotic non-indexable leaf: barrier above stands
                pass
        return self

    def _run_epoch(self, train_set: FeatureSet, batch_size: int,
                   checkpoint_trigger: Trigger):
        cfg = self.config
        if (cfg.cache_on_device
                and get_zoo_context().process_count == 1
                and train_set.memory_type == "DRAM"
                # byte-record tiers decode at batch time: raw object arrays
                # can't live in HBM
                and getattr(train_set, "decoder", None) is None):
            return self._run_epoch_cached(train_set, batch_size,
                                          checkpoint_trigger)
        ts = self.trainer_state
        epoch = ts.epoch
        t0 = time.perf_counter()
        seen = 0
        loss = None

        # async input pipeline: gather → decode → sharded device_put run on a
        # background producer feeding a bounded queue (depth =
        # config.prefetch_depth; 0 = synchronous in-line production),
        # so the host work of batch N+1 overlaps the device step on batch N.
        # Batch ORDER is byte-identical to the sync path per (seed, epoch).
        loader = PrefetchLoader(train_set, batch_size, epoch=epoch,
                                shuffle=self.config.shuffle,
                                put_fn=self._to_global,
                                depth=self.config.prefetch_depth)
        # per-step breakdown window: data-wait accumulates per batch; compute
        # is the window remainder, synced by the float(loss) transfer at each
        # log point so dispatched-but-unfinished device work can't hide
        it = iter(loader)
        win_t0 = t0
        win_steps = 0
        win_data_wait = 0.0
        epoch_data_wait = 0.0
        epoch_compile = 0.0
        try:
            while True:
                td = time.perf_counter()
                try:
                    global_batch = next(it)
                except StopIteration:
                    break
                dw = time.perf_counter() - td
                win_data_wait += dw
                epoch_data_wait += dw
                _DATA_WAIT.observe(dw)
                self._check_interrupt()
                chaos_point("estimator.step")
                key = self._batch_signature(global_batch)
                t_step = time.perf_counter()
                self.train_state, (loss, gnorm) = self._train_step(
                    self.train_state, global_batch)
                if key not in self._step_shapes:
                    # first dispatch of this shape = compile event: sync so
                    # its cost is attributed to compilation, not smeared over
                    # the window — which requires restarting the window clock
                    # here, and excluding the cost from the epoch epilogue's
                    # ComputeMs
                    jax.block_until_ready(loss)
                    self._note_step_signature(key)
                    _COMPILES.inc()
                    compile_s = time.perf_counter() - t_step
                    _COMPILE_TIME.observe(compile_s)
                    epoch_compile += compile_s
                    win_t0 += compile_s
                _STEPS.inc()
                win_steps += 1
                ts.iteration += 1
                seen += batch_size
                if ts.iteration % cfg.log_every_n_steps == 0:
                    loss_val = float(loss)
                    gnorm_val = float(gnorm)
                    _GRAD_NORM.observe(gnorm_val)
                    ts.last_loss = loss_val
                    now = time.perf_counter()
                    throughput = seen / max(now - t0, 1e-9)
                    data_ms = win_data_wait / win_steps * 1e3
                    compute_ms = max(0.0, (now - win_t0 - win_data_wait)
                                     / win_steps) * 1e3
                    _COMPUTE.observe(compute_ms / 1e3)
                    self._observe_comm()
                    _mw.sample("estimator.step")
                    if self.train_summary:
                        self.train_summary.add_scalars(ts.iteration, {
                            "Loss": loss_val, "Throughput": throughput,
                            "GradNorm": gnorm_val,
                            "DataWaitMs": data_ms, "ComputeMs": compute_ms})
                    logger.info("epoch %d iter %d loss %.4f gnorm %.3f "
                                "throughput %.1f rec/s (data %.2fms compute "
                                "%.2fms /step)",
                                epoch, ts.iteration, loss_val, gnorm_val,
                                throughput, data_ms, compute_ms)
                    # fresh clock: the comm probe (and its first-call
                    # compile) ran after `now` and must not be attributed to
                    # the NEXT window's ComputeMs
                    win_t0, win_steps, win_data_wait = (time.perf_counter(),
                                                        0, 0.0)
                if (checkpoint_trigger is not None and checkpoint_trigger(ts)
                        and cfg.checkpoint_dir):
                    self._save(cfg.checkpoint_dir)
        finally:
            # epoch end, step exception, or SIGTERM unwind: the producer
            # thread must not outlive the epoch
            loader.close()
        self._finish_epoch(t0, seen, loss, batch_size,
                           data_wait_s=epoch_data_wait,
                           compile_s=epoch_compile)

    def _finish_epoch(self, t0: float, seen: int, loss,
                      batch_size: Optional[int] = None,
                      data_wait_s: float = 0.0, compile_s: float = 0.0):
        """Epoch epilogue shared by both epoch runners: final-loss scalar,
        epoch/records bookkeeping, checkpoint save, summary flush."""
        cfg = self.config
        ts = self.trainer_state
        steps_this_epoch = max(1, seen // max(1, batch_size or cfg.batch_size))
        if loss is not None:
            # lazy: a 0-d device array; TrainerState materializes it on read.
            # Eagerly float()-ing here costs one full tunnel/network RTT per
            # epoch on remote-chip topologies — with device-cached scanned
            # epochs that RTT dominates the whole epoch wall time.
            ts.last_loss = loss
            # always record the epoch-final loss so short runs still get scalars
            if self.train_summary:
                dt = time.perf_counter() - t0
                self.train_summary.add_scalars(ts.iteration, {
                    "Loss": ts.last_loss, "Throughput": seen / max(dt, 1e-9),
                    "DataWaitMs": data_wait_s / steps_this_epoch * 1e3,
                    # compile cost is reported separately
                    # (zoo_train_compile_seconds), not smeared over steps
                    "ComputeMs": max(0.0, dt - data_wait_s - compile_s)
                    / steps_this_epoch * 1e3})
        ts.epoch += 1
        ts.records_processed += seen
        # epoch boundary = a guaranteed witness point even when the epoch is
        # shorter than log_every_n_steps (the tests' usual shape)
        _mw.sample("estimator.step")
        if cfg.checkpoint_dir:
            # epoch boundary = durability barrier: the save is synchronous
            # (and drains any in-flight mid-epoch write), so a hard kill in
            # epoch N+1 can never lose epoch N's completion
            self._save(cfg.checkpoint_dir, durable=True)
        if self.train_summary:
            self.train_summary.flush()

    def _run_epoch_cached(self, train_set: FeatureSet, batch_size: int,
                          checkpoint_trigger: Trigger):
        """Epoch with the dataset resident in HBM and steps fused into
        ``lax.scan`` blocks (TrainConfig.cache_on_device).

        Triggers/logging fire at block granularity (``scan_block_steps``);
        trailing steps that don't fill a block run through the per-batch path
        so no samples are dropped beyond the usual remainder.
        """
        cfg = self.config
        ts = self.trainer_state
        epoch = ts.epoch
        t0 = time.perf_counter()

        # key the HBM-resident copy on the array objects, not the FeatureSet —
        # fit() wraps raw (x, y) into a fresh FeatureSet every call, and
        # re-uploading ~the whole dataset each epoch would dominate runtime.
        # The key holds STRONG references so object identity can't be recycled
        # by the allocator after a gc (id() alone would alias new datasets).
        leaves = jax.tree_util.tree_leaves(train_set.data)
        cached = getattr(self, "_device_data_key", None)
        if (cached is None or len(cached) != len(leaves)
                or any(a is not b for a, b in zip(cached, leaves))):
            self._device_data = jax.device_put(train_set.data, self._replicated())
            self._device_data_key = leaves
        if getattr(self, "_scan_block", None) is None:
            self._scan_block = self._make_scan_block()
        if self._train_step is None:
            self._train_step = self._make_train_step()

        # epoch permutation computed ON device (jax.random.permutation) so no
        # index upload happens per epoch; deterministic in (seed, epoch)
        n_total = len(train_set)
        if cfg.shuffle:
            if getattr(self, "_perm_n", None) != n_total:
                self._perm_fn = jax.jit(
                    lambda seed: jax.random.permutation(
                        jax.random.PRNGKey(seed),
                        jnp.arange(n_total, dtype=jnp.int32)))
                self._perm_n = n_total
            idx = self._perm_fn(train_set.seed + epoch * 1_000_003)
        else:
            idx = jnp.arange(n_total, dtype=jnp.int32)
        n_steps = n_total // batch_size
        block = max(1, min(cfg.scan_block_steps, n_steps))
        n_blocks = n_steps // block
        seen = 0
        loss = None
        epoch_compile = 0.0
        win_t0, win_steps = t0, 0          # reset at each log point, like
        for b in range(n_blocks):          # the streaming path's window
            self._check_interrupt()
            chaos_point("estimator.step")
            sel = idx[b * block * batch_size:(b + 1) * block * batch_size]
            idx_mat = sel.reshape(block, batch_size)
            t_blk = time.perf_counter()
            self.train_state, (losses, gnorms) = self._scan_block(
                self.train_state, self._device_data, idx_mat)
            scan_key = tuple(idx_mat.shape)
            if scan_key not in self._scan_shapes:
                jax.block_until_ready(losses)
                self._scan_shapes.add(scan_key)
                _COMPILES.inc()
                compile_s = time.perf_counter() - t_blk
                _COMPILE_TIME.observe(compile_s)
                epoch_compile += compile_s
                win_t0 += compile_s     # keep compile out of ComputeMs
            loss = losses[-1]
            # device-cached epochs: data wait is ~0 by construction (the
            # dataset lives in HBM; batches are gathers inside the scan), so
            # the whole block window is compute
            _STEPS.inc(block)
            win_steps += block
            ts.iteration += block
            seen += block * batch_size
            if cfg.log_every_n_steps and (b + 1) * block >= cfg.log_every_n_steps \
                    and ((b + 1) * block) // cfg.log_every_n_steps \
                    > (b * block) // cfg.log_every_n_steps:
                loss_val = float(loss)          # device sync closes the window
                gnorm_val = float(gnorms[-1])
                _GRAD_NORM.observe(gnorm_val)
                ts.last_loss = loss_val
                now = time.perf_counter()
                throughput = seen / max(now - t0, 1e-9)
                compute_ms = (now - win_t0) / max(1, win_steps) * 1e3
                _COMPUTE.observe(compute_ms / 1e3)
                self._observe_comm()
                _mw.sample("estimator.step")
                if self.train_summary:
                    self.train_summary.add_scalars(ts.iteration, {
                        "Loss": loss_val, "Throughput": throughput,
                        "GradNorm": gnorm_val,
                        "DataWaitMs": 0.0, "ComputeMs": compute_ms})
                logger.info("epoch %d iter %d loss %.4f throughput %.1f rec/s",
                            epoch, ts.iteration, loss_val, throughput)
                # fresh clock: keep the comm probe out of the next window
                win_t0, win_steps = time.perf_counter(), 0
            if (checkpoint_trigger is not None and cfg.checkpoint_dir
                    and self._trigger_crossed(checkpoint_trigger, ts, block)):
                self._save(cfg.checkpoint_dir)
        # trailing steps (< one block): per-batch path, gathering on device
        for s in range(n_blocks * block, n_steps):
            self._check_interrupt()
            chaos_point("estimator.step")
            sel = idx[s * batch_size:(s + 1) * batch_size]
            db = jax.tree_util.tree_map(lambda a: jnp.take(a, sel, axis=0),
                                        self._device_data)
            key = self._batch_signature(db)
            t_step = time.perf_counter()
            self.train_state, (loss, _gn) = self._train_step(self.train_state,
                                                             db)
            if key not in self._step_shapes:
                jax.block_until_ready(loss)
                self._note_step_signature(key)
                _COMPILES.inc()
                compile_s = time.perf_counter() - t_step
                _COMPILE_TIME.observe(compile_s)
                epoch_compile += compile_s
            _STEPS.inc()
            ts.iteration += 1
            seen += batch_size
            if (checkpoint_trigger is not None and checkpoint_trigger(ts)
                    and cfg.checkpoint_dir):
                self._save(cfg.checkpoint_dir)
        self._finish_epoch(t0, seen, loss, batch_size,
                           compile_s=epoch_compile)

    def _run_graph_checks(self, sample_batch):
        """Trace the train step (``jax.make_jaxpr`` — no compile) and run the
        graph-layer lint rules against it per ``TrainConfig.graph_checks``.

        Expectations are derived from the config: the flat update-sharding
        path must show exactly one reduce-scatter + one all-gather per global
        step (and none inside the accumulation scan); a declared bf16 policy
        must actually reach the contraction ops; no host callbacks or large
        closure-captured constants may ride the step. The memory tier rides
        the same trace: the train state is rebound every step, so an
        un-donated state (``donate_state=False``) is ``donation-missed``; a
        declared ``hbm_budget_mb`` bounds the static live-range peak; and
        outsized temporaries warn (``peak-temporary``)."""
        from ..analysis import RuleContext, enforce, lint_jaxpr, profile_jaxpr
        from ..analysis.rules.memory import lint_memory

        expect = ({"reduce-scatter": 1, "all-gather": 1}
                  if self._update_mode() == "flat" else None)
        cfg = self.config
        n_state = len(jax.tree_util.tree_leaves(self.train_state))
        batch = self._to_global(sample_batch)
        n_batch = len(jax.tree_util.tree_leaves(batch))
        budget = (int(cfg.hbm_budget_mb * 2 ** 20)
                  if cfg.hbm_budget_mb else None)
        ctx = RuleContext(where="estimator.fit",
                          expect_collectives=expect,
                          compute_dtype=cfg.compute_dtype,
                          hbm_budget_bytes=budget,
                          donated_invars=[cfg.donate_state] * n_state
                          + [False] * n_batch,
                          dead_invars=[True] * n_state + [False] * n_batch)
        step = self._with_policy(self._step_fn())
        closed = jax.make_jaxpr(step)(self.train_state, batch)
        findings = lint_jaxpr(closed, ctx=ctx,
                              rules=["collective-budget", "host-transfer",
                                     "large-constant", "dtype-discipline"])
        findings += lint_memory(closed, ctx=ctx)
        if _mw.enabled():
            # the runtime witness cross-checks measured bytes against this
            prof = profile_jaxpr(closed, donated_invars=ctx.donated_invars)
            _mw.note_static("estimator.step", prof.peak_live_bytes, budget)
        enforce(findings, cfg.graph_checks, logger)

    def _note_step_signature(self, key) -> None:
        """Record a newly-compiled step signature: add it to ``_step_shapes``
        (the compile-event membership set) AND the recompilation-hazard
        tracker — one add-path so the two can't desynchronize. A train step
        re-tracing beyond a handful of distinct batch signatures is compiling
        mid-run (unbucketed ragged batches, drifting dtypes)."""
        self._step_shapes.add(key)
        if self._recompile_tracker is None:
            from ..analysis.graphlint import SignatureTracker

            self._recompile_tracker = SignatureTracker("estimator.step",
                                                       max_distinct=4)
        self._recompile_tracker.add(key)

    def _observe_comm(self):
        """Feed ``zoo_train_comm_seconds``: time one param-sized gradient-
        exchange round (psum, or reduce-scatter + all-gather under update
        sharding) on the dp axis. A measured probe at log-point cadence — the
        in-step collective is fused into the jitted program and cannot be
        timed from the host."""
        if self.mesh.shape.get("dp", 1) <= 1 or self.train_state is None:
            return
        if self._comm_probe_cache is None:
            n_elems = sum(
                int(np.prod(l.shape)) for l in
                jax.tree_util.tree_leaves(self.train_state["params"]))
            self._comm_probe_cache = upd.make_comm_probe(
                self.mesh, n_elems, sharded=self._update_mode() is not None)
        fn, vec = self._comm_probe_cache
        t0 = time.perf_counter()
        jax.block_until_ready(fn(vec))
        _COMM.observe(time.perf_counter() - t0)

    @staticmethod
    def _batch_signature(batch) -> Tuple:
        """Shape/dtype key of a dispatched batch — the thing jit re-traces
        on."""
        return tuple((tuple(l.shape), str(getattr(l, "dtype", type(l))))
                     for l in jax.tree_util.tree_leaves(batch))

    def _check_interrupt(self):
        """SIGTERM lands between device steps (a step is never torn mid-
        collective; peers on other ranks don't wedge mid-psum)."""
        if getattr(self, "_sigterm", False):
            raise _GracefulStop()

    @staticmethod
    def _trigger_crossed(trigger: Trigger, ts: TrainerState, block: int) -> bool:
        """Block-granular trigger test: when iteration jumps by ``block``, an
        interval trigger fires if any multiple of its interval was CROSSED in
        the block (exact modulo equality would almost never hold)."""
        if isinstance(trigger, SeveralIteration):
            return (ts.iteration // trigger.interval
                    > (ts.iteration - block) // trigger.interval)
        return trigger(ts)

    def _save(self, directory: str, durable: bool = False,
              raise_drain_errors: bool = True):
        """``durable=False`` (trigger-based mid-epoch saves — the hot-path
        cost async checkpointing removes): snapshot-then-write; this returns
        after the device→host snapshot and the serialization/fsync/rename run
        on the writer thread (at most one in flight — submit drains the
        previous write first). ``durable=True`` (epoch boundaries, SIGTERM
        finals): drain any in-flight write, then write synchronously — the
        caller's contract is "this state is on disk when I return", which a
        hard kill right after the save must not be able to violate.
        ``raise_drain_errors=False``: a previously FAILED async write is
        logged and forfeited instead of aborting this save (the SIGTERM
        path, where writing the final snapshot beats error propagation)."""
        if get_zoo_context().process_index == 0:
            _CHECKPOINTS.inc()
            writer = None
            if self.config.async_checkpoint and not durable:
                if self._ckpt_writer is None:
                    self._ckpt_writer = ckpt.CheckpointWriter()
                writer = self._ckpt_writer
            else:
                self._drain_checkpoints(raise_errors=raise_drain_errors)
            pub = getattr(self, "_model_publisher", None)
            ckpt.save_checkpoint(directory, self.train_state,
                                 iteration=self.trainer_state.iteration,
                                 epoch=self.trainer_state.epoch,
                                 writer=writer,
                                 on_durable=(pub.on_durable if pub is not None
                                             else None))

    def set_model_publisher(self, publisher) -> "Estimator":
        """Attach a :class:`~..serving.hotswap.ModelPublisher`: every durable
        checkpoint this estimator saves (async writer-thread AND synchronous
        epoch/SIGTERM saves) is announced on the serving fleet's publish
        stream — the trainer half of the continuous-deployment loop."""
        self._model_publisher = publisher
        return self

    def _drain_checkpoints(self, raise_errors: bool = True):
        """Block until the in-flight async checkpoint write (if any) is
        durable. With ``raise_errors=False`` a failed write is logged and
        forfeited (teardown/rollback paths that must not mask the original
        failure)."""
        w = self._ckpt_writer
        if w is None:
            return
        try:
            w.drain()
        except BaseException:
            if raise_errors:
                raise
            logger.exception("async checkpoint write failed; continuing "
                             "with the last durable snapshot")

    # ---------------------------------------------------------------- evaluate
    def evaluate(self, data, batch_size: int = 256,
                 metrics: Sequence = ("accuracy",)) -> Dict[str, float]:
        """Streaming metric evaluation under jit (Estimator.evaluate parity)."""
        eval_set = _as_featureset(data)
        if self.train_state is None:
            first = next(eval_set.batches(batch_size, shuffle=False,
                                          drop_remainder=False))
            self.train_state = self._init_state(first)
        metric_objs: List[Metric] = [get_metric(m) for m in metrics]
        # cache key includes each metric's full scalar config so e.g. AUC(100)
        # and AUC(200) don't collide on one compiled closure
        key = tuple(
            (type(m).__name__, m.name,
             tuple(sorted((k, v) for k, v in vars(m).items()
                          if isinstance(v, (int, float, str, bool)))))
            for m in metric_objs)
        if key not in self._eval_cache:
            model = self.model

            def eval_step(params, mstate, accs, batch):
                x, y = batch
                y_hat, _ = model.apply(params, mstate, x, training=False)
                return [m.update(a, y, y_hat) for m, a in zip(metric_objs, accs)]

            # the accumulator is rebound to the step's output every batch —
            # donating it keeps one accumulator buffer live instead of two
            # (the donation-missed rule's evaluate-jit class)
            self._eval_cache[key] = self._with_policy(
                jax.jit(eval_step, donate_argnums=(2,)))
        eval_step = self._eval_cache[key]
        accs = [m.init() for m in metric_objs]
        # same async loader as the train path: gather/decode + device upload
        # of batch N+1 overlap the eval step on batch N, and every host batch
        # is produced (and counted) through the one FeatureSet iterator
        loader = PrefetchLoader(eval_set, batch_size, epoch=0, shuffle=False,
                                drop_remainder=False, put_fn=self._to_global,
                                depth=self.config.prefetch_depth)
        try:
            for global_batch in loader:
                accs = eval_step(self.train_state["params"],
                                 self.train_state["model_state"],
                                 accs, global_batch)
        finally:
            loader.close()
        return {m.name: m.result(a) for m, a in zip(metric_objs, accs)}

    # ----------------------------------------------------------------- predict
    def predict(self, x, batch_size: int = 256) -> np.ndarray:
        model = self.model
        if not hasattr(self, "_predict_step"):
            self._predict_step = self._with_policy(jax.jit(
                lambda p, s, x: model.apply(p, s, x, training=False)[0]))
        data = (x,) if not isinstance(x, (tuple, list)) else tuple(x)
        fs = FeatureSet(data)
        if self.train_state is None:
            first = next(fs.batches(batch_size, shuffle=False, drop_remainder=False))
            xb = first[0] if len(first) == 1 else list(first)
            self.train_state = self._init_state((xb, None))
        outs = []
        # prefetch host-side production (gather/decode); the jit dispatch
        # handles the transfer, so put_fn stays None here
        loader = PrefetchLoader(fs, batch_size, epoch=0, shuffle=False,
                                drop_remainder=False,
                                depth=self.config.prefetch_depth)
        try:
            for host_batch in loader:
                xb = host_batch[0] if len(host_batch) == 1 else list(host_batch)
                y = self._predict_step(self.train_state["params"],
                                       self.train_state["model_state"], xb)
                outs.append(jax.device_get(y))
        finally:
            loader.close()
        if isinstance(outs[0], (tuple, list)):
            # multi-output model (functional Model with several outputs):
            # concatenate each output head across batches
            return [np.concatenate([np.asarray(o[i]) for o in outs], axis=0)
                    for i in range(len(outs[0]))]
        return np.concatenate([np.asarray(o) for o in outs], axis=0)

    # --------------------------------------------------- batchnorm recalibration
    def recalibrate_batchnorm(self, x, batch_size: int = 32, passes: int = 2,
                              momentum: float = 0.5):
        """Re-estimate BatchNorm moving statistics under the FINAL weights.

        During short trainings the 0.99-momentum EMA lags the fast-moving
        weights, so eval-mode (moving-stat) forward passes diverge from
        train-mode (batch-stat) ones. This runs forward-only passes over ``x``
        with a low-momentum override — the functional equivalent of
        ``torch.optim.swa_utils.update_bn`` — and keeps only the state.
        Dropout-family layers are silenced so the statistics match the
        serving-time distribution.
        """
        from ..nn.layers.normalization import BatchNormalization

        if self.train_state is None:
            return self
        bns = [l for l in _walk_layers(self.model)
               if isinstance(l, BatchNormalization)]
        if not bns:
            return self
        from ..nn.layers.advanced_activations import _SpatialDropout
        from ..nn.layers.core import Dropout, GaussianDropout, GaussianNoise

        # exact class match, NOT hasattr(l, "rate"): atrous convs store their
        # dilation in .rate and zeroing it would break the traced forward
        noisy = []
        for l in _walk_layers(self.model):
            if isinstance(l, (Dropout, GaussianDropout, _SpatialDropout)):
                noisy.append((l, "rate"))
            elif isinstance(l, GaussianNoise):
                noisy.append((l, "sigma"))
        saved = ([(l, "momentum", l.momentum) for l in bns]
                 + [(l, attr, getattr(l, attr)) for l, attr in noisy])
        for l in bns:
            l.momentum = float(momentum)
        for l, attr in noisy:
            setattr(l, attr, 0.0)
        try:
            model = self.model
            # fresh trace every call: momentum/rate are captured at trace time
            fwd = jax.jit(lambda p, s, xb: model.apply(
                p, s, xb, training=True, rng=jax.random.PRNGKey(0))[1])
            data = (x,) if not isinstance(x, (tuple, list)) else tuple(x)
            fs = x if isinstance(x, FeatureSet) else FeatureSet(data)
            # keep only the model's inputs: a labeled FeatureSet (or a fit-style
            # (x, y) tuple) carries targets as trailing components that must not
            # reach model.apply
            n_in = len(getattr(self.model, "input_nodes", ()) or ()) or 1
            mstate = self.train_state["model_state"]
            for _ in range(max(1, passes)):
                for hb in fs.batches(batch_size, shuffle=False,
                                     drop_remainder=False):
                    if isinstance(hb, dict):
                        # dict-tree batches (from_generator/from_xshards): only
                        # models whose apply takes the mapping whole can eat
                        # them — positional multi-input graphs cannot tell
                        # inputs from labels in an unordered mapping
                        if getattr(self.model, "input_nodes", None):
                            raise ValueError(
                                "recalibrate_batchnorm got a dict-tree batch "
                                "but the model takes positional graph inputs; "
                                "pass x as an array/tuple FeatureSet instead")
                        xb = hb
                    else:
                        hb = hb[:n_in]
                        xb = hb[0] if len(hb) == 1 else list(hb)
                    # donation is illegal here: the first iteration's mstate
                    # IS the live train_state["model_state"] — donating would
                    # delete the training state's buffers if a later batch
                    # raises before the reassignment below lands
                    # zoo-lint: disable=donation-missed
                    mstate = fwd(self.train_state["params"], mstate, xb)
            self.train_state["model_state"] = mstate
        finally:
            for l, attr, v in saved:
                setattr(l, attr, v)
        return self

    # ------------------------------------------------------------- summaries
    def set_tensorboard(self, log_dir: str, app_name: str):
        """Topology.scala:207-214 parity."""
        self.train_summary = TrainSummary(log_dir, app_name)
        self.val_summary = ValidationSummary(log_dir, app_name)
        return self

    # --------------------------------------------------------------- weights
    @property
    def params(self):
        return self.train_state["params"] if self.train_state else None

    def save(self, directory: str):
        assert self.train_state is not None
        # public save is SYNCHRONOUS: callers expect a durable file on
        # return; drain first so it can't interleave with an async write
        self._drain_checkpoints()
        return ckpt.save_checkpoint(directory, self.train_state,
                                    iteration=self.trainer_state.iteration,
                                    epoch=self.trainer_state.epoch)

    def load(self, directory: str, sample_batch=None):
        self._drain_checkpoints()
        if self.train_state is None:
            assert sample_batch is not None, "need sample_batch to build state"
            self.train_state = self._init_state(sample_batch)
        path = ckpt.latest_checkpoint(directory) or directory
        restored, meta = ckpt.load_checkpoint(path, self.train_state)
        self.train_state = self._place_state(restored)
        self.trainer_state.iteration = meta["iteration"]
        self.trainer_state.epoch = meta["epoch"]
        return self
