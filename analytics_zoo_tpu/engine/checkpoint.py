"""Checkpoint / resume, with off-hot-path (snapshot-then-write) saving.

Parity: the reference snapshots model + per-submodule OptimMethod into timestamped
dirs at epoch/iteration triggers (KerasNet.setCheckpoint Topology.scala:248-258,
setCheckpointDir :1295-1308, recovery file selection getLatestFile :1522-1539), and
the retry loop reloads the latest pair on failure (Topology.scala:1181-1263).

Format: one ``checkpoint_<iteration>`` directory per snapshot holding
``state.npz`` (flat leaves) + ``meta.json`` (treedef + loop counters). Pure
numpy — no framework dependency — and layout-stable for multi-host: every host
saves only on process 0 unless ``all_hosts`` (sharded leaves land via
``jax.experimental.multihost_utils`` in later rounds).

Async mode (:class:`CheckpointWriter`): the training loop pays ONLY the
device→host snapshot (``zoo_train_checkpoint_snapshot_seconds``); the
serialization + fsync + atomic rename run on an at-most-one-in-flight
``zoo-ckpt-write`` thread (``zoo_train_checkpoint_write_seconds``).  Writes
publish by atomic rename of a ``*.tmp`` staging dir, and ``latest_checkpoint``
only matches completed ``checkpoint_<n>`` names — so a kill mid-write can
never surface a half-written snapshot; the most recent DURABLE checkpoint
always wins.  Callers that must observe a durable state (fit() exit, the
SIGTERM path, rollback-retry restores) drain the writer first.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..common import telemetry as _tm
from ..common.chaos import chaos_point

_CKPT_RE = re.compile(r"^checkpoint_(\d+)$")

_SNAPSHOT_TIME = _tm.histogram(
    "zoo_train_checkpoint_snapshot_seconds",
    "Device→host state-snapshot time — the only checkpoint cost the hot "
    "loop pays in async mode")
_WRITE_TIME = _tm.histogram(
    "zoo_train_checkpoint_write_seconds",
    "Checkpoint serialization + fsync + atomic-rename time (background "
    "zoo-ckpt-write thread in async mode)",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30))


def _flatten_with_paths(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def snapshot_state(state: Any) -> List[np.ndarray]:
    """Materialize every leaf as an independent HOST copy.

    Independence matters for async saves: the train loop donates/overwrites
    its state buffers on the very next step, so the writer thread must never
    alias them. ``device_get`` already copies device arrays; host-numpy
    leaves (which it passes through) are copied explicitly.
    """
    t0 = time.perf_counter()
    leaves, _ = _flatten_with_paths(state)
    host: List[np.ndarray] = []
    for l in leaves:
        h = np.asarray(jax.device_get(l))
        # force a true copy whenever the result aliases anything: device_get
        # passes host-numpy leaves through (h is l), and on the CPU backend
        # it returns a ZERO-COPY view of the live XLA buffer (h.base is a
        # PyCapsule) — which the next donated step would overwrite under the
        # writer thread
        if h is l or h.base is not None or not h.flags["OWNDATA"]:
            h = h.copy()
        host.append(h)
    _SNAPSHOT_TIME.observe(time.perf_counter() - t0)
    return host


def _fsync(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # e.g. directories on filesystems that don't support it
        pass
    finally:
        os.close(fd)


def _write_snapshot(directory: str, host_leaves: List[np.ndarray],
                    meta: Dict, keep: int) -> str:
    """Durable publication: stage under ``*.tmp``, fsync, atomic rename."""
    path = os.path.join(directory, f"checkpoint_{meta['iteration']}")
    tmp = path + ".tmp"
    t0 = time.perf_counter()
    try:
        os.makedirs(tmp, exist_ok=True)
        np.savez(os.path.join(tmp, "state.npz"),
                 **{f"leaf_{i}": l for i, l in enumerate(host_leaves)})
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync(os.path.join(tmp, "state.npz"))
        # deterministic kill site BETWEEN serialization and publication: the
        # chaos drill killing a writer here must leave only complete,
        # durable checkpoints discoverable
        chaos_point("ckpt.write")
        # the staging dir's own entries must be durable BEFORE the rename
        # publishes it, or a crash could surface checkpoint_<n> with a
        # missing/truncated state.npz
        _fsync(tmp)
        # re-saving an existing iteration (rollback re-runs, epoch-boundary
        # overwrite of a trigger save): move the old durable dir ASIDE
        # instead of deleting it, so no kill window exists in which neither
        # version is recoverable; .old never matches latest_checkpoint
        old = None
        if os.path.exists(path):
            old = path + ".old"
            shutil.rmtree(old, ignore_errors=True)
            os.rename(path, old)
        os.rename(tmp, path)
        _fsync(directory)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:    # incl. chaos WorkerKilled: never leave a .tmp
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    finally:
        _WRITE_TIME.observe(time.perf_counter() - t0)
    _gc(directory, keep)
    return path


def save_checkpoint(directory: str, state: Any, *, iteration: int, epoch: int,
                    extra: Optional[Dict] = None, keep: int = 5,
                    writer: Optional["CheckpointWriter"] = None) -> str:
    """Snapshot ``state`` (any pytree of arrays) under ``directory``.

    With ``writer`` the call returns after the device→host snapshot; the
    write itself happens on the writer's background thread (drain the writer
    before depending on the file). Without it, the write is synchronous.
    """
    os.makedirs(directory, exist_ok=True)
    host_leaves = snapshot_state(state)
    meta = {
        "iteration": iteration,
        "epoch": epoch,
        "time": time.time(),
        "n_leaves": len(host_leaves),
        "extra": extra or {},
    }
    if writer is not None:
        return writer.submit(directory, host_leaves, meta, keep)
    return _write_snapshot(directory, host_leaves, meta, keep)


class CheckpointWriter:
    """At-most-one-in-flight background checkpoint writer.

    ``submit`` first drains the previous write (re-raising its failure — a
    lost checkpoint must not stay silent), then hands the already-snapshotted
    host leaves to a fresh daemon ``zoo-ckpt-write`` thread. ``drain`` blocks
    until the in-flight write is durable. Not a thread pool on purpose: one
    writer at a time means two saves can never interleave on the same
    directory, and the newest snapshot is always the last published.
    """

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self._path: Optional[str] = None

    def submit(self, directory: str, host_leaves: List[np.ndarray],
               meta: Dict, keep: int) -> str:
        self.drain()

        def run():
            try:
                self._path = _write_snapshot(directory, host_leaves, meta, keep)
            except BaseException as e:   # surfaced at the next drain/submit
                self._exc = e

        self._thread = threading.Thread(target=run, name="zoo-ckpt-write",
                                        daemon=True)
        self._thread.start()
        return os.path.join(directory, f"checkpoint_{meta['iteration']}")

    def in_flight(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def drain(self) -> Optional[str]:
        """Block until pending work is durable; re-raise a failed write."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._exc is not None:
            e, self._exc = self._exc, None
            raise e
        return self._path

    close = drain


def _gc(directory: str, keep: int) -> None:
    names = os.listdir(directory)
    ckpts = sorted(
        (int(m.group(1)), name) for name in names
        if (m := _CKPT_RE.match(name)))
    for _, name in ckpts[:-keep]:
        shutil.rmtree(os.path.join(directory, name), ignore_errors=True)
    for name in names:        # .old dirs stranded by a crash mid-replace
        if name.endswith(".old") and _CKPT_RE.match(name[:-4]):
            shutil.rmtree(os.path.join(directory, name), ignore_errors=True)


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest COMPLETE snapshot path (getLatestFile parity,
    Topology.scala:1522-1539). ``*.tmp`` staging dirs never match."""
    if not os.path.isdir(directory):
        return None
    best = None
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            it = int(m.group(1))
            if best is None or it > best[0]:
                best = (it, os.path.join(directory, name))
    return best[1] if best else None


def load_checkpoint(path: str, state_template: Any) -> Tuple[Any, Dict]:
    """Restore a snapshot into the structure of ``state_template``."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    data = np.load(os.path.join(path, "state.npz"))
    leaves, treedef = _flatten_with_paths(state_template)
    if meta["n_leaves"] != len(leaves):
        raise ValueError(
            f"checkpoint has {meta['n_leaves']} leaves, template has {len(leaves)}")
    def restore(raw: np.ndarray, like) -> np.ndarray:
        # npz has no representation for ml_dtypes customs (bfloat16, fp8):
        # they round-trip as raw void bytes ("|V2"); the template knows the
        # real dtype, and itemsize is preserved, so a view recovers it
        want = np.dtype(getattr(like, "dtype", raw.dtype))
        if raw.dtype != want and raw.dtype.kind == "V" \
                and raw.dtype.itemsize == want.itemsize:
            return raw.view(want)
        return raw

    new_leaves = [restore(data[f"leaf_{i}"], leaves[i])
                  for i in range(len(leaves))]
    restored = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return restored, meta
