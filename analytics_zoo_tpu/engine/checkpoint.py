"""Checkpoint / resume.

Parity: the reference snapshots model + per-submodule OptimMethod into timestamped
dirs at epoch/iteration triggers (KerasNet.setCheckpoint Topology.scala:248-258,
setCheckpointDir :1295-1308, recovery file selection getLatestFile :1522-1539), and
the retry loop reloads the latest pair on failure (Topology.scala:1181-1263).

Format: one ``checkpoint_<iteration>`` directory per snapshot holding
``state.npz`` (flat leaves) + ``meta.json`` (treedef + loop counters). Pure
numpy — no framework dependency — and layout-stable for multi-host: every host
saves only on process 0 unless ``all_hosts`` (sharded leaves land via
``jax.experimental.multihost_utils`` in later rounds).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

_CKPT_RE = re.compile(r"^checkpoint_(\d+)$")


def _flatten_with_paths(tree):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def save_checkpoint(directory: str, state: Any, *, iteration: int, epoch: int,
                    extra: Optional[Dict] = None, keep: int = 5) -> str:
    """Snapshot ``state`` (any pytree of arrays) under ``directory``."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"checkpoint_{iteration}")
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    leaves, treedef = _flatten_with_paths(state)
    host_leaves = [np.asarray(jax.device_get(l)) for l in leaves]
    np.savez(os.path.join(tmp, "state.npz"),
             **{f"leaf_{i}": l for i, l in enumerate(host_leaves)})
    meta = {
        "iteration": iteration,
        "epoch": epoch,
        "time": time.time(),
        "n_leaves": len(host_leaves),
        "extra": extra or {},
    }
    with open(os.path.join(tmp, "meta.json"), "w") as f:
        json.dump(meta, f)
    if os.path.exists(path):
        shutil.rmtree(path)
    os.rename(tmp, path)
    _gc(directory, keep)
    return path


def _gc(directory: str, keep: int) -> None:
    ckpts = sorted(
        (int(m.group(1)), name) for name in os.listdir(directory)
        if (m := _CKPT_RE.match(name)))
    for _, name in ckpts[:-keep]:
        shutil.rmtree(os.path.join(directory, name), ignore_errors=True)


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest snapshot path (getLatestFile parity, Topology.scala:1522-1539)."""
    if not os.path.isdir(directory):
        return None
    best = None
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            it = int(m.group(1))
            if best is None or it > best[0]:
                best = (it, os.path.join(directory, name))
    return best[1] if best else None


def load_checkpoint(path: str, state_template: Any) -> Tuple[Any, Dict]:
    """Restore a snapshot into the structure of ``state_template``."""
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    data = np.load(os.path.join(path, "state.npz"))
    leaves, treedef = _flatten_with_paths(state_template)
    if meta["n_leaves"] != len(leaves):
        raise ValueError(
            f"checkpoint has {meta['n_leaves']} leaves, template has {len(leaves)}")
    new_leaves = [data[f"leaf_{i}"] for i in range(len(leaves))]
    restored = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return restored, meta
